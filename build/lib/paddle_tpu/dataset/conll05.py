"""CoNLL-2005 SRL: real column-format parsing with synthetic fallback.

reference: python/paddle/v2/dataset/conll05.py — the corpus is a pair
of gzipped column files (words: one token per line, blank line ends a
sentence; props: predicate lemma + one bracket-tag column per
predicate).  Bracket tags like '(A0*', '*', '*)' convert to BIO; each
(sentence, predicate) pair yields the 8 feature sequences + label
sequence the SRL model consumes.
"""

import gzip
import os

from .common import fetch_or_none, rng

__all__ = ["get_dict", "get_embedding", "test", "parse_corpus",
           "reader_creator", "load_dict"]

DATA_URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
            "conll05st-tests.tar.gz")
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
                "wordDict.txt")
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
                "verbDict.txt")
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = ("http://paddlemodels.bj.bcebos.com/conll05st/"
               "targetDict.txt")
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"

UNK_IDX = 0

_SYNTH_WORDS = 4000
_SYNTH_PREDS = 300
_SYNTH_LABELS = 59


def _open_text(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rt", encoding="utf-8")
    return open(path, "r", encoding="utf-8")


def _brackets_to_bio(tags):
    """One predicate's bracket column -> BIO labels (reference
    conll05.py corpus_reader inner loop: '(A0*' opens, '*)' closes,
    bare '*' continues inside a span or emits O outside one)."""
    bio = []
    current = "O"
    inside = False
    for t in tags:
        if t == "*":
            bio.append("I-" + current if inside else "O")
        elif t == "*)":
            bio.append("I-" + current)
            inside = False
        elif "(" in t:
            current = t[1:t.index("*")]
            bio.append("B-" + current)
            inside = ")" not in t
        else:
            raise ValueError("unexpected conll05 tag %r" % t)
    return bio


def parse_corpus(words_path, props_path):
    """Yield (words, predicate, bio_labels) per (sentence, predicate)."""

    def emit(words, prop_rows):
        predicates = [r[0] for r in prop_rows if r[0] != "-"]
        n_preds = len(prop_rows[0]) - 1
        for k in range(n_preds):
            tags = [r[k + 1] for r in prop_rows]
            yield list(words), predicates[k], _brackets_to_bio(tags)

    def corpus():
        from itertools import zip_longest

        with _open_text(words_path) as wf, _open_text(props_path) as pf:
            words, prop_rows = [], []
            for wline, pline in zip_longest(wf, pf):
                if wline is None or pline is None:
                    raise ValueError(
                        "conll05: words/props files have different "
                        "lengths (%s vs %s)" % (words_path, props_path))
                word = wline.strip()
                cols = pline.strip().split()
                if cols:
                    words.append(word)
                    prop_rows.append(cols)
                    continue
                if prop_rows:  # blank line ends a sentence
                    yield from emit(words, prop_rows)
                words, prop_rows = [], []
            if prop_rows:  # no trailing blank line after last sentence
                yield from emit(words, prop_rows)

    return corpus


def reader_creator(corpus_reader, word_dict, verb_dict, label_dict):
    """The 9-slot SRL sample (reference conll05.py reader_creator):
    words, 5 predicate-context features, predicate, mark, labels."""

    def context(words, i, fallback):
        return words[i] if 0 <= i < len(words) else fallback

    def reader():
        for words, predicate, labels in corpus_reader():
            n = len(words)
            v = labels.index("B-V")
            # the reference marks the 5-token window around the verb
            mark = [0] * n
            for off in (-2, -1, 0, 1, 2):
                if 0 <= v + off < n:
                    mark[v + off] = 1

            def ids(tokens):
                return [word_dict.get(t, UNK_IDX) for t in tokens]

            ctx = {off: context(words, v + off,
                                "bos" if off < 0 else "eos")
                   for off in (-2, -1, 0, 1, 2)}
            yield (ids(words),
                   [word_dict.get(ctx[-2], UNK_IDX)] * n,
                   [word_dict.get(ctx[-1], UNK_IDX)] * n,
                   [word_dict.get(ctx[0], UNK_IDX)] * n,
                   [word_dict.get(ctx[1], UNK_IDX)] * n,
                   [word_dict.get(ctx[2], UNK_IDX)] * n,
                   [verb_dict.get(predicate, UNK_IDX)] * n,
                   mark,
                   [label_dict[l] for l in labels])

    return reader


def load_dict(path):
    """One entry per line -> {entry: line_no}."""
    with _open_text(path) as f:
        return {line.strip(): i for i, line in enumerate(f)}


def _synthetic_dicts():
    word_dict = {("w%d" % i): i for i in range(_SYNTH_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_SYNTH_PREDS)}
    label_dict = {("l%d" % i): i for i in range(_SYNTH_LABELS)}
    return word_dict, verb_dict, label_dict


def _real_dicts_or_none():
    """(word, verb, label) dicts from the official files, or None."""
    paths = [fetch_or_none(u, "conll05st", m) for u, m in
             ((WORDDICT_URL, WORDDICT_MD5), (VERBDICT_URL, VERBDICT_MD5),
              (TRGDICT_URL, TRGDICT_MD5))]
    if all(p and os.path.exists(p) for p in paths):
        return tuple(load_dict(p) for p in paths)
    return None


def get_dict():
    return _real_dicts_or_none() or _synthetic_dicts()


def build_dicts_from_corpus(corpus_reader):
    """Derive (word, verb, label) dicts from a corpus — the offline
    analog of the reference's downloaded wordDict/verbDict/targetDict
    for user-supplied column files."""
    words, verbs, labels = set(), set(), set()
    for sent, verb, bio in corpus_reader():
        words.update(sent)
        verbs.add(verb)
        labels.update(bio)
    words |= {"bos", "eos"}
    return ({w: i for i, w in enumerate(sorted(words))},
            {v: i for i, v in enumerate(sorted(verbs))},
            {l: i for i, l in enumerate(sorted(labels))})


def get_embedding(word_dict=None, dim=32):
    """Random embedding sized to the dict (the reference downloads a
    trained Wikipedia table; offline a deterministic random one with
    the right row count keeps models shape-correct)."""
    rows = len(word_dict) if word_dict is not None else _SYNTH_WORDS
    return rng(33).uniform(-1, 1, size=(rows, dim)).astype("float32")


def _synthetic_reader(n, seed):
    r = rng(seed)

    def reader():
        for _ in range(n):
            length = int(r.randint(5, 35))
            word = r.randint(0, _SYNTH_WORDS, size=length).tolist()
            pred_idx = int(r.randint(0, length))
            predicate = [int(r.randint(0, _SYNTH_PREDS))] * length
            ctx_n2 = word[max(0, pred_idx - 2):][:1] * length
            ctx_n1 = word[max(0, pred_idx - 1):][:1] * length
            ctx_0 = [word[pred_idx]] * length
            ctx_p1 = word[min(length - 1, pred_idx + 1):][:1] * length
            ctx_p2 = word[min(length - 1, pred_idx + 2):][:1] * length
            mark = [1 if i == pred_idx else 0 for i in range(length)]
            label = r.randint(0, _SYNTH_LABELS, size=length).tolist()
            yield (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
                   predicate, mark, label)

    return reader


def _extracted_corpus_paths():
    """Download + extract the official test tarball when allowed;
    returns (words_path, props_path) or None."""
    tar_path = fetch_or_none(DATA_URL, "conll05st", DATA_MD5)
    if not tar_path or not os.path.exists(tar_path):
        return None
    import tarfile

    root = os.path.dirname(tar_path)
    words = os.path.join(root, "conll05st-release/test.wsj/words/"
                               "test.wsj.words.gz")
    props = os.path.join(root, "conll05st-release/test.wsj/props/"
                               "test.wsj.props.gz")
    if not (os.path.exists(words) and os.path.exists(props)):
        with tarfile.open(tar_path) as tf:
            try:
                tf.extractall(root, filter="data")  # no ../ escapes
            except TypeError:  # filter= requires python >= 3.11.4
                tf.extractall(root)
    if os.path.exists(words) and os.path.exists(props):
        return words, props
    return None


def test(words_path=None, props_path=None, dicts=None):
    """Real column files (explicit paths, or the downloaded official
    tarball when PADDLE_TPU_ALLOW_DOWNLOAD=1); synthetic otherwise.
    Without `dicts`, dictionaries come from the downloaded dict files
    or are derived from the corpus itself."""
    explicit = words_path is not None or props_path is not None
    if explicit:
        for p in (words_path, props_path):
            if not p or not os.path.exists(p):
                raise FileNotFoundError(
                    "conll05: explicit corpus path %r does not exist"
                    % (p,))
    else:
        found = _extracted_corpus_paths()
        if found:
            words_path, props_path = found
    if words_path and props_path:
        corpus = parse_corpus(words_path, props_path)
        if dicts is None:
            # never pair a real corpus with the synthetic dict fallback
            # (its keys aren't BIO tags -> KeyError mid-read).  Prefer
            # the official dicts — ids then agree with models trained
            # against get_dict() — but only when they actually cover
            # this corpus's labels; otherwise derive from the corpus.
            derived = build_dicts_from_corpus(corpus)
            official = _real_dicts_or_none()
            if official is not None and \
                    set(derived[2]) <= set(official[2]):
                dicts = official
            else:
                dicts = derived
        word_dict, verb_dict, label_dict = dicts
        return reader_creator(corpus, word_dict, verb_dict, label_dict)
    return _synthetic_reader(256, 44)
