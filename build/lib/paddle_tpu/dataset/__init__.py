"""Datasets with the reference's reader API.

reference: python/paddle/v2/dataset/ (mnist, cifar, imdb, uci_housing,
imikolov, movielens, conll05, sentiment, wmt14/16...).

mnist (idx), cifar (pickled-batch tar), imdb (aclImdb tar) and conll05
(column files) carry REAL parsers: they download into
`~/.cache/paddle_tpu/dataset/` when the network allows (md5-checked,
common.py) and accept explicit file paths.  When neither is available
(this build is zero-egress) every dataset falls back to a
*deterministic synthetic stand-in* with the exact shapes, dtypes and
reader API of the original — enough for training-loop,
convergence-trend and benchmark tests.  Network fetches are opt-in:
set PADDLE_TPU_ALLOW_DOWNLOAD=1 to download."""

from . import uci_housing  # noqa: F401
from . import mnist        # noqa: F401
from . import cifar        # noqa: F401
from . import imdb         # noqa: F401
from . import imikolov     # noqa: F401
from . import movielens    # noqa: F401
from . import conll05      # noqa: F401
from . import wmt14        # noqa: F401
from . import wmt16        # noqa: F401
from . import sentiment    # noqa: F401
from . import mq2007       # noqa: F401
from . import flowers      # noqa: F401
from . import voc2012      # noqa: F401

__all__ = ["uci_housing", "mnist", "cifar", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "wmt16", "sentiment",
           "mq2007", "flowers", "voc2012"]
