"""WMT16 translation stand-in (reference: python/paddle/v2/dataset/
wmt16.py — same (src, trg_in, trg_next) triples as wmt14 with a
configurable vocab)."""

from . import wmt14

__all__ = ["train", "test", "get_dict"]


def get_dict(lang, dict_size):
    return {("%s%d" % (lang, i)): i for i in range(dict_size)}


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return wmt14._reader(1024, min(src_dict_size, trg_dict_size), 61)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return wmt14._reader(128, min(src_dict_size, trg_dict_size), 62)
