"""IMDB sentiment: real aclImdb tarball tokenization with synthetic
fallback.

reference: python/paddle/v2/dataset/imdb.py — tokenize() strips
punctuation and lowercases each review in the tar, build_dict()
frequency-ranks words above a cutoff (ties broken alphabetically,
'<unk>' appended last), readers yield (word-id list, 0=pos / 1=neg).
"""

import os
import re
import string
import tarfile
from collections import Counter

from .common import fetch_or_none, synthetic_sequences

__all__ = ["train", "test", "word_dict", "tokenize", "build_dict"]

URL = "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

TRAIN_POS_PATTERN = re.compile(r"aclImdb/train/pos/.*\.txt$")
TRAIN_NEG_PATTERN = re.compile(r"aclImdb/train/neg/.*\.txt$")
TEST_POS_PATTERN = re.compile(r"aclImdb/test/pos/.*\.txt$")
TEST_NEG_PATTERN = re.compile(r"aclImdb/test/neg/.*\.txt$")

_PUNCT_TABLE = str.maketrans("", "", string.punctuation)

_SYNTH_VOCAB = 5147
_SYNTH_TRAIN_N = 512
_SYNTH_TEST_N = 128


def tokenize(tar_path, name_pattern):
    """Yield one token list per tar member matching `name_pattern`."""
    with tarfile.open(tar_path) as tf:
        # sequential walk (tf is its own iterator) — random-access
        # extractfile per member would thrash the archive
        for member in tf:
            if not name_pattern.match(member.name):
                continue
            text = tf.extractfile(member).read().decode(
                "utf-8", errors="ignore")
            yield text.rstrip("\n\r").translate(_PUNCT_TABLE) \
                .lower().split()


def build_dict(tar_path, name_pattern, cutoff=1):
    """Frequency-ranked word ids over matching members; words at or
    below `cutoff` occurrences are dropped; '<unk>' gets the last id."""
    freq = Counter()
    for doc in tokenize(tar_path, name_pattern):
        freq.update(doc)
    kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                  key=lambda wc: (-wc[1], wc[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _tar_reader(tar_path, pos_pattern, neg_pattern, word_idx):
    unk = word_idx["<unk>"]

    def reader():
        for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
            for doc in tokenize(tar_path, pattern):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def _synthetic_reader(n, seed):
    data = synthetic_sequences(n, _SYNTH_VOCAB, 2, seed, min_len=8,
                               max_len=60)

    def reader():
        for seq, label in data:
            yield seq, label

    return reader


def _tar_or_none(tar_path):
    if tar_path is not None:
        if not os.path.exists(tar_path):
            raise FileNotFoundError("imdb: %r does not exist" % tar_path)
        return tar_path
    tar_path = fetch_or_none(URL, "imdb", MD5)
    if tar_path and os.path.exists(tar_path):
        return tar_path
    return None


# full-corpus dict builds are a sequential scan of the whole tarball;
# memoize per (path, mtime) so train()+test() share one scan
_dict_cache = {}


def word_dict(tar_path=None, cutoff=150):
    """reference: imdb.py word_dict() — dict over the whole corpus."""
    tar_path = _tar_or_none(tar_path)
    if tar_path:
        key = (tar_path, os.path.getmtime(tar_path), cutoff)
        if key not in _dict_cache:
            _dict_cache[key] = build_dict(
                tar_path, re.compile(r"aclImdb/((train)|(test))/"
                                     r"((pos)|(neg))/.*\.txt$"), cutoff)
        return _dict_cache[key]
    return {("w%d" % i): i for i in range(_SYNTH_VOCAB)}


def train(word_idx=None, tar_path=None):
    tar_path = _tar_or_none(tar_path)
    if tar_path:
        if word_idx is None:
            word_idx = word_dict(tar_path)
        return _tar_reader(tar_path, TRAIN_POS_PATTERN,
                           TRAIN_NEG_PATTERN, word_idx)
    return _synthetic_reader(_SYNTH_TRAIN_N, 7)


def test(word_idx=None, tar_path=None):
    tar_path = _tar_or_none(tar_path)
    if tar_path:
        if word_idx is None:
            word_idx = word_dict(tar_path)
        return _tar_reader(tar_path, TEST_POS_PATTERN,
                           TEST_NEG_PATTERN, word_idx)
    return _synthetic_reader(_SYNTH_TEST_N, 8)
