"""Data provider for the paddle_trainer-style CLI configs
(reference: the @provider-decorated dataprovider modules that
define_py_data_sources2 points at; convention documented in
trainer_config_helpers/config.py)."""

from . import uci_housing

__all__ = ["provide"]


def provide(file_list, **kwargs):
    """file_list "train" or "test" selects the split; returns a reader
    yielding (features[13], [price]) rows."""
    return uci_housing.test() if file_list == "test" \
        else uci_housing.train()
