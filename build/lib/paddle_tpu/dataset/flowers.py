"""Oxford-102 flowers stand-in (reference: python/paddle/v2/dataset/
flowers.py — 3x224x224 float images, 102 classes)."""

from .common import rng

__all__ = ["train", "test", "valid"]

_CLASSES = 102


def _reader(n, seed, size=224):
    r = rng(seed)

    def reader():
        for _ in range(n):
            label = int(r.randint(0, _CLASSES))
            im = r.rand(3, size, size).astype("float32")
            im[0] += label / float(_CLASSES)  # learnable signal
            yield im, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(256, 91)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(64, 92)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(64, 93)
