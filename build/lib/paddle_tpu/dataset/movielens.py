"""MovieLens stand-in (reference: python/paddle/v2/dataset/movielens.py —
(user, gender, age, job, movie, category-seq, title-seq, score))."""

from .common import rng

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories"]

_USERS = 943
_MOVIES = 1682
_JOBS = 20
_CATS = 18
_TITLE_VOCAB = 1512
age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _USERS


def max_movie_id():
    return _MOVIES


def max_job_id():
    return _JOBS


def movie_categories():
    return {("cat%d" % i): i for i in range(_CATS)}


def _reader(n, seed):
    r = rng(seed)

    def reader():
        for _ in range(n):
            uid = int(r.randint(1, _USERS + 1))
            gender = int(r.randint(0, 2))
            age = int(r.randint(0, len(age_table)))
            job = int(r.randint(0, _JOBS))
            mid = int(r.randint(1, _MOVIES + 1))
            cats = r.randint(0, _CATS,
                             size=int(r.randint(1, 4))).tolist()
            title = r.randint(0, _TITLE_VOCAB,
                              size=int(r.randint(2, 8))).tolist()
            # score correlates with (uid+mid) parity-ish signal
            score = float(((uid * 7 + mid * 13) % 50) / 10.0)
            yield uid, gender, age, job, mid, cats, title, score

    return reader


def train():
    return _reader(4096, 21)


def test():
    return _reader(512, 22)
