"""CIFAR-10/100: real pickled-batch tarball parsing with synthetic
fallback.

reference: python/paddle/v2/dataset/cifar.py reader_creator — walk the
tar members whose name contains the split marker, unpickle each batch
dict, yield (pixels/255 as float32 [3072], int label); CIFAR-100 labels
come from 'fine_labels'.
"""

import os
import pickle
import tarfile

import numpy as np

from .common import fetch_or_none, synthetic_images

__all__ = ["train10", "test10", "train100", "test100",
           "reader_creator"]

URL_PREFIX = "https://www.cs.toronto.edu/~kriz/"
CIFAR10_URL = URL_PREFIX + "cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
CIFAR100_URL = URL_PREFIX + "cifar-100-python.tar.gz"
CIFAR100_MD5 = "eb9058c3a382ffc7106e4002c42a8d85"

_SYNTH_TRAIN_N = 1024
_SYNTH_TEST_N = 256


def _batch_samples(batch):
    data = batch[b"data"] if b"data" in batch else batch["data"]
    labels = None
    for key in (b"labels", "labels", b"fine_labels", "fine_labels"):
        if key in batch:
            labels = batch[key]
            break
    if labels is None:
        raise ValueError("cifar batch has no labels/fine_labels")
    data = np.asarray(data, np.float32) / 255.0
    for row, label in zip(data, labels):
        yield row, int(label)


def reader_creator(tar_path, split_marker):
    """Yield samples from every member whose name contains
    `split_marker` ('data_batch'/'test_batch' for CIFAR-10,
    'train'/'test' for CIFAR-100)."""

    def reader():
        with tarfile.open(tar_path, mode="r") as tf:
            for member in tf:
                if split_marker not in member.name or member.isdir():
                    continue
                batch = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                yield from _batch_samples(batch)

    return reader


def _synthetic_reader(n, classes, seed):
    imgs, labels = synthetic_images(n, (3072,), classes, seed)

    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def _make(url, md5, marker, classes, synth_n, seed, tar_path=None):
    if tar_path is not None:
        # an explicit path must exist — silently training on synthetic
        # data because of a typo would be worse than failing
        if not os.path.exists(tar_path):
            raise FileNotFoundError("cifar: %r does not exist" % tar_path)
        return reader_creator(tar_path, marker)
    tar_path = fetch_or_none(url, "cifar", md5)
    if tar_path and os.path.exists(tar_path):
        return reader_creator(tar_path, marker)
    return _synthetic_reader(synth_n, classes, seed)


def train10(tar_path=None):
    return _make(CIFAR10_URL, CIFAR10_MD5, "data_batch", 10,
                 _SYNTH_TRAIN_N, 100, tar_path)


def test10(tar_path=None):
    return _make(CIFAR10_URL, CIFAR10_MD5, "test_batch", 10,
                 _SYNTH_TEST_N, 101, tar_path)


def train100(tar_path=None):
    return _make(CIFAR100_URL, CIFAR100_MD5, "train", 100,
                 _SYNTH_TRAIN_N, 102, tar_path)


def test100(tar_path=None):
    return _make(CIFAR100_URL, CIFAR100_MD5, "test", 100,
                 _SYNTH_TEST_N, 103, tar_path)
