"""MQ2007 learning-to-rank stand-in (reference: python/paddle/v2/
dataset/mq2007.py — query groups of 46-dim feature vectors with
relevance labels; pairwise/listwise reader modes)."""

from .common import rng

__all__ = ["train", "test", "FEATURE_DIM"]

FEATURE_DIM = 46


def _reader(n_queries, seed, format="pairwise"):
    r = rng(seed)

    def reader():
        for _ in range(n_queries):
            docs = int(r.randint(5, 20))
            feats = r.uniform(-1, 1,
                              size=(docs, FEATURE_DIM)).astype("float32")
            # relevance correlates with feature sum
            rel = (feats.sum(1) > 0).astype("int64") + \
                (feats.sum(1) > 1).astype("int64")
            if format == "listwise":
                yield feats, rel
                continue
            for i in range(docs):
                for j in range(docs):
                    if rel[i] > rel[j]:
                        yield 1.0, feats[i], feats[j]

    return reader


def train(format="pairwise"):
    return _reader(64, 81, format)


def test(format="pairwise"):
    return _reader(16, 82, format)
