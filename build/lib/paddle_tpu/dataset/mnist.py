"""MNIST dataset: real idx-format parsing with synthetic fallback.

reference: python/paddle/v2/dataset/mnist.py:37 (reader_creator over
the gzip idx3/idx1 pair; images scaled to [-1, 1], int labels 0-9).
The reference shells out to zcat; here the gzip module + one
numpy.frombuffer per file does the same decode without subprocesses.
"""

import gzip
import os
import struct

import numpy as np

from .common import fetch_or_none, synthetic_images

__all__ = ["train", "test", "parse_idx_images", "parse_idx_labels"]

URL_PREFIX = "http://yann.lecun.com/exdb/mnist/"
TRAIN_IMAGE_URL = URL_PREFIX + "train-images-idx3-ubyte.gz"
TRAIN_IMAGE_MD5 = "f68b3c2dcbeaaa9fbdd348bbdeb94873"
TRAIN_LABEL_URL = URL_PREFIX + "train-labels-idx1-ubyte.gz"
TRAIN_LABEL_MD5 = "d53e105ee54ea40749a09fcbcd1e9432"
TEST_IMAGE_URL = URL_PREFIX + "t10k-images-idx3-ubyte.gz"
TEST_IMAGE_MD5 = "9fb629c4189551a2d022fa330f9573f3"
TEST_LABEL_URL = URL_PREFIX + "t10k-labels-idx1-ubyte.gz"
TEST_LABEL_MD5 = "ec29112dd5afa0611ce80d1b7f02629c"

_IDX_IMAGE_MAGIC = 2051
_IDX_LABEL_MAGIC = 2049

_SYNTH_TRAIN_N = 2048
_SYNTH_TEST_N = 512


def _open_maybe_gz(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def parse_idx_images(path):
    """idx3-ubyte -> float32 [n, rows*cols] scaled to [-1, 1]."""
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _IDX_IMAGE_MAGIC:
            raise ValueError("%s: bad idx3 magic %d" % (path, magic))
        raw = np.frombuffer(f.read(n * rows * cols), np.uint8)
    images = raw.reshape(n, rows * cols).astype(np.float32)
    return images / 255.0 * 2.0 - 1.0


def parse_idx_labels(path):
    """idx1-ubyte -> int64 [n]."""
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _IDX_LABEL_MAGIC:
            raise ValueError("%s: bad idx1 magic %d" % (path, magic))
        raw = np.frombuffer(f.read(n), np.uint8)
    return raw.astype(np.int64)


def reader_creator(image_path, label_path):
    def reader():
        images = parse_idx_images(image_path)
        labels = parse_idx_labels(label_path)
        if images.shape[0] != labels.shape[0]:
            raise ValueError("mnist: %d images vs %d labels"
                             % (images.shape[0], labels.shape[0]))
        for i in range(images.shape[0]):
            yield images[i], int(labels[i])

    return reader


def _synthetic_reader(n, seed):
    imgs, labels = synthetic_images(n, (784,), 10, seed)

    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def _make(image_url, image_md5, label_url, label_md5, synth_n, seed,
          image_path=None, label_path=None):
    explicit = image_path is not None or label_path is not None
    if image_path is None:
        image_path = fetch_or_none(image_url, "mnist", image_md5)
    if label_path is None:
        label_path = fetch_or_none(label_url, "mnist", label_md5)
    if explicit:
        # explicit paths must both resolve — never silently swap a
        # user-supplied file for synthetic data
        for p in (image_path, label_path):
            if not p or not os.path.exists(p):
                raise FileNotFoundError(
                    "mnist: %r does not exist (explicit paths require "
                    "both image and label files)" % (p,))
        return reader_creator(image_path, label_path)
    if image_path and label_path and os.path.exists(image_path) \
            and os.path.exists(label_path):
        return reader_creator(image_path, label_path)
    return _synthetic_reader(synth_n, seed)


def train(image_path=None, label_path=None):
    """Real idx files when available (downloaded or passed explicitly);
    deterministic synthetic digits otherwise."""
    return _make(TRAIN_IMAGE_URL, TRAIN_IMAGE_MD5, TRAIN_LABEL_URL,
                 TRAIN_LABEL_MD5, _SYNTH_TRAIN_N, 42,
                 image_path, label_path)


def test(image_path=None, label_path=None):
    return _make(TEST_IMAGE_URL, TEST_IMAGE_MD5, TEST_LABEL_URL,
                 TEST_LABEL_MD5, _SYNTH_TEST_N, 43,
                 image_path, label_path)
