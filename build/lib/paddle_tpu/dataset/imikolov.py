"""PTB/imikolov word2vec stand-in (reference: python/paddle/v2/dataset/
imikolov.py — N-gram tuples over a word vocabulary)."""

from .common import rng

__all__ = ["train", "test", "build_dict"]

_VOCAB = 2073
_TRAIN_N = 2048
_TEST_N = 256


def build_dict(min_word_freq=50):
    return {("w%d" % i): i for i in range(_VOCAB)}


def _reader(n, gram_n, seed):
    r = rng(seed)
    # markov-ish structure: next word correlates with sum of context
    def reader():
        for _ in range(n):
            ctx = r.randint(0, _VOCAB, size=gram_n - 1)
            nxt = int((ctx.sum() * 31 + 7) % _VOCAB)
            yield tuple(int(c) for c in ctx) + (nxt,)

    return reader


def train(word_idx=None, n=5):
    return _reader(_TRAIN_N, n, 11)


def test(word_idx=None, n=5):
    return _reader(_TEST_N, n, 12)
