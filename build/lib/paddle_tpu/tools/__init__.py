"""Operational tools (cluster launch, model conversion)."""
