"""Cluster job launcher: spawn pservers + trainers for one training job.

reference: paddle/scripts/cluster_train/paddle.py (fabric/ssh job
spawner setting PADDLE_* env per process) and the env-var role protocol
of tests/book_distribute/notest_dist_fit_a_line.py:45-53
(TRAINING_ROLE / PSERVERS / TRAINER_ID).  Local mode runs everything on
this host; remote mode emits the per-host commands (ssh execution is
site-specific by design).

Usage:
    python -m paddle_tpu.tools.cluster_launch \
        --pservers=127.0.0.1:7164,127.0.0.1:7165 --trainers=2 \
        [--async] train.py [script args...]
"""

import argparse
import os
import signal
import subprocess
import sys

__all__ = ["launch", "main"]


def launch(script_argv, pservers, trainers, sync=True, env=None,
           python=sys.executable):
    """Spawn len(pservers) pserver processes + `trainers` trainer
    processes; returns (pserver_procs, trainer_procs)."""
    base_env = dict(os.environ)
    base_env.update(env or {})
    base_env["PSERVERS"] = ",".join(pservers)
    base_env["TRAINERS"] = str(trainers)
    base_env["PADDLE_SYNC"] = "1" if sync else "0"

    ps_procs = []
    for ep in pservers:
        code = ("import os,sys,signal;"
                "from paddle_tpu.distributed import run_pserver;"
                "s=run_pserver(os.environ['PSERVER_ENDPOINT'],"
                "trainers=int(os.environ['TRAINERS']),"
                "sync=os.environ['PADDLE_SYNC']=='1');"
                "print('pserver ready', flush=True);"
                "signal.pause()")
        ps_procs.append(subprocess.Popen(
            [python, "-c", code],
            env={**base_env, "TRAINING_ROLE": "PSERVER",
                 "PSERVER_ENDPOINT": ep},
            stdout=subprocess.PIPE, text=True))
    # trainers have no connect retry: wait until every pserver has
    # bound its port before spawning them
    for p in ps_procs:
        line = p.stdout.readline()
        if "ready" not in line:
            raise RuntimeError("pserver failed to start: %r" % line)

    tr_procs = []
    for tid in range(trainers):
        tr_procs.append(subprocess.Popen(
            [python] + list(script_argv),
            env={**base_env, "TRAINING_ROLE": "TRAINER",
                 "TRAINER_ID": str(tid)}))
    return ps_procs, tr_procs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pservers", required=True,
                    help="comma-separated host:port endpoints")
    ap.add_argument("--trainers", type=int, default=1)
    ap.add_argument("--async", dest="sync", action="store_false",
                    help="async SGD (reference: asyncSGD)")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="trainer script + args")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("missing trainer script")

    pservers = args.pservers.split(",")
    ps_procs, tr_procs = launch(args.script, pservers, args.trainers,
                                sync=args.sync)
    rc = 0
    try:
        for p in tr_procs:
            rc |= p.wait()
    finally:
        for p in ps_procs:
            p.send_signal(signal.SIGTERM)
        for p in ps_procs:
            p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
