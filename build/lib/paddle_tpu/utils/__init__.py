"""Process-level utilities: flags, logging helpers.

Maps the reference's paddle/utils (gflags registry Flags.cpp:18-100,
Stat timers — timers live in fluid.profiler here).
"""

from . import flags
from .flags import DEFINE_flag, get_flag, set_flag, parse_flags_from_env

__all__ = ["flags", "DEFINE_flag", "get_flag", "set_flag",
           "parse_flags_from_env"]
