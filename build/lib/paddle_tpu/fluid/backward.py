"""Symbolic backward pass over the program IR.

TPU-native re-design of the reference autodiff builders
(reference: python/paddle/v2/fluid/backward.py:338 append_backward,
:116 _addup_repetitive_outputs_, :166 _remove_no_grad_branch_;
C++ twin paddle/framework/backward.cc:523 AppendBackward).

Matches the reference's *structure* — grad ops are appended to the same
block, gradient variables are named `<var>@GRAD`, repeated contributions
are accumulated with `sum` ops — but each grad op's kernel is derived from
the forward kernel with jax.vjp (see ops/registry.py), so no per-op grad
functor library exists.  Control-flow ops (scan/cond based) differentiate
through the same mechanism, replacing the reference's recursive sub-block
backward (backward.cc:415 MakeBlockBackward).
"""

from collections import defaultdict

from ..core.desc import OpDesc
from ..core.types import grad_var_name, GRAD_SUFFIX
from ..ops import registry as op_registry
from . import framework

__all__ = ["append_backward", "calc_gradient"]

EMPTY = "@EMPTY@"


def _op_info_for(op_type):
    return op_registry.get_op_info(op_type)


class _GradState:
    def __init__(self, block):
        self.block = block
        self.contribs = defaultdict(list)  # var name -> [grad contrib names]
        self.new_ops = []

    def add_contrib(self, var_name):
        """Reserve a fresh grad contribution name for var_name."""
        n = len(self.contribs[var_name])
        gname = (grad_var_name(var_name) if n == 0
                 else "%s@RENAME@%d" % (grad_var_name(var_name), n))
        self.contribs[var_name].append(gname)
        return gname

    def has_grad(self, var_name):
        return len(self.contribs[var_name]) > 0

    def finalize(self, var_name):
        """Return the final grad var name for var_name, emitting a `sum` op
        if there are multiple contributions (reference:
        backward.py:116 _addup_repetitive_outputs_)."""
        contribs = self.contribs[var_name]
        if not contribs:
            return None
        if len(contribs) == 1:
            return contribs[0]
        out = grad_var_name(var_name)
        if out in contribs:
            # rename the canonical one so sum's output is fresh
            renamed = out + "@RENAME@0r"
            for op in self.new_ops:
                for names in op.outputs.values():
                    for i, n in enumerate(names):
                        if n == out:
                            names[i] = renamed
                for names in op.inputs.values():
                    for i, n in enumerate(names):
                        if n == out:
                            names[i] = renamed
            contribs = [renamed if c == out else c for c in contribs]
        sum_op = OpDesc("sum", {"X": contribs}, {"Out": [out]}, {})
        self.new_ops.append(sum_op)
        self.contribs[var_name] = [out]
        return out


def _make_grad_op(op_desc, state, no_grad_names):
    """Build the grad OpDesc for one forward op; returns None if no input
    needs a gradient."""
    info = _op_info_for(op_desc.type)
    if info.stop_gradient_op:
        return None

    # out grads (finalize accumulations from already-emitted consumers)
    og_inputs = {}
    any_og = False
    for slot, names in op_desc.outputs.items():
        gs = []
        for n in names:
            g = state.finalize(n) if n != EMPTY else None
            gs.append(g if g is not None else EMPTY)
            any_og = any_og or g is not None
        og_inputs["OG@" + slot] = gs
    if not any_og:
        return None

    # which inputs get grads
    out_slots = {}
    any_grad = False
    for slot, names in op_desc.inputs.items():
        if slot in info.nondiff_inputs:
            continue
        outs = []
        for n in names:
            if n in no_grad_names:
                outs.append(EMPTY)
            else:
                outs.append(state.add_contrib(n))
                any_grad = True
        out_slots[slot + GRAD_SUFFIX] = outs
    if not any_grad:
        return None

    grad_inputs = dict(op_desc.inputs)
    for slot, names in op_desc.outputs.items():
        grad_inputs["O@" + slot] = list(names)
    grad_inputs.update(og_inputs)

    return OpDesc(op_desc.type + "_grad", grad_inputs, out_slots,
                  dict(op_desc.attrs))


def _collect_no_grad(block, no_grad_set):
    no_grad = set(no_grad_set or ())
    bd = block.desc
    prog = block.program.desc
    while True:
        for name, vd in bd.vars.items():
            if vd.stop_gradient:
                no_grad.add(name)
        if bd.parent_idx < 0:
            break
        bd = prog.block(bd.parent_idx)
    return no_grad


def _append_grad_ops(block, targets, target_grads, no_grad_names,
                     stop_at_op=None):
    """Emit grad ops into `block` for the reverse slice from `targets`.
    targets: list of var names seeded with grads named by target_grads."""
    state = _GradState(block)
    for t, tg in zip(targets, target_grads):
        state.contribs[t].append(tg)

    fwd_ops = list(block.desc.ops)
    for op_desc in reversed(fwd_ops):
        if op_registry.is_grad_op_type(op_desc.type):
            continue
        info = _op_info_for(op_desc.type)
        if info.stop_gradient_op:
            continue
        if not any(state.has_grad(n) for n in op_desc.output_names()):
            continue
        g = _make_grad_op(op_desc, state, no_grad_names)
        if g is not None:
            state.new_ops.append(g)

    return state


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append backward ops computing d(loss)/d(param) for every trainable
    parameter; returns [(param, grad_var)] (reference: backward.py:338).
    """
    assert isinstance(loss, framework.Variable)
    program = loss.block.program
    block = program.global_block()
    no_grad_names = _collect_no_grad(block, no_grad_set)

    # seed: d loss / d loss = 1 (reference fills with fill_constant)
    loss_grad = grad_var_name(loss.name)
    seed_op = OpDesc(
        "fill_constant", {}, {"Out": [loss_grad]},
        {"shape": list(loss.shape) or [1], "value": 1.0,
         "dtype": loss.dtype})
    block.desc.ops.append(seed_op)
    _ensure_grad_var(block, loss.name)

    state = _append_grad_ops(block, [loss.name], [loss_grad],
                             no_grad_names)

    # finalize leaf grads (params & inputs) — emits pending sum ops
    params = block.all_parameters()
    if parameter_list is not None:
        wanted = set(parameter_list)
        params = [p for p in params if p.name in wanted]
    params_grads = []
    for p in params:
        if not getattr(p, "trainable", True):
            continue
        gname = state.finalize(p.name)
        if gname is None:
            continue
        params_grads.append((p, gname))

    if callbacks is None:
        callbacks = [_error_clip_callback]
    elif not isinstance(callbacks, (list, tuple)):
        callbacks = [callbacks]
    for op in state.new_ops:
        block.desc.ops.append(op)
        for names in op.outputs.values():
            for n in names:
                if n != EMPTY:
                    _ensure_grad_var(block, _src_of(n))
        _apply_sparse_grad_types(block, op)
        # per-appended-grad-op hook (reference: backward.py callbacks;
        # error_clip ops are injected right after the grad op)
        for cb in callbacks:
            cb(block=block, context={})
    block.sync_with_desc()

    # return Variables for the grads
    out = []
    for p, gname in params_grads:
        gvar = block.var(gname) if block.has_var(gname) else None
        out.append((p, gvar))
    return out


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Compute grads of targets w.r.t. inputs (reference later adds
    gradients.calc_gradient; provided for API completeness)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    block = targets[0].block
    program = block.program
    no_grad_names = _collect_no_grad(block, no_grad_set)
    # inputs must receive grads even if marked stop_gradient
    no_grad_names -= {v.name for v in inputs}

    tnames, tgrads = [], []
    for i, t in enumerate(targets):
        g = grad_var_name(t.name)
        if target_gradients is not None and target_gradients[i] is not None:
            g = target_gradients[i].name
        else:
            block.desc.ops.append(OpDesc(
                "fill_constant", {}, {"Out": [g]},
                {"shape": list(t.shape) or [1], "value": 1.0,
                 "dtype": t.dtype}))
            _ensure_grad_var(block, t.name)
        tnames.append(t.name)
        tgrads.append(g)

    state = _append_grad_ops(block, tnames, tgrads, no_grad_names)
    grads = []
    for v in inputs:
        grads.append(state.finalize(v.name))
    block.desc.ops.extend(state.new_ops)
    for op in state.new_ops:
        for names in op.outputs.values():
            for n in names:
                if n != EMPTY:
                    _ensure_grad_var(block, _src_of(n))
        _apply_sparse_grad_types(block, op)
    block.sync_with_desc()
    return [block.var(g) if g is not None else None for g in grads]


def _error_clip_callback(block, context):
    """Apply per-variable error clipping to the grad op just appended
    (reference: clip.py error_clip_callback)."""
    op_desc = block.desc.ops[-1]
    for grad_n in op_desc.output_names():
        if grad_n == EMPTY or not grad_n.endswith(GRAD_SUFFIX):
            continue
        fwd_name = _src_of(grad_n)
        try:
            fwd_var = block.var_recursive(fwd_name)
        except ValueError:
            continue
        error_clip = getattr(fwd_var, "error_clip", None)
        if error_clip is not None:
            error_clip.append_clip_op(block, grad_n)


def _src_of(grad_name):
    base = grad_name.split("@RENAME@")[0]
    if base.endswith(GRAD_SUFFIX):
        return base[: -len(GRAD_SUFFIX)]
    return base


def _apply_sparse_grad_types(block, op_desc):
    """Type grad VarDescs that a grad op produces as SelectedRows (the
    descs default to mirroring the dense forward var).  Driven by the
    forward op's registry hook — reference: the per-op VarTypeInference
    pass, e.g. lookup_table_op.cc marking W@GRAD as SelectedRows when
    is_sparse.  Grad-accumulation `sum` ops propagate the typing: the
    sum of all-SelectedRows contributions is a SelectedRows (rows
    concatenated, reference: sum_op.cc SelectedRows path), so a table
    looked up more than once still routes sparse."""
    from ..core.types import VarType

    if op_desc.type == "sum":
        in_names = [n for n in op_desc.input("X") if n != EMPTY]
        in_descs = [block.desc.vars.get(n) for n in in_names]
        if in_descs and all(
                vd is not None and vd.type == VarType.SELECTED_ROWS
                for vd in in_descs):
            for n in op_desc.output("Out"):
                vd = block.desc.vars.get(n)
                if vd is not None:
                    vd.type = VarType.SELECTED_ROWS
        return
    if not op_registry.is_grad_op_type(op_desc.type):
        return
    info = _op_info_for(op_registry.forward_type_of_grad(op_desc.type))
    hook = info.sparse_grad_slots
    if hook is None:
        return
    for slot in hook(op_desc.attrs):
        for n in op_desc.outputs.get(slot + GRAD_SUFFIX, []):
            if n == EMPTY:
                continue
            vd = block.desc.vars.get(n)
            if vd is not None:
                vd.type = VarType.SELECTED_ROWS


def _ensure_grad_var(block, src_name):
    """Create VarDescs for `src@GRAD` (+ any renames) mirroring src meta."""
    from ..core.desc import VarDesc

    bd = block.desc
    src = None
    b = bd
    prog = block.program.desc
    while True:
        if src_name in b.vars:
            src = b.vars[src_name]
            break
        if b.parent_idx < 0:
            break
        b = prog.block(b.parent_idx)
    gname = grad_var_name(src_name)
    names = [gname]
    # include rename variants already referenced by ops
    for op in bd.ops:
        for ns in list(op.outputs.values()) + list(op.inputs.values()):
            for n in ns:
                if n.startswith(gname + "@RENAME@"):
                    names.append(n)
    for n in names:
        if n not in bd.vars:
            vd = VarDesc(n)
            if src is not None:
                vd.type = src.type
                vd.dtype = src.dtype
                vd.shape = src.shape
                vd.lod_level = src.lod_level
            bd.vars[n] = vd
