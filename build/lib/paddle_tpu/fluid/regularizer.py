"""Weight-decay regularizers as appended ops.

reference: python/paddle/v2/fluid/regularizer.py (append_regularization_ops,
L1DecayRegularizer, L2DecayRegularizer).
"""

from . import framework

__all__ = ["append_regularization_ops", "L1Decay", "L2Decay",
           "L1DecayRegularizer", "L2DecayRegularizer"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=framework.unique_name(param.name + "_l2_decay"),
            dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale", inputs={"X": [param]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=framework.unique_name(param.name + "_sign"),
            dtype=param.dtype, shape=param.shape)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = block.create_var(
            name=framework.unique_name(param.name + "_l1_decay"),
            dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff})
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference: regularizer.py append_regularization_ops — adds
    `grad + coeff*decay(param)` per regularized parameter."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        regularization_term = None
        reg = getattr(param, "regularizer", None) or regularization
        if grad is not None and reg is not None:
            regularization_term = reg(param, grad, grad.block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        new_grad = block.create_var(
            name=framework.unique_name(grad.name + "_reg"),
            dtype=grad.dtype, shape=grad.shape)
        block.append_op(type="sum",
                        inputs={"X": [grad, regularization_term]},
                        outputs={"Out": [new_grad]})
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
