"""Profiler: per-op timing tables and XLA trace hooks.

reference: paddle/platform/profiler.h:27-146 (RecordEvent around every op,
ParseEvents table) + python/paddle/v2/fluid/profiler.py.  The compiled
path profiles at segment granularity (XLA owns fusion); the eager executor
mode gives reference-style per-op attribution.  `profiler(...)` can also
start JAX's own trace for TensorBoard.
"""

import contextlib
import time
from collections import defaultdict

__all__ = ["profiler", "reset_profiler", "get_profile_records",
           "cuda_profiler", "tpu_profiler"]

_records = defaultdict(lambda: {"calls": 0, "total": 0.0,
                                "min": float("inf"), "max": 0.0})
_enabled = [False]


def is_enabled():
    return _enabled[0]


def record(name, seconds):
    r = _records[name]
    r["calls"] += 1
    r["total"] += seconds
    r["min"] = min(r["min"], seconds)
    r["max"] = max(r["max"], seconds)


@contextlib.contextmanager
def record_event(name):
    if not _enabled[0]:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - t0)


def reset_profiler():
    _records.clear()


def get_profile_records():
    return {k: dict(v) for k, v in _records.items()}


def _print_table(sorted_key=None):
    rows = []
    for name, r in _records.items():
        rows.append((name, r["calls"], r["total"],
                     r["min"] if r["calls"] else 0.0, r["max"],
                     r["total"] / max(r["calls"], 1)))
    key_idx = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}.get(
        sorted_key, 2)
    rows.sort(key=lambda x: -x[key_idx] if isinstance(x[key_idx], (int,
              float)) else 0)
    print("%-40s %8s %12s %12s %12s %12s" % (
        "Event", "Calls", "Total(s)", "Min(s)", "Max(s)", "Ave(s)"))
    for row in rows:
        print("%-40s %8d %12.6f %12.6f %12.6f %12.6f" % row)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, trace_dir=None):
    """reference: fluid/profiler.py profiler context manager."""
    _enabled[0] = True
    reset_profiler()
    jax_trace = None
    if trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)
        jax_trace = trace_dir
    try:
        yield
    finally:
        _enabled[0] = False
        if jax_trace:
            import jax

            jax.profiler.stop_trace()
        _print_table(sorted_key)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Kept for API parity (reference: fluid/profiler.py:33); maps to a JAX
    device trace."""
    with profiler(trace_dir=None):
        yield


tpu_profiler = cuda_profiler
