"""Automatic mixed precision: bf16 compute, f32 master weights.

TPU-native counterpart of the reference's float16 support (reference:
paddle/math/float16.h — CUDA half/ARM fp16 interop; fp16 design docs).
On TPU the native fast dtype is bfloat16: when enabled, the heavy MXU
ops (mul/matmul/conv/lstm projections) cast their f32 operands to bf16
and accumulate in f32 (`preferred_element_type`) — master-weight
semantics without loss scaling (bf16 keeps f32's exponent range).

Activations BETWEEN ops also stay bf16 by default
(`FLAGS_amp_bf16_act`): conv/matmul results are not cast back to f32,
so the elementwise/norm chains read and write half the bytes (HBM
bandwidth is the usual TPU bottleneck).  What remains f32 regardless:
parameters + optimizer state (masters), all reduction statistics
(batch/layer norm mean/var), losses, and everything crossing the
feed/fetch boundary.  Set FLAGS_amp_bf16_act=0 for the conservative
cast-back-to-f32 behaviour.
"""

import contextlib

from ..utils import flags

__all__ = ["enable_bf16", "disable_bf16", "bf16_enabled", "bf16_guard"]


def enable_bf16():
    flags.set_flag("amp_bf16", True)


def disable_bf16():
    flags.set_flag("amp_bf16", False)


def bf16_enabled():
    return flags.get_flag("amp_bf16")


@contextlib.contextmanager
def bf16_guard():
    prev = bf16_enabled()
    flags.set_flag("amp_bf16", True)
    try:
        yield
    finally:
        flags.set_flag("amp_bf16", prev)
