"""Optimizers: build update ops into the program.

TPU-native equivalent of reference optimizers
(reference: python/paddle/v2/fluid/optimizer.py — Optimizer:28,
minimize:204, SGD/Momentum/Adagrad/Adam/Adamax/DecayedAdagrad:228-550).
`minimize` = append_backward + regularization + clipping +
per-parameter update ops; the whole train step then compiles into one XLA
executable with donated parameter buffers.
"""

from collections import defaultdict

from . import framework
from .framework import unique_name, Variable
from .backward import append_backward
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from . import clip as clip_mod

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
           "Adadelta", "RMSProp", "Ftrl",
           "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer",
           "AdamOptimizer", "AdamaxOptimizer", "DecayedAdagradOptimizer",
           "AdadeltaOptimizer", "RMSPropOptimizer", "FtrlOptimizer",
           "Optimizer"]


class Optimizer:
    def __init__(self, learning_rate, regularization=None,
                 global_step=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning_rate should be float or Variable")
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._global_step = global_step
        self._accumulators = defaultdict(dict)
        self.helper = None
        self._learning_rate_map = {}
        # the program minimize() is operating on; set by
        # create_optimization_pass so accumulators/lr land in the right
        # program even when it is not the default one
        self._target_program = None

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self, program):
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        lr_name = unique_name("learning_rate")
        lr_var = program.global_block().create_var(
            name=lr_name, shape=[1], dtype="float32", persistable=True)
        self.helper.set_variable_initializer(
            lr_var, Constant(float(self._learning_rate)))
        self._learning_rate_map[program] = lr_var

    def _global_learning_rate(self, program=None):
        if program is None:
            program = self._target_program or \
                framework.default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = getattr(param, "optimize_attr",
                           {"learning_rate": 1.0}).get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        helper = self.helper
        out = helper.create_tmp_variable("float32", stop_gradient=True)
        helper.append_op(type="scale", inputs={"X": [base]},
                         outputs={"Out": [out]},
                         attrs={"scale": float(param_lr)})
        return out

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        var_name = unique_name("_".join([param.name, name]))
        block = (self._target_program or
                 framework.default_main_program()).global_block()
        var = block.create_var(
            name=var_name, shape=shape or list(param.shape),
            dtype=dtype or param.dtype, persistable=True)
        self.helper.set_variable_initializer(var, Constant(fill_value))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks for subclasses -----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # -- main entry ---------------------------------------------------------
    def create_optimization_pass(self, parameters_and_grads, loss,
                                 startup_program=None):
        """reference: optimizer.py:151."""
        program = loss.block.program
        self._target_program = program
        self.helper = LayerHelper(self.__class__.__name__,
                                  main_program=program,
                                  startup_program=startup_program)
        self._create_accumulators(
            program.global_block(),
            [p[0] for p in parameters_and_grads if p[1] is not None])
        self._create_global_learning_rate(program)

        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if getattr(param_and_grad[0], "trainable", True):
                op = self._append_optimize_op(program.global_block(),
                                              param_and_grad)
                optimize_ops.append(op)

        self._finish_update(program.global_block())

        if self._global_step is not None:
            from .layers import tensor as tensor_layers

            tensor_layers.increment(self._global_step, value=1.0,
                                    in_place=True)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """reference: optimizer.py:204."""
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads, clip_ops = clip_mod.append_gradient_clip_ops(
            params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self.create_optimization_pass(
            params_grads, loss, startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        grad_var = block.var(grad) if isinstance(grad, str) else grad
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad_var],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        main_block = (self._target_program or
                      framework.default_main_program()).global_block()
        self._beta1_pow_acc = main_block.create_var(
            name=unique_name("beta1_pow_acc"), shape=[1], dtype="float32",
            persistable=True)
        self.helper.set_variable_initializer(self._beta1_pow_acc,
                                             Constant(self._beta1))
        self._beta2_pow_acc = main_block.create_var(
            name=unique_name("beta2_pow_acc"), shape=[1], dtype="float32",
            persistable=True)
        self.helper.set_variable_initializer(self._beta2_pow_acc,
                                             Constant(self._beta2))
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment1 = self._get_accumulator(self._moment1_acc_str, param)
        moment2 = self._get_accumulator(self._moment2_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment1": [moment1], "Moment2": [moment2],
                    "Beta1Pow": [self._beta1_pow_acc],
                    "Beta2Pow": [self._beta2_pow_acc]},
            outputs={"ParamOut": [param], "Moment1Out": [moment1],
                     "Moment2Out": [moment2]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block):
        """Advance beta powers once per step (reference: optimizer.py Adam
        _finish_update appends scale ops)."""
        block.append_op(
            type="scale", inputs={"X": [self._beta1_pow_acc]},
            outputs={"Out": [self._beta1_pow_acc]},
            attrs={"scale": self._beta1})
        block.append_op(
            type="scale", inputs={"X": [self._beta2_pow_acc]},
            outputs={"Out": [self._beta2_pow_acc]},
            attrs={"scale": self._beta2})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        main_block = (self._target_program or
                      framework.default_main_program()).global_block()
        self._beta1_pow_acc = main_block.create_var(
            name=unique_name("beta1_pow_acc"), shape=[1], dtype="float32",
            persistable=True)
        self.helper.set_variable_initializer(self._beta1_pow_acc,
                                             Constant(self._beta1))
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [self._beta1_pow_acc]},
            outputs={"ParamOut": [param], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block):
        block.append_op(
            type="scale", inputs={"X": [self._beta1_pow_acc]},
            outputs={"Out": [self._beta1_pow_acc]},
            attrs={"scale": self._beta1})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, param)
        asu = self._get_accumulator(self._avg_squared_update_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    _mean_square_acc_str = "mean_square"
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.9, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "rmsprop"
        self._decay = decay
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._mean_square_acc_str, p)
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        ms = self._get_accumulator(self._mean_square_acc_str, param)
        mom = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad], "MeanSquare": [ms],
                    "Moment": [mom],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MeanSquareOut": [ms],
                     "MomentOut": [mom]},
            attrs={"decay": self._decay, "epsilon": self._epsilon,
                   "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        Optimizer.__init__(self, learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator(self._squared_acc_str, param)
        lin = self._get_accumulator(self._linear_acc_str, param)
        return block.append_op(
            type=self.type,
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
