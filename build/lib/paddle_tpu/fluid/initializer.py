"""Parameter initializers as startup-program ops.

TPU-native equivalent of reference initializers
(reference: python/paddle/v2/fluid/initializer.py — Constant, Uniform,
Normal, Xavier, MSRA).  Each __call__ appends the corresponding init op
(fill_constant / uniform_random / gaussian_random) to the startup block;
XLA compiles the whole startup program into one executable.
"""

import math

from . import framework

__all__ = ["Constant", "Uniform", "Normal", "Xavier", "MSRA",
           "ConstantInitializer", "UniformInitializer", "NormalInitializer",
           "XavierInitializer", "MSRAInitializer", "force_init_on_cpu"]


def force_init_on_cpu():
    # placement is XLA's concern on TPU; kept for API parity
    return False


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _fan_in_out(self, var):
        shape = var.shape
        if len(shape) < 2:
            return (1, shape[0] if shape else 1)
        receptive = 1
        for d in shape[2:]:
            receptive *= d
        # conv weight [out_c, in_c, kh, kw] (reference initializer.py
        # computes fan from the first two dims times receptive field)
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": float(self.low), "max": float(self.high),
                   "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": float(self.loc), "std": float(self.scale),
                   "seed": self.seed})


class XavierInitializer(Initializer):
    """reference: initializer.py XavierInitializer (Glorot & Bengio 2010)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fan_in, fan_out = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        fan_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """reference: initializer.py MSRAInitializer (He et al. 2015)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fan_in, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fan_in)
            NormalInitializer(0.0, std, self.seed)(var, block)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
