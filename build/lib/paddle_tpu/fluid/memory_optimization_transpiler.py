"""Memory-optimization transpiler: liveness-driven buffer reuse.

reference: python/paddle/v2/fluid/memory_optimization_transpiler.py —
liveness analysis (ControlFlowGraph:33) rewriting the program so later
temporaries reuse the storage of dead ones.  Here the pass REWRITES the
program the same way (dead temp's name adopted by a compatible later
def, so the scope slot is overwritten in place).  Under jit the rewrite
is belt-and-braces — XLA's buffer assignment performs equivalent reuse
at compile time — but in the eager debug executor it genuinely caps the
live-buffer count, and the rewrite doubles as the reference-parity
surface.  `memory_optimize(..., rewrite=False)` keeps the old
report-only behavior.
"""

from collections import defaultdict

from . import framework

__all__ = ["memory_optimize", "ControlFlowGraph"]


class ControlFlowGraph:
    """Forward liveness over a block's op list (reference:
    memory_optimization_transpiler.py ControlFlowGraph:33 — same uses /
    defs / live-in / live-out construction)."""

    def __init__(self, program):
        self._program = program
        block = program.global_block()
        self._ops = list(block.desc.ops)
        # "@EMPTY@" is the backward builder's missing-slot placeholder,
        # not a variable (same filter as the executor's analysis)
        self._uses = [set(od.input_names()) - {"@EMPTY@"}
                      for od in self._ops]
        self._defs = [set(od.output_names()) - {"@EMPTY@"}
                      for od in self._ops]
        self._live_in = [set() for _ in self._ops]
        self._live_out = [set() for _ in self._ops]

    def analyze(self):
        changed = True
        n = len(self._ops)
        while changed:
            changed = False
            for i in reversed(range(n)):
                live_out = set()
                if i + 1 < n:
                    live_out = self._live_in[i + 1]
                live_in = self._uses[i] | (live_out - self._defs[i])
                if live_in != self._live_in[i] or \
                        live_out != self._live_out[i]:
                    self._live_in[i] = live_in
                    self._live_out[i] = live_out
                    changed = True
        return self

    def reuse_candidates(self):
        """Vars dead after an op whose buffer a later def could reuse
        (what XLA's buffer assignment will actually fold)."""
        persist = set()
        block = self._program.global_block()
        for name, var in block.vars.items():
            if getattr(var, "persistable", False):
                persist.add(name)
        released = defaultdict(list)
        for i in range(len(self._ops)):
            dead = (self._live_in[i] | self._defs[i]) - self._live_out[i]
            for name in sorted(dead - persist):
                released[i].append(name)
        return dict(released)


def _sub_block_names(program):
    """Var names referenced by any non-root block: those cross block
    boundaries by name, so the root-block rename must not touch them."""
    names = set()
    for block in program.blocks[1:]:
        for od in block.desc.ops:
            names.update(od.input_names())
            names.update(od.output_names())
        names.update(block.desc.vars.keys())
    return names


def _rewrite_for_reuse(program, cfg, skip_set):
    """Rename later temp defs onto dead compatible temps (reference:
    the ControlFlowGraph rewrite loop).  Eligibility: both vars are
    root-block, non-persistable, dense (lod_level 0), static identical
    shape + dtype, not fed/fetched/skipped, and not referenced by any
    sub-block.  Returns {original_name: reused_name}."""
    block = program.global_block()
    bd = block.desc
    sub_names = _sub_block_names(program)

    def eligible(name):
        vd = bd.vars.get(name)
        if vd is None or name in skip_set or name in sub_names:
            return False
        if vd.persistable or (vd.lod_level or 0) > 0:
            return False
        # shapes must match as signatures (dynamic batch dims compare
        # positionally: (-1, 8) reuses (-1, 8)); the scope slot rebinds
        # per step so equal signatures guarantee matching descs for
        # downstream shape inference
        if not tuple(vd.shape or ()):
            return False
        from ..core.types import VarType

        if vd.type not in (None, VarType.DENSE_TENSOR):
            return False
        return True

    def signature(name):
        vd = bd.vars[name]
        return (tuple(vd.shape), vd.dtype)

    # feed vars: producer-less non-persistable root vars — never rename
    produced = set()
    for od in cfg._ops:
        produced.update(od.output_names())
    feeds = {n for n, vd in bd.vars.items()
             if not vd.persistable and n not in produced}

    pool = defaultdict(list)     # (shape, dtype) -> [dead var names]
    renames = {}                 # original -> adopted name
    pooled = set()               # names currently in the pool
    seen_defs = set()

    def resolve(n):
        return renames.get(n, n)

    for i, od in enumerate(cfg._ops):
        # release vars whose last USE is this op (candidates computed
        # on the ORIGINAL names, then mapped through prior renames);
        # this op's own dead defs join the pool only after its outputs
        # are placed, so two outputs can never adopt one slot
        dead_uses = (cfg._live_in[i] - cfg._live_out[i]) - cfg._defs[i]
        dead_defs = cfg._defs[i] - cfg._live_out[i]
        for orig in dead_uses:
            name = resolve(orig)
            if orig in feeds or not eligible(orig):
                continue
            if name not in pooled:
                pool[signature(orig)].append(name)
                pooled.add(name)
        for slot, names in od.outputs.items():
            for j, orig in enumerate(names):
                if orig in seen_defs or orig in renames:
                    continue
                seen_defs.add(orig)
                if not eligible(orig) or orig in cfg._uses[i]:
                    continue
                sig = signature(orig)
                if pool[sig]:
                    adopted = pool[sig].pop()
                    pooled.discard(adopted)
                    renames[orig] = adopted
        for orig in dead_defs:
            name = resolve(orig)
            if not eligible(orig):
                continue
            if name not in pooled:
                pool[signature(orig)].append(name)
                pooled.add(name)

    if renames:
        for od in bd.ops:
            for names in list(od.inputs.values()) + \
                    list(od.outputs.values()):
                for j, n in enumerate(names):
                    if n in renames:
                        names[j] = renames[n]
        for orig in renames:
            bd.vars.pop(orig, None)
            block.vars.pop(orig, None)
        block.sync_with_desc()
    return renames


def memory_optimize(input_program=None, skip_opt_set=None,
                    print_log=False, rewrite=True):
    """reference: memory_optimization_transpiler.py memory_optimize.
    Rewrites the root block so compatible later temps adopt dead temps'
    storage slots; returns (released_map, renames).  skip_opt_set:
    names to leave untouched (e.g. fetch targets kept under their own
    name).  rewrite=False reports liveness only."""
    program = input_program or framework.default_main_program()
    cfg = ControlFlowGraph(program).analyze()
    candidates = cfg.reuse_candidates()
    renames = {}
    if rewrite:
        renames = _rewrite_for_reuse(program, cfg,
                                     set(skip_opt_set or ()))
    if print_log:
        for i, names in sorted(candidates.items()):
            print("op %d releases %s" % (i, names))
        for orig, adopted in sorted(renames.items()):
            print("reuse: %s -> %s" % (orig, adopted))
    return candidates, renames
