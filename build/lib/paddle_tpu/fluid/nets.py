"""Composite networks.

Capability parity with the reference's nets module (reference:
python/paddle/v2/fluid/nets.py — simple_img_conv_pool, img_conv_group,
sequence_conv_pool, glu, scaled_dot_product_attention), expressed in
this framework's own idiom.  These are pure graph-builder sugar: every
composite lowers to the same conv/pool/matmul ops, which XLA then fuses
— there is nothing runtime-level here.
"""

from . import layers

__all__ = ["simple_img_conv_pool", "sequence_conv_pool", "glu",
           "scaled_dot_product_attention", "img_conv_group"]


def _per_stage(value, n_stages):
    """Broadcast a scalar hyperparameter to one entry per conv stage;
    sized values (list/tuple/ndarray — anything with a length, except
    strings) must already match the stage count."""
    if hasattr(value, "__len__") and not isinstance(value, str):
        if len(value) != n_stages:
            raise ValueError(
                "per-stage setting has %d entries for %d stages"
                % (len(value), n_stages))
        return list(value)
    return [value] * n_stages


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type="max"):
    """One conv (with activation) followed by one pool — the LeNet-style
    building block."""
    conv = layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size,
                         param_attr=param_attr, act=act)
    return layers.pool2d(input=conv, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max"):
    """A VGG-style block: N stacked convs (optionally each followed by
    batch-norm and dropout), then one pooling layer.  When a stage has
    batch-norm, the activation rides the BN op so conv→BN→act fuses
    into one XLA computation instead of materializing a pre-activation.
    """
    n = len(conv_num_filter)
    stages = zip(conv_num_filter,
                 _per_stage(conv_filter_size, n),
                 _per_stage(conv_padding, n),
                 _per_stage(param_attr, n),
                 _per_stage(conv_with_batchnorm, n),
                 _per_stage(conv_batchnorm_drop_rate, n))

    x = input
    for filters, fsize, pad, pattr, with_bn, drop in stages:
        x = layers.conv2d(input=x, num_filters=filters, filter_size=fsize,
                          padding=pad, param_attr=pattr,
                          act=None if with_bn else conv_act)
        if with_bn:
            x = layers.batch_norm(input=x, act=conv_act)
            if drop:
                x = layers.dropout(x=x, dropout_prob=drop)

    return layers.pool2d(input=x, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    pool_out = layers.sequence_pool(input=conv_out, pool_type=pool_type)
    return pool_out


def glu(input, dim=-1):
    """Gated linear unit (reference: nets.py glu)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    act_b = layers.sigmoid(x=b)
    return layers.elementwise_mul(x=a, y=act_b)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (reference: nets.py:338).
    Pure matmul/softmax chain — XLA fuses it; on TPU this is the flash-
    attention-shaped hot path."""
    if not (len(queries.shape) == len(keys.shape) == len(values.shape) == 3):
        raise ValueError("inputs must be 3-D [batch, seq, dim]")
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys hidden dims must match")
    if keys.shape[1] != values.shape[1]:
        raise ValueError("keys and values seq lens must match")
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")

    def __split_heads(x, num_heads):
        if num_heads == 1:
            return x
        hidden_size = x.shape[-1]
        reshaped = layers.reshape(
            x=x, shape=[x.shape[0], x.shape[1], num_heads,
                        hidden_size // num_heads])
        return layers.transpose(x=reshaped, perm=[0, 2, 1, 3])

    def __combine_heads(x):
        if len(x.shape) == 3:
            return x
        trans = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            x=trans, shape=[trans.shape[0], trans.shape[1],
                            trans.shape[2] * trans.shape[3]])

    q = __split_heads(queries, num_heads)
    k = __split_heads(keys, num_heads)
    v = __split_heads(values, num_heads)

    key_dim_per_head = keys.shape[-1] // num_heads
    scaled_q = layers.scale(x=q, scale=key_dim_per_head ** -0.5)
    product = layers.matmul(x=scaled_q, y=k, transpose_y=True)

    weights = layers.reshape(
        x=product, shape=[-1, product.shape[-1]])
    weights = layers.softmax(weights)
    weights = layers.reshape(x=weights, shape=list(product.shape))
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    return __combine_heads(ctx_multiheads)
