"""In-graph streaming metrics.

Capability parity with the reference's stateful evaluators (reference:
python/paddle/v2/fluid/evaluator.py — Accuracy, ChunkEvaluator;
gserver/evaluators/Evaluator.cpp for the CTC/mAP variants), re-designed
for this runtime rather than transcribed: each metric owns persistable
counter variables that the main program accumulates into **on device**
(one fused add per batch, riding the compiled step), while `reset()`
and `eval()` are **host-side scope operations** — the scope here is a
host dict of device buffers, so zeroing a counter is a store and the
final precision/recall/ratio arithmetic is a handful of scalar divides
that have no business inside an XLA program.  The reference instead
builds dedicated reset/eval sub-programs and clones state vars into
them; that machinery buys nothing on this runtime and is gone.
"""

import numpy as np

from .framework import unique_name
from .layer_helper import LayerHelper
from .initializer import Constant
from ..core.scope import global_scope
from ..core.types import np_dtype
from . import layers

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance", "DetectionMAP",
           "Evaluator"]


class Evaluator:
    """Base: counter plumbing shared by all streaming metrics.

    Subclasses append their per-batch ops at construction time (so the
    counters update as part of the normal training/eval step) and
    implement `_combine(reads)` mapping counter values to the metric.
    """

    def __init__(self, prefix, **kwargs):
        self.helper = LayerHelper(prefix, **kwargs)
        if self.helper.main_program.current_block().idx != 0:
            raise ValueError(
                "streaming metrics accumulate into top-level counters; "
                "construct the evaluator outside any sub-block")
        self.metrics = []   # per-batch metric Variables (fetchable)
        self.states = []    # accumulator Variables (persistable)

    # -- counter plumbing ------------------------------------------------

    def _counter(self, tag, dtype="int32", shape=(1,)):
        """A persistable accumulator ([1]-shaped unless a per-class
        shape is asked for), zero-initialized by the startup program."""
        var = self.helper.create_variable(
            name=unique_name("%s.%s" % (self.helper.name, tag)),
            persistable=True, dtype=dtype, shape=list(shape))
        self.helper.set_variable_initializer(var, Constant(0.0))
        self.states.append(var)
        return var

    def _accumulate(self, counter, amount):
        """counter += amount, on device, as part of the main program."""
        if amount.dtype != counter.dtype:
            amount = layers.cast(amount, dtype=counter.dtype)
        self.helper.append_op(type="sum",
                              inputs={"X": [counter, amount]},
                              outputs={"Out": [counter]})

    def _reads(self, scope):
        """Host values of all counters, in registration order."""
        return [np.asarray(scope.get(v.name)) for v in self.states]

    # -- public API ------------------------------------------------------

    def reset(self, executor, reset_program=None):
        """Zero every counter.  Direct host stores into the scope; the
        `executor`/`reset_program` arguments are accepted for drop-in
        compatibility with the reference signature but no program run
        is needed on this runtime."""
        scope = global_scope()
        for var in self.states:
            scope.set(var.name,
                      np.zeros([int(d) for d in var.shape] or [1],
                               np_dtype(var.dtype)))

    def eval(self, executor, eval_program=None):
        return self._combine(self._reads(global_scope()))

    def _combine(self, reads):
        raise NotImplementedError(type(self).__name__)

    # compat shim for code written against the reference's method name
    def create_state(self, suffix, dtype, shape):
        return self._counter(suffix, dtype=dtype, shape=shape)


def _ratio(num, den):
    return float(num) / float(den) if den else 0.0


class Accuracy(Evaluator):
    """Streaming top-k accuracy: correct/total over every batch since
    the last reset (reference: fluid/evaluator.py Accuracy on top of
    accuracy_op.h)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        self.correct = self._counter("correct")
        self.total = self._counter("total")
        batch_correct = self.helper.create_tmp_variable(
            dtype="int32", stop_gradient=True)
        batch_total = self.helper.create_tmp_variable(
            dtype="int32", stop_gradient=True)
        batch_acc = layers.accuracy(input=input, label=label, k=k,
                                    correct=batch_correct,
                                    total=batch_total)
        self._accumulate(self.correct, batch_correct)
        self._accumulate(self.total, batch_total)
        self.metrics.append(batch_acc)

    def _combine(self, reads):
        correct, total = (r.sum() for r in reads)
        return np.array([_ratio(correct, total)], np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk-level precision/recall/F1 (reference:
    fluid/evaluator.py ChunkEvaluator over chunk_eval_op)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, **kwargs):
        super().__init__("chunk_eval", **kwargs)
        self.num_infer = self._counter("infer_chunks")
        self.num_label = self._counter("label_chunks")
        self.num_correct = self._counter("correct_chunks")
        (precision, recall, f1,
         batch_infer, batch_label, batch_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self._accumulate(self.num_infer, batch_infer)
        self._accumulate(self.num_label, batch_label)
        self._accumulate(self.num_correct, batch_correct)
        self.metrics.extend([precision, recall, f1])

    def _combine(self, reads):
        infer, label, correct = (r.sum() for r in reads)
        precision = _ratio(correct, infer)
        recall = _ratio(correct, label)
        f1 = (2 * precision * recall / (precision + recall)
              if correct else 0.0)
        return (np.array([precision]), np.array([recall]),
                np.array([f1]))


class EditDistance(Evaluator):
    """Streaming edit distance / sequence error rate (reference:
    gserver/evaluators/CTCErrorEvaluator.cpp — total edit distance and
    instance error rate).  `input` are hypothesis id sequences, `label`
    the references."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super().__init__("edit_distance", **kwargs)
        self.total_distance = self._counter("total_distance", "float32")
        self.seq_num = self._counter("seq_num")
        self.wrong_seqs = self._counter("wrong_seqs")
        dist, batch_seqs = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        batch_dist = layers.reduce_sum(input=dist, dim=0, keep_dim=False)
        # distances are >= 0, so sign(d) flags each wrong sequence
        batch_wrong = layers.reduce_sum(
            input=layers.sign(dist), dim=0, keep_dim=False)
        self._accumulate(self.total_distance, batch_dist)
        self._accumulate(self.seq_num, batch_seqs)
        self._accumulate(self.wrong_seqs, batch_wrong)
        self.metrics.append(dist)

    def _combine(self, reads):
        total, n, wrong = (r.sum() for r in reads)
        return (np.array([_ratio(total, n)]),
                np.array([_ratio(wrong, n)]))


class DetectionMAP(Evaluator):
    """Detection mean average precision (reference:
    gserver/evaluators/DetectionMAPEvaluator.cpp).  The detection_map
    op scores each batch; eval() reports the UNWEIGHTED mean of batch
    mAPs (the reference accumulates global per-class TP/FP across the
    pass; the batch mean keeps the evaluator state in-graph and tracks
    the same ranking signal, but differs numerically on uneven
    batches)."""

    def __init__(self, detect_res, label, overlap_threshold=0.5,
                 background_id=0, ap_type="11point",
                 evaluate_difficult=False, **kwargs):
        super().__init__("detection_map", **kwargs)
        self.map_sum = self._counter("map_sum", "float32")
        self.batches = self._counter("batches", "float32")
        batch_map = self.helper.create_tmp_variable(
            dtype="float32", stop_gradient=True)
        self.helper.append_op(
            type="detection_map",
            inputs={"DetectRes": [detect_res], "Label": [label]},
            outputs={"MAP": [batch_map]},
            attrs={"overlap_threshold": float(overlap_threshold),
                   "background_label_id": int(background_id),
                   "ap_type": ap_type,
                   "evaluate_difficult": bool(evaluate_difficult)})
        self._accumulate(self.map_sum, batch_map)
        self._accumulate(
            self.batches,
            layers.fill_constant(shape=[1], dtype="float32", value=1.0))
        self.metrics.append(batch_map)

    def _combine(self, reads):
        map_sum, batches = (r.sum() for r in reads)
        return np.array([_ratio(map_sum, batches)])
