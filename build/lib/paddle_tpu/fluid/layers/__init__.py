"""Layers namespace (reference: python/paddle/v2/fluid/layers/__init__.py)."""

from . import math_op_patch  # applies Variable operator overloading
from .nn import *            # noqa: F401,F403
from .tensor import *        # noqa: F401,F403
from .ops import *           # noqa: F401,F403
from .io import *            # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .device import *        # noqa: F401,F403

from . import nn, tensor, ops, io, control_flow, device

__all__ = []
__all__ += nn.__all__
__all__ += tensor.__all__
__all__ += ops.__all__
__all__ += io.__all__
__all__ += control_flow.__all__
__all__ += device.__all__
