"""Tensor layers (reference: python/paddle/v2/fluid/layers/tensor.py)."""

from ..layer_helper import LayerHelper
from ..framework import Variable, default_main_program, default_startup_program
from ..initializer import Constant

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "reshape",
    "split_lod_tensor", "merge_lod_tensor", "increment",
]


def create_tensor(dtype, name=None, persistable=False, **kwargs):
    helper = LayerHelper("create_tensor", name=name, **kwargs)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, attr=None, is_bias=False,
                     default_initializer=None, **kwargs):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", **kwargs)
    attr = ParamAttr.to_attr(attr)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False, name=None,
                      **kwargs):
    helper = LayerHelper("global_var", name=name, **kwargs)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name)
    helper.set_variable_initializer(var, Constant(value))
    return var


def cast(x, dtype, **kwargs):
    helper = LayerHelper("cast", **kwargs)
    out = helper.create_tmp_variable(dtype, lod_level=x.lod_level)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, **kwargs):
    helper = LayerHelper("concat", **kwargs)
    # feature-axis concat of ragged sequences stays ragged; axis-0
    # concat flattens to dense (sequence_concat is the ragged axis-0 op)
    lod = 0 if axis == 0 else max(getattr(x, "lod_level", 0)
                                  for x in input)
    out = helper.create_tmp_variable(helper.input_dtype, lod_level=lod)
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None, **kwargs):
    helper = LayerHelper("sum", **kwargs)
    if out is None:
        out = helper.create_tmp_variable(helper.input_dtype)
    helper.append_op(type="sum", inputs={"X": input},
                     outputs={"Out": [out]})
    return out


def assign(input, output, **kwargs):
    helper = LayerHelper("assign", **kwargs)
    if isinstance(input, Variable):
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    else:
        import numpy as np

        arr = np.asarray(input)
        helper.append_op(
            type="assign_value", outputs={"Out": [output]},
            attrs={"shape": list(arr.shape), "dtype": str(arr.dtype),
                   "values": arr.reshape(-1).tolist()})
    return output


def fill_constant(shape, dtype, value, out=None, **kwargs):
    helper = LayerHelper("fill_constant", **kwargs)
    if out is None:
        out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        type="fill_constant", outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape], "dtype": dtype,
               "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  **kwargs):
    helper = LayerHelper("fill_constant_batch_size_like", **kwargs)
    out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape], "dtype": dtype,
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype, **kwargs):
    return fill_constant(shape=shape, dtype=dtype, value=1.0, **kwargs)


def zeros(shape, dtype, **kwargs):
    return fill_constant(shape=shape, dtype=dtype, value=0.0, **kwargs)


def reshape(x, shape, act=None, **kwargs):
    helper = LayerHelper("reshape", **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape]})
    if act:
        return _act(helper, out, act)
    return out


def _act(helper, var, act):
    tmp = helper.create_tmp_variable(var.dtype)
    helper.append_op(type=act, inputs={"X": [var]}, outputs={"Out": [tmp]})
    return tmp


def increment(x, value=1.0, in_place=True, **kwargs):
    helper = LayerHelper("increment", **kwargs)
    if in_place:
        out = x
    else:
        out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def split_lod_tensor(input, mask, level=0, **kwargs):
    helper = LayerHelper("split_lod_tensor", **kwargs)
    out_true = helper.create_tmp_variable(input.dtype,
                                          lod_level=input.lod_level)
    out_false = helper.create_tmp_variable(input.dtype,
                                           lod_level=input.lod_level)
    helper.append_op(
        type="split_lod_tensor",
        inputs={"X": [input], "Mask": [mask]},
        outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
        attrs={"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0, **kwargs):
    helper = LayerHelper("merge_lod_tensor", **kwargs)
    out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
    helper.append_op(
        type="merge_lod_tensor",
        inputs={"X": [x], "Mask": [mask], "InTrue": [in_true],
                "InFalse": [in_false]},
        outputs={"Out": [out]}, attrs={"level": level})
    return out
