"""Operator overloading on Variable (reference:
python/paddle/v2/fluid/layers/math_op_patch.py)."""

from ..framework import Variable, unique_name
from ..layer_helper import LayerHelper

__all__ = ["monkey_patch_variable"]


def monkey_patch_variable():
    def unique_tmp_name():
        return unique_name("tmp")

    def safe_get_dtype(var):
        return var.dtype

    def create_tensor(block, value, dtype, shape):
        value = float(value)
        tmp_name = unique_tmp_name()
        var = block.create_var(name=tmp_name, shape=shape, dtype=dtype,
                               stop_gradient=True)
        block.append_op(
            type="fill_constant", outputs={"Out": [var]},
            attrs={"dtype": dtype, "shape": shape, "value": value})
        return var

    def create_scalar(block, value, dtype):
        return create_tensor(block, value, dtype, shape=[1])

    def astype(self, dtype):
        block = self.block
        out = block.create_var(name=unique_tmp_name(), dtype=dtype)
        block.append_op(type="cast", inputs={"X": [self]},
                        outputs={"Out": [out]},
                        attrs={"in_dtype": self.dtype, "out_dtype": dtype})
        return out

    def _elemwise_method_creator_(method_name, op_type, reverse=False):
        def __impl__(self, other_var):
            block = self.block
            dtype = safe_get_dtype(self)
            if not isinstance(other_var, Variable):
                other_var = create_scalar(block, value=other_var,
                                          dtype=dtype)
            lhs, rhs = self, other_var
            if reverse:
                lhs, rhs = rhs, lhs
            out = block.create_var(name=unique_tmp_name(), dtype=dtype,
                                   lod_level=self.lod_level)
            block.append_op(
                type=op_type, inputs={"X": [lhs], "Y": [rhs]},
                outputs={"Out": [out]}, attrs={"axis": -1})
            return out

        __impl__.__name__ = method_name
        return __impl__

    for method, op_type, reverse in (
            ("__add__", "elementwise_add", False),
            ("__radd__", "elementwise_add", False),
            ("__sub__", "elementwise_sub", False),
            ("__rsub__", "elementwise_sub", True),
            ("__mul__", "elementwise_mul", False),
            ("__rmul__", "elementwise_mul", False),
            ("__div__", "elementwise_div", False),
            ("__truediv__", "elementwise_div", False),
            ("__rdiv__", "elementwise_div", True),
            ("__rtruediv__", "elementwise_div", True),
            ("__pow__", "elementwise_pow", False),
            ("__lt__", "less_than", False),
            ("__le__", "less_equal", False),
            ("__gt__", "greater_than", False),
            ("__ge__", "greater_equal", False)):
        setattr(Variable, method,
                _elemwise_method_creator_(method, op_type, reverse))

    Variable.astype = astype


monkey_patch_variable()
