"""Device layers (reference: python/paddle/v2/fluid/layers/device.py —
get_places backed by get_places_op.cc)."""

from ..layer_helper import LayerHelper

__all__ = ["get_places"]


def get_places(device_count=None, device_type=None, **kwargs):
    """Return the device list for data-parallel layout.  On TPU this is
    informational — mesh construction (paddle_tpu.parallel.make_mesh) is
    the real device layout; kept for API parity with parallel_do users."""
    import jax

    helper = LayerHelper("get_places", **kwargs)
    out = helper.create_variable(name=helper.name, dtype="int32")
    devices = jax.devices()
    if device_count is None:
        device_count = len(devices)
    out.device_count = min(device_count, len(devices))
    helper.append_op(type="get_places", outputs={"Out": [out]},
                     attrs={"device_count": device_count,
                            "device_type": device_type or "TPU"})
    return out
