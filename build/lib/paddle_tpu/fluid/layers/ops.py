"""Auto-generated unary/scalar layers.

reference: python/paddle/v2/fluid/layers/ops.py (generated from OpProtos by
layer_function_generator.py) — here generated from the registry's
activation list.
"""

from ..layer_helper import LayerHelper

__act_ops__ = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "hard_shrink", "sqrt", "abs", "ceil", "floor", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "brelu",
    "leaky_relu", "soft_relu", "elu", "relu6", "pow", "stanh",
    "thresholded_relu", "hard_sigmoid", "swish",
]

__other_ops__ = ["mean", "scale", "clip", "clip_by_norm", "sign"]

__all__ = __act_ops__ + ["mean", "scale", "sign"]


def _make_unary(op_type, out_slot="Out"):
    def layer(x=None, **kwargs):
        if x is None:
            x = kwargs.pop("input", None)
        attrs = {k: v for k, v in kwargs.items()
                 if k not in ("name", "main_program", "startup_program")}
        helper = LayerHelper(op_type, name=kwargs.get("name"))
        out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={out_slot: [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    return layer


for _op in __act_ops__ + ["sign"]:
    globals()[_op] = _make_unary(_op)


def mean(x=None, **kwargs):
    if x is None:
        x = kwargs.pop("input")
    helper = LayerHelper("mean", name=kwargs.get("name"))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x=None, scale=1.0, **kwargs):
    if x is None:
        x = kwargs.pop("input")
    helper = LayerHelper("scale", name=kwargs.get("name"))
    out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
    helper.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"scale": scale})
    return out


def _make_elementwise(op_type):
    def layer(x, y, axis=-1, act=None, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        if act is None:
            return out
        tmp = helper.create_tmp_variable(out.dtype, lod_level=out.lod_level)
        helper.append_op(type=act, inputs={"X": [out]},
                         outputs={"Out": [tmp]})
        return tmp

    layer.__name__ = op_type
    return layer


for _op in ("elementwise_add", "elementwise_sub", "elementwise_mul",
            "elementwise_div", "elementwise_max", "elementwise_min",
            "elementwise_pow"):
    globals()[_op] = _make_elementwise(_op)
    __all__.append(_op)
