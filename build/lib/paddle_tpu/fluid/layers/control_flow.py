"""Control-flow layers: While / StaticRNN / DynamicRNN / ConditionalBlock
and tensor-array helpers.

TPU-native equivalents of the reference control-flow DSL
(reference: python/paddle/v2/fluid/layers/control_flow.py — While:602,
StaticRNN:378, DynamicRNN:1252, ConditionalBlock:1065, array_write /
array_read / array_length, less_than, increment).  The sub-blocks these
build are lowered in-trace to lax.while_loop / lax.scan by the ops in
ops/control_flow.py — not interpreted per-iteration like the reference's
nested-Executor design (while_op.cc:48-63).
"""

import contextlib

from ..layer_helper import LayerHelper
from ..framework import Variable, default_main_program, unique_name
from ...core.desc import BlockRef
from ...core.types import VarType

__all__ = [
    "While", "StaticRNN", "DynamicRNN", "ConditionalBlock", "less_than",
    "array_write", "array_read", "array_length", "create_array",
    "max_sequence_len", "lod_rank_table", "lod_tensor_to_array",
    "array_to_lod_tensor", "shrink_memory", "reorder_lod_tensor_by_rank",
    "split_lod_tensor", "merge_lod_tensor", "Print", "IfElse",
    "ParallelDo", "equal",
]


def less_than(x, y, cond=None, **kwargs):
    """reference: control_flow.py less_than, compare_op.cc."""
    helper = LayerHelper("less_than", **kwargs)
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(type="less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def equal(x, y, cond=None, **kwargs):
    """reference: control_flow.py equal, compare_op.cc."""
    helper = LayerHelper("equal", **kwargs)
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def create_array(dtype, capacity=None, **kwargs):
    """reference: control_flow.py create_array (LOD_TENSOR_ARRAY var)."""
    helper = LayerHelper("array", **kwargs)
    arr = helper.create_variable(
        name=unique_name("array"), dtype=dtype,
        type=VarType.TENSOR_ARRAY)
    arr.capacity = capacity
    return arr


def array_write(x, i, array=None, capacity=None, **kwargs):
    """reference: control_flow.py array_write,
    tensor_array_read_write_op.cc."""
    from ...core.tensor_array import DEFAULT_CAPACITY

    helper = LayerHelper("array_write", **kwargs)
    if array is None:
        array = create_array(x.dtype)
    cap = capacity or getattr(array, "capacity", None) or DEFAULT_CAPACITY
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x], "I": [i], "Array": [array]},
        outputs={"Out": [array]},
        attrs={"capacity": int(cap)})
    return array


def array_read(array, i, **kwargs):
    helper = LayerHelper("array_read", **kwargs)
    out = helper.create_tmp_variable(array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array, **kwargs):
    helper = LayerHelper("array_length", **kwargs)
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


def max_sequence_len(rank_table, **kwargs):
    helper = LayerHelper("max_seqence_len", **kwargs)
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


def _block_reads_writes(block):
    """(reads-from-outside, writes) of a built sub-block."""
    produced = set()
    reads, writes = [], []
    for op in block.desc.ops:
        for n in op.input_names():
            if n != "@EMPTY@" and n not in produced and n not in reads:
                reads.append(n)
        for n in op.output_names():
            if n != "@EMPTY@":
                produced.add(n)
                if n not in writes:
                    writes.append(n)
    # names declared in the sub-block itself are internal
    local = set(block.desc.vars.keys())
    outer_reads = [n for n in reads if n not in local or n in writes]
    outer_reads = [n for n in outer_reads
                   if block.parent_block.has_var_recursive(n)]
    return outer_reads, writes


class While:
    """reference: control_flow.py While:602.

    cond must be a bool scalar Variable, re-assigned inside the block.
    `max_steps` bounds the loop and makes it reverse-differentiable
    (lowered to lax.scan instead of lax.while_loop).
    """

    def __init__(self, cond, max_steps=None, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.max_steps = max_steps

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        yield
        program.rollback()

        outer_reads, writes = _block_reads_writes(sub_block)
        cond_name = self.cond_var.name
        # loop state: vars written in the block that live outside it
        carry = [n for n in writes
                 if parent_block.has_var_recursive(n)]
        if cond_name not in carry:
            carry.append(cond_name)
        x_names = list(dict.fromkeys(outer_reads + carry))

        parent_block.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [cond_name]},
            outputs={"Out": list(carry)},
            attrs={"sub_block": BlockRef(sub_block.idx),
                   "x_names": x_names, "carry_names": list(carry),
                   "cond_name": cond_name,
                   "max_steps": self.max_steps},
            infer_shape=False)


class ConditionalBlock:
    """reference: control_flow.py ConditionalBlock:1065."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        for i in inputs:
            assert isinstance(i, Variable)
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        yield
        program.rollback()

        outer_reads, writes = _block_reads_writes(sub_block)
        out_names = [n for n in writes
                     if parent_block.has_var_recursive(n)]
        x_names = list(dict.fromkeys(outer_reads + out_names))

        parent_block.append_op(
            type="conditional_block",
            inputs={"X": x_names,
                    "Cond": [self.inputs[0].name]},
            outputs={"Out": list(out_names)},
            attrs={"sub_block": BlockRef(sub_block.idx),
                   "x_names": x_names, "out_names": list(out_names),
                   "is_scalar_condition": self.is_scalar_condition},
            infer_shape=False)


class StaticRNN:
    """Fixed-length RNN over dense [batch, T, ...] inputs.

    reference: control_flow.py StaticRNN:378 (backed by recurrent_op.cc);
    here the step block becomes one lax.scan body.
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = self.BEFORE_RNN_BLOCK
        self.seq_inputs = []      # (outer Variable [B,T,...], step var)
        self.memories = []        # dicts: boot (outer), pre (step), post
        self.step_outputs = []    # step vars
        self.outputs = []         # outer Variables [B,T,...]
        self.sub_block = None
        self.seq_len = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self.parent_block = program.current_block()
        self.sub_block = program.create_block()
        self.status = self.IN_RNN_BLOCK
        yield
        self.status = self.AFTER_RNN_BLOCK
        program.rollback()
        self._complete()

    def _assert_in_rnn(self):
        if self.status != self.IN_RNN_BLOCK:
            raise ValueError("must be called inside rnn.step()")

    def step_input(self, x):
        """x: [batch, T, ...] dense; returns the per-step [batch, ...]
        view inside the block."""
        self._assert_in_rnn()
        if self.seq_len is None:
            self.seq_len = x.shape[1]
        ipt = self.sub_block.create_var(
            name=unique_name("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype,
            shape=(x.shape[0],) + tuple(x.shape[2:]))
        self.seq_inputs.append((x, ipt))
        return ipt

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               dtype="float32", init_batch_dim_idx=0, ref_batch_dim_idx=0):
        """Loop-carried state.  init: outer Variable with the initial
        value; otherwise zeros of [batch_ref.shape[0]] + shape."""
        self._assert_in_rnn()
        from . import tensor as tensor_layers

        if init is not None and init_batch_dim_idx != 0:
            raise ValueError(
                "init_batch_dim_idx != 0 is not supported: memories are "
                "batch-major ([batch, ...]) in this framework")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            # resolve a step-input ref back to its outer (batch-major)
            # var, whose batch dim is 0; for a direct outer ref honor
            # ref_batch_dim_idx
            outer_ref, ref_dim = batch_ref, ref_batch_dim_idx
            for x, ipt in self.seq_inputs:
                if batch_ref.name == ipt.name:
                    outer_ref, ref_dim = x, 0
                    break
            parent_prog = self.helper.main_program
            cur = parent_prog.current_block_idx
            parent_prog.current_block_idx = self.parent_block.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=outer_ref, shape=[1] + list(shape), value=value,
                    dtype=dtype, input_dim_idx=ref_dim)
            finally:
                parent_prog.current_block_idx = cur
        pre = self.sub_block.create_var(
            name=unique_name("@".join([self.helper.name, "mem"])),
            dtype=init.dtype, shape=init.shape)
        self.memories.append({"boot": init, "pre": pre, "post": None})
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn()
        for m in self.memories:
            if m["pre"].name == mem.name:
                m["post"] = var
                return
        raise ValueError("unknown memory %r" % mem.name)

    def step_output(self, o):
        self._assert_in_rnn()
        self.step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        from . import tensor as tensor_layers

        parent = self.parent_block
        prog = self.helper.main_program
        assert prog.current_block().idx == parent.idx

        for m in self.memories:
            if m["post"] is None:
                raise ValueError("memory never updated; call update_memory")

        # time-major step inputs: [B,T,...] -> [T,B,...]
        tm_inputs = []
        for x, ipt in self.seq_inputs:
            perm = [1, 0] + list(range(2, len(x.shape)))
            tm = self._transpose(x, perm)
            tm_inputs.append((tm, ipt))

        outer_reads, _ = _block_reads_writes(self.sub_block)
        bound = ({ipt.name for _, ipt in self.seq_inputs}
                 | {m["pre"].name for m in self.memories})
        closure_names = [n for n in outer_reads if n not in bound]

        step_out_vars = []
        for so in self.step_outputs:
            v = parent.create_var(
                name=unique_name(self.helper.name + "@out_tm"),
                dtype=so.dtype)
            step_out_vars.append(v)
        final_mem_vars = [
            parent.create_var(name=unique_name(self.helper.name + "@fmem"),
                              dtype=m["boot"].dtype)
            for m in self.memories]

        parent.append_op(
            type="recurrent",
            inputs={
                "StepInputs": [tm.name for tm, _ in tm_inputs],
                "Boot": [m["boot"].name for m in self.memories],
                "Closure": closure_names,
            },
            outputs={"StepOutputs": [v.name for v in step_out_vars],
                     "FinalMems": [v.name for v in final_mem_vars]},
            attrs={
                "sub_block": BlockRef(self.sub_block.idx),
                "step_input_names": [ipt.name for _, ipt in tm_inputs],
                "closure_names": closure_names,
                "mem_pre_names": [m["pre"].name for m in self.memories],
                "mem_post_names": [m["post"].name for m in self.memories],
                "step_output_names": [o.name for o in self.step_outputs],
                "has_mask": False,
            })

        # back to batch-major
        self.outputs = []
        for v, so in zip(step_out_vars, self.step_outputs):
            ndim = len(so.shape) + 1
            perm = [1, 0] + list(range(2, ndim))
            self.outputs.append(self._transpose(v, perm))
        self.final_memories = final_mem_vars

    def _transpose(self, x, perm):
        helper = self.helper
        out = helper.main_program.current_block().create_var(
            name=unique_name(helper.name + "@t"), dtype=x.dtype)
        helper.main_program.current_block().append_op(
            type="transpose", inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"axis": list(perm)})
        return out

    def __call__(self, *args, **kwargs):
        if self.status != self.AFTER_RNN_BLOCK:
            raise ValueError("rnn() must be called after the step block")
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs


class DynamicRNN(StaticRNN):
    """Variable-length RNN over RaggedTensor (LoD) inputs.

    reference: control_flow.py DynamicRNN:1252 — there it expands to
    lod_rank_table + while + memory-shrinking; here ragged input is
    padded to [B, maxT, D] with a mask and runs the same scan engine
    with masked memory carries (states freeze past each sequence's end,
    outputs ragged again).
    """

    def __init__(self, name=None):
        StaticRNN.__init__(self, name=name)
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._ragged_like = None
        self._mask_var = None

    def block(self):
        return self.step()

    def step_input(self, x):
        """x: RaggedTensor Variable (lod_level 1); returns per-step
        [batch, D] view."""
        self._assert_in_rnn()
        if x.lod_level == 0:
            return StaticRNN.step_input(self, x)
        parent_prog = self.helper.main_program
        cur = parent_prog.current_block_idx
        parent_prog.current_block_idx = self.parent_block.idx
        try:
            padded, mask = _sequence_to_dense(self.helper, x)
            if self._ragged_like is None:
                self._ragged_like = x
                self._mask_var = mask
        finally:
            parent_prog.current_block_idx = cur
        ipt = self.sub_block.create_var(
            name=unique_name("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype, shape=(-1,) + tuple(x.shape[1:]))
        self.seq_inputs.append((padded, ipt))
        return ipt

    def _complete(self):
        # same as StaticRNN but with the validity mask and ragged output
        parent = self.parent_block

        for m in self.memories:
            if m["post"] is None:
                raise ValueError("memory never updated; call update_memory")

        tm_inputs = []
        for x, ipt in self.seq_inputs:
            perm = [1, 0] + list(range(2, len(x.shape)))
            tm_inputs.append((self._transpose(x, perm), ipt))
        mask_tm = None
        if self._mask_var is not None:
            mask_tm = self._transpose(self._mask_var, [1, 0])

        outer_reads, _ = _block_reads_writes(self.sub_block)
        bound = ({ipt.name for _, ipt in self.seq_inputs}
                 | {m["pre"].name for m in self.memories})
        closure_names = [n for n in outer_reads if n not in bound]

        step_out_vars = [
            parent.create_var(name=unique_name(self.helper.name + "@out_tm"),
                              dtype=so.dtype)
            for so in self.step_outputs]
        final_mem_vars = [
            parent.create_var(name=unique_name(self.helper.name + "@fmem"),
                              dtype=m["boot"].dtype)
            for m in self.memories]

        inputs = {
            "StepInputs": [tm.name for tm, _ in tm_inputs],
            "Boot": [m["boot"].name for m in self.memories],
            "Closure": closure_names,
        }
        if mask_tm is not None:
            inputs["Mask"] = [mask_tm.name]
        parent.append_op(
            type="recurrent", inputs=inputs,
            outputs={"StepOutputs": [v.name for v in step_out_vars],
                     "FinalMems": [v.name for v in final_mem_vars]},
            attrs={
                "sub_block": BlockRef(self.sub_block.idx),
                "step_input_names": [ipt.name for _, ipt in tm_inputs],
                "closure_names": closure_names,
                "mem_pre_names": [m["pre"].name for m in self.memories],
                "mem_post_names": [m["post"].name for m in self.memories],
                "step_output_names": [o.name for o in self.step_outputs],
                "has_mask": mask_tm is not None,
            })

        self.outputs = []
        for v, so in zip(step_out_vars, self.step_outputs):
            ndim = len(so.shape) + 1
            perm = [1, 0] + list(range(2, ndim))
            bm = self._transpose(v, perm)          # [B, T, ...]
            if self._ragged_like is not None:
                rag = _dense_to_sequence(self.helper, bm,
                                         self._ragged_like)
                self.outputs.append(rag)
            else:
                self.outputs.append(bm)
        self.final_memories = final_mem_vars


def _sequence_to_dense(helper, x):
    block = helper.main_program.current_block()
    padded = block.create_var(name=unique_name(helper.name + "@padded"),
                              dtype=x.dtype)
    mask = block.create_var(name=unique_name(helper.name + "@mask"),
                            dtype="float32")
    mask.stop_gradient = True
    block.append_op(
        type="sequence_to_dense", inputs={"X": [x]},
        outputs={"Out": [padded], "Mask": [mask]})
    return padded, mask


def _dense_to_sequence(helper, x, like):
    block = helper.main_program.current_block()
    out = block.create_var(name=unique_name(helper.name + "@ragged"),
                           dtype=x.dtype, lod_level=like.lod_level)
    block.append_op(
        type="dense_to_sequence", inputs={"X": [x], "Like": [like]},
        outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# LoD rank-table layer plumbing (reference: control_flow.py
# lod_rank_table:790s, lod_tensor_to_array, array_to_lod_tensor,
# shrink_memory, reorder_lod_tensor_by_rank; ops in
# ops/control_flow.py keep host semantics like the reference's CPU-only
# kernels)
# ---------------------------------------------------------------------------

def lod_rank_table(x, level=0, **kwargs):
    helper = LayerHelper("lod_rank_table", **kwargs)
    table = helper.create_variable(
        name=unique_name("lod_rank_table.tmp"), dtype="int32",
        type=VarType.RAW, stop_gradient=True)
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]},
                     attrs={"level": level}, infer_shape=False)
    return table


def lod_tensor_to_array(x, table, **kwargs):
    helper = LayerHelper("lod_tensor_to_array", **kwargs)
    array = helper.create_variable(
        name=unique_name("lod_tensor_to_array.tmp"), dtype=x.dtype,
        type=VarType.TENSOR_ARRAY, stop_gradient=True)
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]}, infer_shape=False)
    return array


def array_to_lod_tensor(x, table, **kwargs):
    helper = LayerHelper("array_to_lod_tensor", **kwargs)
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=1)
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def shrink_memory(x, i, table, **kwargs):
    helper = LayerHelper("shrink_memory", **kwargs)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def reorder_lod_tensor_by_rank(x, rank_table, **kwargs):
    helper = LayerHelper("reorder_lod_tensor_by_rank", **kwargs)
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=1)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def split_lod_tensor(input, mask, level=0, **kwargs):
    helper = LayerHelper("split_lod_tensor", **kwargs)
    out_true = helper.create_tmp_variable(dtype=input.dtype,
                                          lod_level=input.lod_level)
    out_false = helper.create_tmp_variable(dtype=input.dtype,
                                           lod_level=input.lod_level)
    helper.append_op(type="split_lod_tensor",
                     inputs={"X": [input], "Mask": [mask]},
                     outputs={"OutTrue": [out_true],
                              "OutFalse": [out_false]},
                     attrs={"level": level}, infer_shape=False)
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0, **kwargs):
    helper = LayerHelper("merge_lod_tensor", **kwargs)
    out = helper.create_tmp_variable(dtype=in_true.dtype,
                                     lod_level=x.lod_level)
    helper.append_op(type="merge_lod_tensor",
                     inputs={"X": [x], "Mask": [mask],
                             "InTrue": [in_true], "InFalse": [in_false]},
                     outputs={"Out": [out]},
                     attrs={"level": level}, infer_shape=False)
    return out


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both", **kwargs):
    """reference: the print operator (print_op.cc) — debug-print a
    tensor as it flows; forwards its input unchanged."""
    helper = LayerHelper("print", **kwargs)
    out = helper.create_tmp_variable(dtype=input.dtype,
                                     lod_level=input.lod_level)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"first_n": first_n,
                            "message": message or "",
                            "summarize": summarize,
                            "print_tensor_name": print_tensor_name,
                            "print_phase": print_phase},
                     infer_shape=False)
    return out


class IfElse:
    """Row-routed two-branch execution (reference: control_flow.py
    IfElse:~900 over split_lod_tensor / conditional blocks /
    merge_lod_tensor): rows where cond holds flow through the
    true_block, the rest through the false_block, outputs merge back in
    input order."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.status = self.OUT_IF_ELSE_BLOCKS
        self._true_inputs = {}
        self._false_inputs = {}
        self._true_outputs = []
        self._false_outputs = []

    def input(self, x):
        if self.status == self.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be called inside a block")
        true_part, false_part = split_lod_tensor(x, self.cond)
        self._true_inputs[x.name] = true_part
        self._false_inputs[x.name] = false_part
        return (true_part if self.status == self.IN_IF_ELSE_TRUE_BLOCKS
                else false_part)

    @contextlib.contextmanager
    def true_block(self):
        self.status = self.IN_IF_ELSE_TRUE_BLOCKS
        yield
        self.status = self.OUT_IF_ELSE_BLOCKS

    @contextlib.contextmanager
    def false_block(self):
        self.status = self.IN_IF_ELSE_FALSE_BLOCKS
        yield
        self.status = self.OUT_IF_ELSE_BLOCKS

    def output(self, *outs):
        if self.status == self.IN_IF_ELSE_TRUE_BLOCKS:
            self._true_outputs.extend(outs)
        elif self.status == self.IN_IF_ELSE_FALSE_BLOCKS:
            self._false_outputs.extend(outs)
        else:
            raise ValueError("output() must be called inside a block")

    def __call__(self):
        if len(self._true_outputs) != len(self._false_outputs):
            raise ValueError("true/false blocks must produce the same "
                             "number of outputs")
        merged = []
        # any split input serves as the row-order template
        template = next(iter(self._true_inputs))
        prog_var = self.helper.main_program.current_block().var(template)
        for t, f in zip(self._true_outputs, self._false_outputs):
            merged.append(merge_lod_tensor(t, f, prog_var, self.cond))
        return merged if len(merged) > 1 else merged[0]


class ParallelDo:
    """API-compat data-parallel block (reference: control_flow.py
    ParallelDo:230 over parallel_do_op.cc — splits the batch across
    places and averages gradients via NCCL).  On TPU, batch-splitting
    is expressed declaratively: the whole program runs SPMD over a
    Mesh (paddle_tpu.parallel.ParallelTrainer shards the batch over
    the 'dp' axis and XLA inserts the gradient psum over ICI), so this
    wrapper executes its block once on the global batch — numerically
    identical to the reference's split-and-average."""

    def __init__(self, places, name=None):
        self.places = places
        self._ins = []
        self._outs = []

    @contextlib.contextmanager
    def do(self):
        yield

    def read_input(self, var):
        self._ins.append(var)
        return var

    def write_output(self, var):
        self._outs.append(var)

    def __call__(self):
        return self._outs if len(self._outs) != 1 else self._outs[0]
