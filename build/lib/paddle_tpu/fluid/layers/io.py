"""Data layers (reference: python/paddle/v2/fluid/layers/io.py)."""

from ..layer_helper import LayerHelper
from ..framework import default_main_program, default_startup_program
from ...core.types import VarType

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarType.DENSE_TENSOR, stop_gradient=True, **kwargs):
    """Declare a feed variable (reference: layers/io.py data).  With
    append_batch_size the leading dim is dynamic (-1): the executor
    re-specializes the compiled block per distinct feed shape, so readers
    should produce fixed-size (or bucketed) batches to bound compilations.
    """
    helper = LayerHelper("data", name=name, **kwargs)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape

    return helper.create_global_variable(
        name=name, shape=shape, dtype=dtype, type=type,
        stop_gradient=stop_gradient, lod_level=lod_level)
