"""Python side of the C inference API (consumed by native/capi.cc).

reference: paddle/capi — the C deployment path loads a trained model
and runs forward-only; here CEngine wraps load_inference_model and a
cached compiled executor run.
"""

import numpy as np

__all__ = ["CEngine"]


class CEngine:
    def __init__(self, model_dir):
        import paddle_tpu.fluid as fluid

        self._fluid = fluid
        self._exe = fluid.Executor(fluid.CPUPlace())
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            model_dir, self._exe)
        self._program = program
        self._feed_names = feed_names
        self._fetch_vars = fetch_vars

    def run(self, arr):
        outs = self._exe.run(self._program,
                             feed={self._feed_names[0]: arr},
                             fetch_list=list(self._fetch_vars))
        return np.asarray(outs[0])

    def run_raw(self, data, shape):
        """bytes + shape tuple -> (bytes, shape tuple); float32 only
        (the C API's plain-buffer contract)."""
        arr = np.frombuffer(data, np.float32).reshape(shape)
        out = self.run(arr).astype(np.float32)
        return out.tobytes(), tuple(int(d) for d in out.shape)
