"""Functional program export: turn a Program into a pure jittable function.

This is the TPU-native counterpart of handing a compiled inference/training
graph to callers (reference: paddle/inference/inference.h:23 InferenceEngine
runs a loaded ProgramDesc; paddle/framework/executor.cc:79 interprets it).
Here the whole block becomes ONE pure function of (state, feeds, rng) so it
can be jax.jit-ed, pjit-ed over a Mesh, differentiated, or exported.

The function is closed over the program structure only — parameters and
other persistable state flow through the `state` dict argument, so the
caller owns placement/sharding of every buffer.
"""

import jax

from .fluid.executor import ExecContext, apply_op, RNG_STATE_NAME

__all__ = ["FunctionalProgram", "functionalize", "state_from_scope",
           "state_to_scope"]


class FunctionalProgram:
    """A Program block as a pure function.

    __call__(state, feeds, rng=None) -> (fetches, new_state)
      state:   dict name -> array for every persistable var the block reads
               (parameters, BN moving stats, optimizer accumulators)
      feeds:   dict feed name -> array
      fetches: list of arrays in fetch_names order
      new_state: dict with the same keys as `state` (updated persistables)
    """

    def __init__(self, program, feed_names, fetch_names, block_idx=0):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.block_idx = block_idx

        block_desc = program.desc.block(block_idx)
        self.ops = list(block_desc.ops)

        # persistable vars: anything marked persistable in any block var
        # table reachable from this block
        persist = set()
        bd = block_desc
        prog_desc = program.desc
        while True:
            for name, vd in bd.vars.items():
                if vd.persistable:
                    persist.add(name)
            if bd.parent_idx < 0:
                break
            bd = prog_desc.block(bd.parent_idx)

        reads, writes = set(), set()
        produced = set(self.feed_names)
        for od in self.ops:
            for n in od.input_names():
                if n != "@EMPTY@" and n not in produced:
                    reads.add(n)
            for n in od.output_names():
                if n != "@EMPTY@":
                    produced.add(n)
                    writes.add(n)
        # state the function needs in: persistable reads; state out:
        # persistable writes (e.g. BN moving stats, optimizer updates)
        self.state_in_names = sorted(persist & reads)
        self.state_out_names = sorted(persist & writes)

    def __call__(self, state, feeds, rng=None):
        env = dict(state)
        env.update(feeds)
        # rng rides the state dict (RNG_STATE_NAME) so stochastic ops
        # (dropout, sampling) stay pure: the advanced key is returned
        # in new_state and feeds the next step
        if rng is None:
            rng = env.pop(RNG_STATE_NAME, None)
        ctx = ExecContext(None, self.program, self.block_idx, env, rng=rng)
        for od in self.ops:
            apply_op(ctx, od)
        new_state = dict(state)
        for n in self.state_out_names:
            if n in env:
                new_state[n] = env[n]
        # only round-trip the key when the caller put it in state —
        # explicit rng= callers (ParallelTrainer) keep the state
        # structure unchanged for their sharding specs
        if ctx.rng is not None and RNG_STATE_NAME in state:
            new_state[RNG_STATE_NAME] = ctx.rng
        fetches = [env[n] for n in self.fetch_names]
        return fetches, new_state


def functionalize(program, feed_names, fetch_names, block_idx=0):
    return FunctionalProgram(program, feed_names, fetch_names, block_idx)


def state_from_scope(fp, scope=None):
    """Collect the initial state dict for a FunctionalProgram from a Scope
    (after the startup program ran)."""
    from .core.scope import global_scope

    scope = scope or global_scope()
    state = {}
    for n in set(fp.state_in_names) | set(fp.state_out_names):
        v = scope.get(n)
        if v is not None:
            state[n] = v
    return state


def state_to_scope(state, scope=None):
    from .core.scope import global_scope

    scope = scope or global_scope()
    for n, v in state.items():
        if n != RNG_STATE_NAME:
            scope.set(n, v)
