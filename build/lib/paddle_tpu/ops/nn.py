"""Misc NN op kernels: lstm_unit, nce, bilinear, conv_shift, etc.

TPU-native equivalents of reference ops (paddle/operators/lstm_unit_op.cc,
nce_op.cc, bilinear_tensor_product_op.cc, conv_shift_op.cc).
"""

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("lstm_unit")
def lstm_unit(ctx, ins, attrs):
    """One fused LSTM cell step (reference: lstm_unit_op.cc): X holds the
    four pre-activation gates [i f o g] concatenated."""
    x = ins["X"][0]            # [N, 4D]
    c_prev = ins["C_prev"][0]  # [N, D]
    forget_bias = attrs.get("forget_bias", 0.0)
    d = c_prev.shape[1]
    i, f, o, g = (x[:, :d], x[:, d:2 * d], x[:, 2 * d:3 * d], x[:, 3 * d:])
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op("nce", uses_rng=True, nondiff_inputs=("Label",))
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation loss (reference: nce_op.cc/.h).
    Negative samples are drawn uniformly, matching the reference's
    Sampler default."""
    x = ins["Input"][0]            # [N, D]
    label = ins["Label"][0]        # [N, num_true]
    w = ins["Weight"][0]           # [C, D]
    b = ins["Bias"][0] if "Bias" in ins else None  # [C, 1]
    num_classes = int(attrs["num_total_classes"])
    num_neg = int(attrs.get("num_neg_samples", 10))
    n = x.shape[0]
    label = jnp.reshape(label, (n, -1)).astype(jnp.int32)
    num_true = label.shape[1]

    key = ctx.next_rng()
    neg = jax.random.randint(key, (n, num_neg), 0, num_classes)
    samples = jnp.concatenate([label, neg], axis=1)  # [N, T+S]

    sw = jnp.take(w, samples.reshape(-1), axis=0) \
        .reshape(n, -1, w.shape[1])                  # [N, T+S, D]
    logits = jnp.einsum("nd,nsd->ns", x, sw)
    if b is not None:
        logits = logits + jnp.take(jnp.reshape(b, (-1,)),
                                   samples.reshape(-1)).reshape(n, -1)
    p_model = jax.nn.sigmoid(logits)
    p_noise = 1.0 / num_classes
    # true part
    true_p = p_model[:, :num_true]
    true_cost = -jnp.log(true_p / (true_p + num_true * p_noise) + 1e-12)
    neg_p = p_model[:, num_true:]
    neg_cost = -jnp.log(num_true * p_noise /
                        (neg_p + num_true * p_noise) + 1e-12)
    cost = jnp.sum(true_cost, 1, keepdims=True) + \
        jnp.sum(neg_cost, 1, keepdims=True)
    return {"Cost": [cost], "SampleLogits": [logits],
            "SampleLabels": [samples]}


from .registry import register_grad_kernel


@register_grad_kernel("nce")
def nce_grad(ctx, ins, attrs):
    """Replays the saved samples (SampleLabels) instead of the RNG."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    b = ins["Bias"][0] if "Bias" in ins else None
    samples = ins["O@SampleLabels"][0]
    og = ins["OG@Cost"][0]
    num_classes = int(attrs["num_total_classes"])
    label = ins["Label"][0]
    n = x.shape[0]
    num_true = jnp.reshape(label, (n, -1)).shape[1]

    def f(x_, w_, b_):
        sw = jnp.take(w_, samples.reshape(-1), axis=0) \
            .reshape(n, -1, w_.shape[1])
        logits = jnp.einsum("nd,nsd->ns", x_, sw)
        if b_ is not None:
            logits = logits + jnp.take(jnp.reshape(b_, (-1,)),
                                       samples.reshape(-1)).reshape(n, -1)
        p_model = jax.nn.sigmoid(logits)
        p_noise = 1.0 / num_classes
        true_p = p_model[:, :num_true]
        true_cost = -jnp.log(true_p / (true_p + num_true * p_noise)
                             + 1e-12)
        neg_p = p_model[:, num_true:]
        neg_cost = -jnp.log(num_true * p_noise /
                            (neg_p + num_true * p_noise) + 1e-12)
        return jnp.sum(true_cost, 1, keepdims=True) + \
            jnp.sum(neg_cost, 1, keepdims=True)

    if b is not None:
        _, vjp = jax.vjp(f, x, w, b)
        dx, dw, db = vjp(og)
        return {"Input@GRAD": [dx], "Weight@GRAD": [dw],
                "Bias@GRAD": [db]}
    _, vjp = jax.vjp(lambda x_, w_: f(x_, w_, None), x, w)
    dx, dw = vjp(og)
    return {"Input@GRAD": [dx], "Weight@GRAD": [dw]}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx, ins, attrs):
    """out_k = x W_k y^T + b (reference: bilinear_tensor_product_op.cc)."""
    x = ins["X"][0]  # [N, Dx]
    y = ins["Y"][0]  # [N, Dy]
    w = ins["Weight"][0]  # [K, Dx, Dy]
    out = jnp.einsum("nd,kde,ne->nk", x, w, y)
    if "Bias" in ins:
        out = out + jnp.reshape(ins["Bias"][0], (1, -1))
    return {"Out": [out]}


@register_op("conv_shift")
def conv_shift(ctx, ins, attrs):
    """Circular correlation (reference: conv_shift_op.cc)."""
    x = ins["X"][0]  # [N, D]
    y = ins["Y"][0]  # [N, M], M odd, M <= D
    n, d = x.shape
    m = y.shape[1]
    half = m // 2
    idx = (jnp.arange(d)[:, None] + jnp.arange(-half, half + 1)[None, :]) % d
    # out[i,j] = sum_k x[i, (j+k-half)%d] * y[i,k]
    gathered = x[:, idx]             # [N, D, M]
    out = jnp.einsum("ndm,nm->nd", gathered, y)
    return {"Out": [out]}


@register_op("cast_embedding_ids", stop_gradient_op=True)
def cast_embedding_ids(ctx, ins, attrs):
    # helper op (not in reference): int cast for id tensors
    x = ins["X"][0]
    return {"Out": [x.astype(jnp.int32)]}
