"""CTC ops: warpctc loss, ctc_align (greedy decode), edit_distance,
sequence_erase.

TPU-native equivalents of the reference CTC family (reference:
paddle/operators/warpctc_op.cc — dlopen'ed libwarpctc; ctc_align_op.cc;
edit_distance_op.cc; sequence_erase_op.cc).

Design departures:
  * warpctc is a native XLA lowering: log-space alpha recursion over the
    extended label sequence as a masked lax.scan on a padded batch — no
    external library, and gradients come from jax.vjp of the forward (the
    reference reuses warpctc's internal gradient via the WarpCTCGrad
    workspace output, warpctc_op.h).
  * ctc_align / edit_distance / sequence_erase produce dynamically-sized
    sequences, so they are host ops (the reference's versions are also
    trivially small); they are eval/data-path, never inside a jitted
    training step.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..core.ragged import RaggedTensor
from .sequence import ragged_to_padded

NEG_INF = -1e30


@register_op("warpctc", nondiff_inputs=("Label",))
def warpctc(ctx, ins, attrs):
    logits = ins["Logits"][0]    # ragged [T, C]
    label = ins["Label"][0]      # ragged [L, 1] int
    blank = int(attrs.get("blank", 0))
    norm_by_times = bool(attrs.get("norm_by_times", False))

    lg_pad, t_lens = ragged_to_padded(logits)        # [B, Tmax, C]
    lb = label.with_values(label.values.reshape(-1, 1).astype(jnp.int32))
    lb_pad, l_lens = ragged_to_padded(lb)            # [B, Lmax, 1]
    lb_pad = lb_pad[:, :, 0]
    B, Tmax, C = lg_pad.shape
    Lmax = lb_pad.shape[1]
    S = 2 * Lmax + 1

    logp = jax.nn.log_softmax(lg_pad, axis=-1)

    # extended label sequence: blank, l1, blank, l2, ..., blank
    s_idx = jnp.arange(S)
    is_lbl = (s_idx % 2) == 1
    lbl_pos = jnp.clip(s_idx // 2, 0, Lmax - 1)
    ext = jnp.where(is_lbl[None, :], lb_pad[:, lbl_pos], blank)  # [B, S]
    # valid extended positions: s < 2*L_b + 1
    s_valid = s_idx[None, :] < (2 * l_lens[:, None] + 1)
    # skip transition allowed: ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, ext.dtype),
                              ext[:, :-2]], axis=1)
    can_skip = is_lbl[None, :] & (ext != ext_m2)

    def gather_logp(lp_t):
        return jnp.take_along_axis(lp_t, ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(l_lens > 0, gather_logp(logp[:, 0])[:, 1], NEG_INF))
    alpha0 = jnp.where(s_valid, alpha0, NEG_INF)

    t_range = jnp.arange(Tmax)
    active = t_range[None, :] < t_lens[:, None]      # [B, Tmax]

    def step(alpha, inputs):
        lp_t, act = inputs
        a_m1 = jnp.concatenate(
            [jnp.full((B, 1), NEG_INF), alpha[:, :-1]], axis=1)
        a_m2 = jnp.concatenate(
            [jnp.full((B, 2), NEG_INF), alpha[:, :-2]], axis=1)
        a_m2 = jnp.where(can_skip, a_m2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a_m1), a_m2)
        new = merged + gather_logp(lp_t)
        new = jnp.where(s_valid, new, NEG_INF)
        alpha = jnp.where(act[:, None], new, alpha)
        return alpha, None

    alpha_last, _ = lax.scan(
        step, alpha0,
        (jnp.swapaxes(logp, 0, 1)[1:], jnp.swapaxes(active, 0, 1)[1:]))

    # final: logsumexp of states 2L and 2L-1
    end1 = 2 * l_lens               # final blank
    end2 = jnp.maximum(2 * l_lens - 1, 0)  # final label
    a_end1 = jnp.take_along_axis(alpha_last, end1[:, None], axis=1)[:, 0]
    a_end2 = jnp.take_along_axis(alpha_last, end2[:, None], axis=1)[:, 0]
    a_end2 = jnp.where(l_lens > 0, a_end2, NEG_INF)
    loss = -jnp.logaddexp(a_end1, a_end2)
    if norm_by_times:
        loss = loss / jnp.maximum(t_lens, 1).astype(loss.dtype)
    return {"Loss": [loss.reshape(-1, 1)],
            "WarpCTCGrad": [logits.with_values(
                jnp.zeros_like(logits.values))]}


@register_op("ctc_align", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Input",))
def ctc_align(ctx, ins, attrs):
    """Greedy CTC decode: merge repeated tokens then drop blanks
    (reference: ctc_align_op.h)."""
    x = ins["Input"][0]
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    splits = np.asarray(x.last_splits())
    vals = np.asarray(x.values).reshape(-1)

    out_vals = []
    out_splits = [0]
    for s in range(len(splits) - 1):
        seq = vals[int(splits[s]):int(splits[s + 1])]
        if merge and len(seq):
            keep = np.ones(len(seq), bool)
            keep[1:] = seq[1:] != seq[:-1]
            seq = seq[keep]
        seq = seq[seq != blank]
        out_vals.extend(seq.tolist())
        out_splits.append(len(out_vals))
    out = np.asarray(out_vals, np.int32).reshape(-1, 1)
    if out.size == 0:
        out = np.zeros((0, 1), np.int32)
    return {"Output": [RaggedTensor(jnp.asarray(out),
                                    [np.asarray(out_splits, np.int64)])]}


def _levenshtein(hyp, ref):
    m, n = len(hyp), len(ref)
    if m == 0:
        return n
    if n == 0:
        return m
    prev = np.arange(n + 1, dtype=np.int64)
    for i in range(1, m + 1):
        cur = np.empty(n + 1, np.int64)
        cur[0] = i
        for j in range(1, n + 1):
            cost = 0 if hyp[i - 1] == ref[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        prev = cur
    return int(prev[n])


@register_op("edit_distance", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Hyps", "Refs"))
def edit_distance(ctx, ins, attrs):
    hyps = ins["Hyps"][0]
    refs = ins["Refs"][0]
    normalized = bool(attrs.get("normalized", False))
    ignored = set(attrs.get("ignored_tokens") or [])

    h_splits = np.asarray(hyps.last_splits())
    r_splits = np.asarray(refs.last_splits())
    hv = np.asarray(hyps.values).reshape(-1)
    rv = np.asarray(refs.values).reshape(-1)
    B = len(h_splits) - 1
    out = np.zeros((B, 1), np.float32)
    for s in range(B):
        h = [t for t in hv[int(h_splits[s]):int(h_splits[s + 1])].tolist()
             if t not in ignored]
        r = [t for t in rv[int(r_splits[s]):int(r_splits[s + 1])].tolist()
             if t not in ignored]
        d = _levenshtein(h, r)
        if normalized:
            d = d / max(len(r), 1)
        out[s, 0] = d
    return {"Out": [out],
            "SequenceNum": [np.asarray([B], np.int32)]}


@register_op("sequence_erase", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("X",))
def sequence_erase(ctx, ins, attrs):
    """Remove given tokens from each sequence (reference:
    sequence_erase_op.cc)."""
    x = ins["X"][0]
    tokens = set(attrs.get("tokens") or [])
    splits = np.asarray(x.last_splits())
    vals = np.asarray(x.values).reshape(-1)
    out_vals = []
    out_splits = [0]
    for s in range(len(splits) - 1):
        seq = [t for t in vals[int(splits[s]):int(splits[s + 1])].tolist()
               if t not in tokens]
        out_vals.extend(seq)
        out_splits.append(len(out_vals))
    out = np.asarray(out_vals, np.asarray(x.values).dtype).reshape(-1, 1)
    if out.size == 0:
        out = np.zeros((0, 1), np.asarray(x.values).dtype)
    return {"Out": [RaggedTensor(jnp.asarray(out),
                                 [np.asarray(out_splits, np.int64)])]}
