"""Loss op kernels.

TPU-native equivalents of reference loss ops (paddle/operators/
cross_entropy_op.cc, softmax_with_cross_entropy_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, hinge_loss_op.cc,
huber_loss_op.cc, log_loss_op.cc, margin_rank_loss_op.cc,
modified_huber_loss_op.cc, rank_loss_op.cc, smooth_l1_loss_op.cc).
"""

import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import RaggedTensor


def _vals(v):
    x = v.values if isinstance(v, RaggedTensor) else v
    # losses always compute/accumulate in f32: bf16 activations
    # (FLAGS_amp_bf16_act) upcast at the loss boundary -- e.g. log_loss's
    # 1e-4 epsilon would be absorbed entirely by bf16 rounding near p=1
    if x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    return x


def _label_1d(label):
    l = _vals(label)
    if l.ndim > 1:
        l = jnp.reshape(l, (-1,))
    return l.astype(jnp.int32)


@register_op("cross_entropy", nondiff_inputs=("Label",))
def cross_entropy(ctx, ins, attrs):
    xr = ins["X"][0]
    x = _vals(xr)
    label = ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        l = _vals(label)
        out = -jnp.sum(l * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        l = _label_1d(label)
        picked = jnp.take_along_axis(x, l[:, None], axis=-1)
        out = -jnp.log(picked + eps)
    if isinstance(xr, RaggedTensor):
        return {"Y": [xr.with_values(out)]}
    return {"Y": [out]}


@register_op("softmax_with_cross_entropy", nondiff_inputs=("Label",))
def softmax_with_cross_entropy(ctx, ins, attrs):
    logits = _vals(ins["Logits"][0])
    label = ins["Label"][0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        l = _vals(label)
        loss = -jnp.sum(l * logp, axis=-1, keepdims=True)
    else:
        l = _label_1d(label)
        loss = -jnp.take_along_axis(logp, l[:, None], axis=-1)
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x = _vals(ins["X"][0])
    label = _vals(ins["Label"][0]).astype(x.dtype)
    # stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register_op("hinge_loss")
def hinge_loss(ctx, ins, attrs):
    logits = _vals(ins["Logits"][0])
    labels = _vals(ins["Labels"][0]).astype(logits.dtype)
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)]}


@register_op("huber_loss")
def huber_loss(ctx, ins, attrs):
    x = _vals(ins["X"][0])
    y = _vals(ins["Y"][0])
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    return {"Residual": [r], "Out": [loss]}


@register_op("log_loss")
def log_loss(ctx, ins, attrs):
    p = _vals(ins["Predicted"][0])
    l = _vals(ins["Labels"][0]).astype(p.dtype)
    eps = attrs.get("epsilon", 1e-4)
    loss = -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register_op("rank_loss")
def rank_loss(ctx, ins, attrs):
    label = _vals(ins["Label"][0])
    left = _vals(ins["Left"][0])
    right = _vals(ins["Right"][0])
    d = left - right
    loss = jnp.log1p(jnp.exp(d)) - label * d
    return {"Out": [loss]}


@register_op("margin_rank_loss")
def margin_rank_loss(ctx, ins, attrs):
    label = _vals(ins["Label"][0])
    x1 = _vals(ins["X1"][0])
    x2 = _vals(ins["X2"][0])
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    act = (out > 0).astype(x1.dtype)
    return {"Out": [out], "Activated": [act]}


@register_op("modified_huber_loss")
def modified_huber_loss(ctx, ins, attrs):
    x = _vals(ins["X"][0])
    y = _vals(ins["Y"][0])
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    return {"IntermediateVal": [z], "Out": [loss]}


@register_op("smooth_l1_loss")
def smooth_l1_loss(ctx, ins, attrs):
    x = _vals(ins["X"][0])
    y = _vals(ins["Y"][0])
    sigma = attrs.get("sigma", 1.0)
    sigma2 = sigma * sigma
    d = x - y
    if "InsideWeight" in ins:
        d = d * _vals(ins["InsideWeight"][0])
    ad = jnp.abs(d)
    val = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * d * d,
                    ad - 0.5 / sigma2)
    if "OutsideWeight" in ins:
        val = val * _vals(ins["OutsideWeight"][0])
    out = jnp.sum(val, axis=tuple(range(1, val.ndim)))
    return {"Diff": [d], "Out": [jnp.reshape(out, (-1, 1))]}
