"""Activation op kernels.

TPU-native equivalents of the reference activation catalogue
(paddle/operators/activation_op.cc — the full list of 20+ unary
activations, each with a hand-written CPU/CUDA functor pair).  Here each is
one jnp expression; gradients come from jax.vjp via the generic grad path,
replacing the reference's hand-derived grad functors.
"""

import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import RaggedTensor


def _unary(name, fn, extra_attrs=()):
    @register_op(name)
    def kernel(ctx, ins, attrs, fn=fn):
        xr = ins["X"][0]
        x = xr.values if isinstance(xr, RaggedTensor) else xr
        out = fn(x, attrs)
        if isinstance(xr, RaggedTensor):
            return {"Out": [xr.with_values(out)]}
        return {"Out": [out]}
    kernel.__name__ = name
    return kernel


_unary("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_unary("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_unary("exp", lambda x, a: jnp.exp(x))
_unary("relu", lambda x, a: jax.nn.relu(x))
_unary("tanh", lambda x, a: jnp.tanh(x))
_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_unary("softshrink", lambda x, a: jnp.where(
    x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
    jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
_unary("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_unary("sqrt", lambda x, a: jnp.sqrt(x))
_unary("abs", lambda x, a: jnp.abs(x))
_unary("ceil", lambda x, a: jnp.ceil(x))
_unary("floor", lambda x, a: jnp.floor(x))
_unary("round", lambda x, a: jnp.round(x))
_unary("reciprocal", lambda x, a: 1.0 / x)
_unary("log", lambda x, a: jnp.log(x))
_unary("square", lambda x, a: jnp.square(x))
_unary("softplus", lambda x, a: jax.nn.softplus(x))
_unary("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_unary("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0),
                                      a.get("t_max", 24.0)))
_unary("leaky_relu", lambda x, a: jnp.where(
    x >= 0, x, x * a.get("alpha", 0.02)))
_unary("soft_relu", lambda x, a: jnp.log(
    1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                         a.get("threshold", 40.0)))))
_unary("elu", lambda x, a: jnp.where(
    x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)))
_unary("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_unary("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_unary("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
    a.get("scale_a", 2.0 / 3.0) * x))
_unary("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, 0.0))
_unary("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_unary("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))


@register_op("softmax")
def softmax(ctx, ins, attrs):
    # reference: operators/softmax_op.cc — softmax over the last dim of 2D
    xr = ins["X"][0]
    x = xr.values if isinstance(xr, RaggedTensor) else xr
    if x.dtype == jnp.bfloat16:
        # f32 exponentials; probabilities back in the activation dtype
        out = jax.nn.softmax(x.astype(jnp.float32), axis=-1).astype(x.dtype)
    else:
        out = jax.nn.softmax(x, axis=-1)
    if isinstance(xr, RaggedTensor):
        return {"Out": [xr.with_values(out)]}
    return {"Out": [out]}


@register_op("prelu")
def prelu(ctx, ins, attrs):
    x = ins["X"][0]
    alpha = ins["Alpha"][0]
    return {"Out": [jnp.where(x >= 0, x, x * jnp.reshape(alpha, (1, -1))
                              if alpha.size > 1 else x * alpha)]}
