"""Distributed send/recv host ops.

TPU-native equivalent of the reference's send/recv pair (reference:
paddle/operators/send_op.cc:35 — gRPC client shipping grads,
recv_op.cc:86 — server applying the optimizer and serving back params).
`dist_send` is one round trip: ship gradient blocks to their pservers
(native framed-TCP clients), block until the (sync-mode) aggregated
update applies, write the fresh parameter back.  Runs host-side
(jittable=False): XLA finishes forward+backward on-device, then this op
does DCN IO.
"""

import numpy as np
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import SelectedRows


def _bname(pname, begin):
    return "%s@%d" % (pname, begin)


class ClientPool:
    """One native connection per endpoint per process."""

    _clients = {}

    @classmethod
    def get(cls, endpoint):
        c = cls._clients.get(endpoint)
        if c is None:
            from .. import native

            host, port = endpoint.rsplit(":", 1)
            c = native.PServerClient(host, int(port))
            cls._clients[endpoint] = c
        return c

    @classmethod
    def reset(cls):
        for c in cls._clients.values():
            try:
                c.close()
            except Exception:
                pass
        cls._clients.clear()


@register_op("dist_send", jittable=False, stop_gradient_op=True,
             in_place_outputs=("ParamOut",))
def dist_send(ctx, ins, attrs):
    param = ins["Param"][0]
    grad = ins["Grad"][0]
    pname = attrs["param_name"]
    blocks = attrs["blocks"]

    if isinstance(grad, SelectedRows):
        # sparse path: rows only (reference: SelectedRows transfer +
        # getParameterSparse ParameterServer2.h:510); sparse params are
        # assigned whole to one endpoint by the transpiler
        ep = blocks[0][0]
        c = ClientPool.get(ep)
        rows = np.asarray(grad.rows)
        vals = np.asarray(grad.values).reshape(rows.shape[0], -1)
        c.send_sparse_grad(_bname(pname, 0), rows, vals)
        uniq = np.unique(rows)
        got = c.get_rows(_bname(pname, 0), uniq, vals.shape[1])
        p = np.array(param)
        p.reshape(p.shape[0], -1)[uniq] = got
        return {"ParamOut": [jnp.asarray(p)]}

    flat = np.asarray(param).reshape(-1)
    g = np.asarray(grad, dtype=np.float32).reshape(-1)
    out = flat.astype(np.float32).copy()
    for ep, begin, size in blocks:
        c = ClientPool.get(ep)
        out[begin:begin + size] = c.send_grad(
            _bname(pname, begin), g[begin:begin + size])
    return {"ParamOut": [jnp.asarray(out.reshape(param.shape),
                                     dtype=param.dtype)]}
