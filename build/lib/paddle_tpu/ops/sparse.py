"""Embedding / sparse op kernels.

TPU-native equivalents of reference ops (paddle/operators/
lookup_table_op.cc — the CTR/sparse-update workhorse with dense and
SelectedRows gradients, split_selected_rows_op.cc).
"""

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad_kernel
from ..core.ragged import RaggedTensor, SelectedRows


@register_op("lookup_table", nondiff_inputs=("Ids",),
             sparse_grad_slots=lambda attrs:
                 ("W",) if attrs.get("is_sparse") else ())
def lookup_table(ctx, ins, attrs):
    w = ins["W"][0]
    ids = ins["Ids"][0]
    ragged = isinstance(ids, RaggedTensor)
    idv = ids.values if ragged else ids
    flat = jnp.reshape(idv, (-1,)).astype(jnp.int32)
    padding_idx = int(attrs.get("padding_idx", -1))
    out = jnp.take(w, flat, axis=0)
    if padding_idx >= 0:
        out = jnp.where((flat == padding_idx)[:, None],
                        jnp.zeros_like(out), out)
    if ragged:
        return {"Out": [ids.with_values(out)]}
    # keep leading dims of ids, append emb dim
    lead = idv.shape[:-1] if idv.ndim > 1 and idv.shape[-1] == 1 \
        else idv.shape
    return {"Out": [out.reshape(tuple(lead) + (w.shape[1],))]}


@register_grad_kernel("lookup_table")
def lookup_table_grad(ctx, ins, attrs):
    """Sparse path returns a SelectedRows gradient (reference:
    lookup_table_op.cc LookupTableGradKernel, is_sparse attr) — the
    optimizer ops then scatter-update only the touched rows."""
    w = ins["W"][0]
    ids = ins["Ids"][0]
    og = ins["OG@Out"][0]
    ragged = isinstance(ids, RaggedTensor)
    idv = ids.values if ragged else ids
    flat_ids = jnp.reshape(idv, (-1,)).astype(jnp.int32)
    g = og.values if isinstance(og, RaggedTensor) else og
    flat_g = jnp.reshape(g, (-1, w.shape[1]))
    padding_idx = int(attrs.get("padding_idx", -1))
    if padding_idx >= 0:
        flat_g = jnp.where((flat_ids == padding_idx)[:, None],
                           jnp.zeros_like(flat_g), flat_g)
    if ragged:
        # zero out padded rows beyond nvalid
        mask = ids.valid_mask()
        flat_g = jnp.where(mask[:, None], flat_g, 0.0)
    if attrs.get("is_sparse", False):
        return {"W@GRAD": [SelectedRows(flat_ids, flat_g, w.shape[0])]}
    dense = jnp.zeros_like(w).at[flat_ids].add(flat_g)
    return {"W@GRAD": [dense]}


@register_op("split_selected_rows", stop_gradient_op=True)
def split_selected_rows(ctx, ins, attrs):
    """Partition a SelectedRows by row-id range (reference:
    split_selected_rows_op.cc; used by the pserver transpiler to shard
    sparse grads across servers)."""
    x = ins["X"][0]
    sections = attrs["height_sections"]
    outs = []
    start = 0
    for h in sections:
        in_range = (x.rows >= start) & (x.rows < start + h)
        # static shapes: keep all rows, zero the out-of-range ones and
        # rebase ids (rows out of range point at row 0 with zero values)
        rows = jnp.where(in_range, x.rows - start, 0)
        vals = jnp.where(in_range[:, None], x.values, 0.0)
        outs.append(SelectedRows(rows, vals, h))
        start += h
    return {"Out": outs}
