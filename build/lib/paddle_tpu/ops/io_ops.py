"""Host-side IO ops: feed, fetch, save, load, print.

TPU-native equivalents of reference ops (paddle/operators/feed_op.cc,
fetch_op.cc, save_op.cc, load_op.cc, print_op.cc).  These are the
non-jittable ops that split a block into compiled segments; they run on
host between XLA executions, matching the reference's interleaved
executor semantics.
"""

import os

import numpy as np
import jax

from .registry import register_op
from ..core.ragged import RaggedTensor


@register_op("feed", jittable=False, stop_gradient_op=True)
def feed(ctx, ins, attrs):
    feed_list = ctx.scope.get("feed") or []
    col = int(attrs.get("col", 0))
    return {"Out": [feed_list[col]]}


@register_op("fetch", jittable=False, stop_gradient_op=True)
def fetch(ctx, ins, attrs):
    col = int(attrs.get("col", 0))
    fetch_list = ctx.scope.get("fetch") or []
    while len(fetch_list) <= col:
        fetch_list.append(None)
    fetch_list[col] = ins["X"][0]
    ctx.scope.set("fetch", fetch_list)
    return {}


def _var_file(dirname, name):
    return os.path.join(dirname, name.replace("/", "_"))


@register_op("save", jittable=False, stop_gradient_op=True)
def save(ctx, ins, attrs):
    """reference save_op.cc: one raw tensor file per var."""
    path = attrs["file_path"]
    overwrite = attrs.get("overwrite", True)
    if os.path.exists(path) and not overwrite:
        raise RuntimeError("%r exists and overwrite=False" % path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    x = ins["X"][0]
    if isinstance(x, RaggedTensor):
        np.savez(path, __ragged__=1, values=np.asarray(x.values),
                 nvalid=np.asarray(x.nvalid),
                 **{"rs%d" % i: np.asarray(rs)
                    for i, rs in enumerate(x.row_splits)})
    else:
        np.savez(path, __ragged__=0, values=np.asarray(x))
    return {}


@register_op("load", jittable=False, stop_gradient_op=True)
def load(ctx, ins, attrs):
    path = attrs["file_path"]
    real = path if os.path.exists(path) else path + ".npz"
    with np.load(real) as data:
        if int(data["__ragged__"]) == 1:
            splits = []
            i = 0
            while "rs%d" % i in data:
                splits.append(data["rs%d" % i])
                i += 1
            out = RaggedTensor(jax.numpy.asarray(data["values"]), splits,
                              nvalid=int(data["nvalid"]))
        else:
            out = jax.device_put(data["values"],
                                 ctx.place.device() if ctx.place else None)
    return {"Out": [out]}


@register_op("print", jittable=False)
def print_op(ctx, ins, attrs):
    """reference print_op.cc: tensor debugger; forwards input unchanged."""
    x = ins["In"][0] if "In" in ins else ins["X"][0]
    msg = attrs.get("message", "")
    arr = x.values if isinstance(x, RaggedTensor) else x
    arr = np.asarray(arr)
    parts = [msg]
    if attrs.get("print_tensor_name", True):
        parts.append("var")
    if attrs.get("print_tensor_shape", True):
        parts.append("shape=%s" % (arr.shape,))
    if attrs.get("print_tensor_dtype", True):
        parts.append("dtype=%s" % arr.dtype)
    summarize = int(attrs.get("summarize", -1))
    flat = arr.reshape(-1)
    if summarize > 0:
        flat = flat[:summarize]
    parts.append("data=%s" % (flat,))
    print(" ".join(str(p) for p in parts))
    return {"Out": [x]}
