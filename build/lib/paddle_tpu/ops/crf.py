"""Linear-chain CRF ops.

TPU-native equivalents of the reference CRF family
(reference: paddle/operators/linear_chain_crf_op.cc — forward alpha
recursion + NLL; crf_decoding_op.cc — Viterbi; chunk_eval_op.cc —
chunk-level precision/recall/F1).

Design departures:
  * linear_chain_crf runs the forward recursion as a masked lax.scan over
    a padded [B, Tmax, D] batch (the reference loops per sequence on CPU,
    linear_chain_crf_op.h:129).  Log-space throughout (the reference uses
    L1-normalized exp space, linear_chain_crf_op.h:158).  Gradients come
    from jax.vjp of the forward — no hand-written backward
    (linear_chain_crf_op.h:218 is the hand-rolled one).
  * crf_decoding / chunk_eval are host ops (jittable=False): the reference
    registers them CPU-only too; they are eval-path.

Transition layout (reference linear_chain_crf_op.cc:29-33): row 0 =
start weights a, row 1 = end weights b, rows 2.. = transition matrix w
([D, D], w[i, j] = score of tag i -> tag j).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..core.ragged import RaggedTensor
from .sequence import ragged_to_padded


def _pad_batch(emission, label=None):
    e_pad, lengths = ragged_to_padded(emission)  # [B, Tmax, D]
    l_pad = None
    if label is not None:
        l_rt = label if isinstance(label, RaggedTensor) else None
        assert l_rt is not None, "CRF Label must be a sequence (ragged)"
        lp, _ = ragged_to_padded(l_rt.with_values(
            l_rt.values.reshape(-1, 1).astype(jnp.int32)))
        l_pad = lp[:, :, 0]
    return e_pad, l_pad, lengths


@register_op("linear_chain_crf", nondiff_inputs=("Label",))
def linear_chain_crf(ctx, ins, attrs):
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    e_pad, l_pad, lengths = _pad_batch(emission, label)
    B, Tmax, D = e_pad.shape
    a = transition[0]          # start weights
    b = transition[1]          # end weights
    w = transition[2:]         # [D, D]

    t_idx = jnp.arange(Tmax)

    # ---- logZ: masked forward recursion ---------------------------------
    def step(alpha, inputs):
        e_t, active = inputs          # [B, D], [B]
        new = jax.nn.logsumexp(alpha[:, :, None] + w[None], axis=1) + e_t
        alpha = jnp.where(active[:, None], new, alpha)
        return alpha, alpha

    alpha0 = a[None] + e_pad[:, 0]
    active = (t_idx[None, :] < lengths[:, None])  # [B, Tmax]
    alpha_last, alphas = lax.scan(
        step, alpha0,
        (jnp.swapaxes(e_pad, 0, 1)[1:], jnp.swapaxes(active, 0, 1)[1:]))
    log_z = jax.nn.logsumexp(alpha_last + b[None], axis=-1)  # [B]

    # ---- gold path score -------------------------------------------------
    lbl = jnp.clip(l_pad, 0, D - 1)
    e_at_lbl = jnp.take_along_axis(e_pad, lbl[:, :, None],
                                   axis=2)[:, :, 0]          # [B, Tmax]
    e_score = jnp.sum(jnp.where(active, e_at_lbl, 0.0), axis=1)
    trans_score = w[lbl[:, :-1], lbl[:, 1:]]                 # [B, Tmax-1]
    trans_active = active[:, 1:]
    t_score = jnp.sum(jnp.where(trans_active, trans_score, 0.0), axis=1)
    last_pos = jnp.maximum(lengths - 1, 0)
    last_lbl = jnp.take_along_axis(lbl, last_pos[:, None], axis=1)[:, 0]
    score = a[lbl[:, 0]] + e_score + t_score + b[last_lbl]

    nll = (log_z - score).reshape(-1, 1)

    # workspace outputs kept for reference parity (grads come from vjp)
    from .sequence import padded_to_ragged

    alphas_full = jnp.concatenate([alpha0[None], alphas], axis=0)
    alpha_rt = padded_to_ragged(jnp.swapaxes(alphas_full, 0, 1), emission)
    return {"Alpha": [alpha_rt],
            "EmissionExps": [emission.with_values(jnp.exp(emission.values))],
            "TransitionExps": [jnp.exp(transition)],
            "LogLikelihood": [nll]}


@register_op("crf_decoding", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Emission", "Transition", "Label"))
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference: crf_decoding_op.h).  With Label given,
    outputs 1 where the decoded tag equals the label, else 0."""
    emission = ins["Emission"][0]
    transition = np.asarray(ins["Transition"][0], np.float64)
    a, b, w = transition[0], transition[1], transition[2:]
    splits = np.asarray(emission.last_splits())
    values = np.asarray(emission.values, np.float64)
    nvalid = int(np.asarray(emission.nvalid))

    path = np.zeros((values.shape[0], 1), np.int32)
    for s in range(len(splits) - 1):
        lo, hi = int(splits[s]), int(splits[s + 1])
        if hi <= lo:
            continue
        x = values[lo:hi]
        T, D = x.shape
        delta = a + x[0]
        back = np.zeros((T, D), np.int32)
        for t in range(1, T):
            cand = delta[:, None] + w
            back[t] = cand.argmax(axis=0)
            delta = cand.max(axis=0) + x[t]
        delta = delta + b
        tags = np.zeros(T, np.int32)
        tags[T - 1] = int(delta.argmax())
        for t in range(T - 1, 0, -1):
            tags[t - 1] = back[t, tags[t]]
        path[lo:hi, 0] = tags

    if ins.get("Label") and ins["Label"][0] is not None:
        lbl = ins["Label"][0]
        lv = np.asarray(lbl.values).reshape(-1).astype(np.int32)
        match = (path[:nvalid, 0] == lv[:nvalid]).astype(np.int32)
        out = np.zeros_like(path)
        out[:nvalid, 0] = match
        path = out
    return {"ViterbiPath": [emission.with_values(jnp.asarray(path))]}


def _extract_chunks(tags, num_types, scheme, excluded):
    """-> set of (begin, end, type) chunks (reference: chunk_eval_op.h
    Segment extraction).  Tag encoding per scheme:
      plain: tag == type
      IOB:   tag = type*2 + (0 begin | 1 inside)
      IOE:   tag = type*2 + (0 inside | 1 end)
      IOBES: tag = type*4 + (0 begin | 1 inside | 2 end | 3 single)
    with one extra 'outside' tag = num_types*tag_width."""
    chunks = []
    n = len(tags)
    i = 0
    if scheme == "plain":
        while i < n:
            t = tags[i]
            if 0 <= t < num_types:
                j = i
                while j + 1 < n and tags[j + 1] == t:
                    j += 1
                chunks.append((i, j, t))
                i = j + 1
            else:
                i += 1
    elif scheme == "IOB":
        while i < n:
            t = tags[i]
            if 0 <= t < num_types * 2:
                ctype, pos = divmod(t, 2)
                j = i
                while (j + 1 < n and tags[j + 1] == ctype * 2 + 1):
                    j += 1
                chunks.append((i, j, ctype))
                i = j + 1
            else:
                i += 1
    elif scheme == "IOE":
        while i < n:
            t = tags[i]
            if 0 <= t < num_types * 2:
                ctype = t // 2
                j = i
                while j < n and tags[j] == ctype * 2 and j + 1 < n and \
                        tags[j + 1] // 2 == ctype:
                    j += 1
                if j < n and tags[j] // 2 == ctype:
                    chunks.append((i, j, ctype))
                    i = j + 1
                else:
                    i += 1
            else:
                i += 1
    elif scheme == "IOBES":
        while i < n:
            t = tags[i]
            if 0 <= t < num_types * 4:
                ctype, pos = divmod(t, 4)
                if pos == 3:  # single
                    chunks.append((i, i, ctype))
                    i += 1
                elif pos == 0:  # begin
                    j = i
                    while (j + 1 < n and tags[j + 1] // 4 == ctype and
                           tags[j + 1] % 4 == 1):
                        j += 1
                    if j + 1 < n and tags[j + 1] // 4 == ctype and \
                            tags[j + 1] % 4 == 2:
                        j += 1
                    chunks.append((i, j, ctype))
                    i = j + 1
                else:
                    i += 1
            else:
                i += 1
    else:
        raise ValueError("unknown chunk scheme %r" % scheme)
    return {(b, e, t) for (b, e, t) in chunks if t not in excluded}


@register_op("chunk_eval", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Inference", "Label"))
def chunk_eval(ctx, ins, attrs):
    inference = ins["Inference"][0]
    label = ins["Label"][0]
    num_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = set(attrs.get("excluded_chunk_types") or [])

    splits = np.asarray(label.last_splits())
    inf_v = np.asarray(inference.values).reshape(-1)
    lbl_v = np.asarray(label.values).reshape(-1)

    num_infer = num_label = num_correct = 0
    for s in range(len(splits) - 1):
        lo, hi = int(splits[s]), int(splits[s + 1])
        ic = _extract_chunks(inf_v[lo:hi].tolist(), num_types, scheme,
                             excluded)
        lc = _extract_chunks(lbl_v[lo:hi].tolist(), num_types, scheme,
                             excluded)
        num_infer += len(ic)
        num_label += len(lc)
        num_correct += len(ic & lc)

    precision = num_correct / num_infer if num_infer else 0.0
    recall = num_correct / num_label if num_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if num_correct else 0.0)
    f32 = np.float32
    return {"Precision": [np.asarray([precision], f32)],
            "Recall": [np.asarray([recall], f32)],
            "F1-Score": [np.asarray([f1], f32)],
            "NumInferChunks": [np.asarray([num_infer], np.int32)],
            "NumLabelChunks": [np.asarray([num_label], np.int32)],
            "NumCorrectChunks": [np.asarray([num_correct], np.int32)]}
