"""Beam search ops (generation path).

TPU-native equivalents of the reference beam search pair
(reference: paddle/operators/beam_search_op.cc — per-source top-k over
candidate (prefix, token) pairs with end-id pruning;
beam_search_decode_op.cc — backtrack the per-step selections into full
hypotheses).

Both are host ops (jittable=False): the reference registers them CPU-only
as well (no .cu kernels) — beam bookkeeping is dynamic-shaped by nature.
A fully-on-TPU static-shape beam decode (dense [batch, beam] state with
lax.top_k inside lax.while_loop) is provided separately in
paddle_tpu.models.decode for the performance path; these ops keep the
reference's LoD program semantics for program parity.

LoD convention (reference beam_search_op.h:46-63): selected_ids/scores
are [M, 1] with two split levels: level 0 = source sentences over beam
rows, level 1 = one segment per input beam row (its surviving items).
"""

import numpy as np
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import RaggedTensor


def _splits_of(rt, level):
    return np.asarray(rt.row_splits[level]).astype(np.int64)


@register_op("beam_search", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("pre_ids", "ids", "scores"))
def beam_search(ctx, ins, attrs):
    pre_ids_t = ins["pre_ids"][0]
    ids_t = ins["ids"][0]
    scores_t = ins["scores"][0]
    level = int(attrs.get("level", 0))
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs.get("end_id", 0))

    scores = np.asarray(scores_t.values if isinstance(scores_t,
                                                      RaggedTensor)
                        else scores_t)
    n_rows = scores.shape[0]
    scores = scores.reshape(n_rows, -1)
    ids = np.asarray(ids_t.values if isinstance(ids_t, RaggedTensor)
                     else ids_t).reshape(n_rows, -1).astype(np.int64)
    pre_ids = np.asarray(pre_ids_t.values if isinstance(
        pre_ids_t, RaggedTensor) else pre_ids_t).reshape(-1).astype(
            np.int64)
    if isinstance(scores_t, RaggedTensor):
        high = _splits_of(scores_t, level)
    elif isinstance(ids_t, RaggedTensor):
        high = _splits_of(ids_t, level)
    else:
        high = np.asarray([0, n_rows], np.int64)  # one source
    # per-source top-beam_size over all (row, candidate) items
    # (reference: SelectTopBeamSizeItems)
    selected_per_row = [[] for _ in range(n_rows)]
    for s in range(len(high) - 1):
        items = []
        for r in range(int(high[s]), int(high[s + 1])):
            for j in range(ids.shape[1]):
                items.append((r, int(ids[r, j]), float(scores[r, j])))
        items.sort(key=lambda it: -it[2])
        for r, tok, sc in items[:beam_size]:
            selected_per_row[r].append((tok, sc))

    # prune rows whose prefix already ended (reference:
    # PruneEndidCandidates)
    for r in range(min(n_rows, len(pre_ids))):
        if pre_ids[r] == end_id:
            selected_per_row[r] = []

    out_ids, out_scores = [], []
    low = [0]
    for r in range(n_rows):
        row_items = sorted(selected_per_row[r], key=lambda it: it[0])
        for tok, sc in row_items:
            out_ids.append(tok)
            out_scores.append(sc)
        low.append(len(out_ids))
    low = np.asarray(low, np.int64)

    sel_ids = np.asarray(out_ids, np.int64).reshape(-1, 1)
    sel_scores = np.asarray(out_scores, np.float32).reshape(-1, 1)
    if sel_ids.size == 0:
        sel_ids = np.zeros((0, 1), np.int64)
        sel_scores = np.zeros((0, 1), np.float32)
    return {
        "selected_ids": [RaggedTensor(jnp.asarray(sel_ids), [high, low])],
        "selected_scores": [RaggedTensor(jnp.asarray(sel_scores),
                                         [high, low])],
    }


@register_op("beam_search_decode", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Ids", "Scores"))
def beam_search_decode(ctx, ins, attrs):
    """Backtrack per-step beam selections into hypotheses.

    Ids/Scores: host lists of the per-step selected_ids/selected_scores
    RaggedTensors (2-level splits as produced by beam_search).  Outputs
    SentenceIds/SentenceScores: [Ntok, 1] with 2-level splits
    (source -> hypothesis -> tokens), mirroring reference
    beam_search_decode_op.h PackAllSteps.
    """
    steps_ids = ins["Ids"]
    steps_scores = ins["Scores"]
    if len(steps_ids) == 1 and isinstance(steps_ids[0], (list, tuple)):
        steps_ids = list(steps_ids[0])
        steps_scores = list(steps_scores[0])
    n_steps = len(steps_ids)
    assert n_steps > 0, "beam_search_decode needs at least one step"

    ids_np = [np.asarray(t.values).reshape(-1) for t in steps_ids]
    scores_np = [np.asarray(t.values).reshape(-1) for t in steps_scores]
    lod0 = [np.asarray(t.row_splits[0]) for t in steps_ids]
    lod1 = [np.asarray(t.row_splits[1]) for t in steps_ids]
    n_src = len(lod0[0]) - 1

    # parent of item m at step t = index j of the level-1 segment
    # containing m; that j is the item index at step t-1.
    parents = []
    for t in range(n_steps):
        par = np.searchsorted(lod1[t], np.arange(len(ids_np[t])),
                              side="right") - 1
        parents.append(par)

    def source_of(t, item):
        row = parents[t][item] if t >= 0 else item
        # level-1 segment j corresponds to beam row j; level 0 maps rows
        # to sources
        return int(np.searchsorted(lod0[t], row, side="right") - 1)

    # an item is a leaf if no item at step t+1 has it as parent
    sentences = [[] for _ in range(n_src)]  # per source: (ids, scores)
    for t in range(n_steps):
        if t + 1 < n_steps:
            has_kid = np.zeros(len(ids_np[t]), bool)
            kids = parents[t + 1]
            has_kid[kids[kids < len(has_kid)]] = True
        else:
            has_kid = np.zeros(len(ids_np[t]), bool)
        for m in range(len(ids_np[t])):
            if has_kid[m]:
                continue
            # backtrack to the root
            toks, scs = [], []
            tt, mm = t, m
            while tt >= 0:
                toks.append(int(ids_np[tt][mm]))
                scs.append(float(scores_np[tt][mm]))
                mm = int(parents[tt][mm])
                tt -= 1
            toks.reverse()
            scs.reverse()
            sentences[source_of(t, m)].append((toks, scs))

    out_ids, out_scores = [], []
    l0, l1 = [0], [0]
    for s in range(n_src):
        for toks, scs in sentences[s]:
            out_ids.extend(toks)
            out_scores.extend(scs)
            l1.append(len(out_ids))
        l0.append(len(l1) - 1)
    sent_ids = np.asarray(out_ids, np.int64).reshape(-1, 1)
    sent_scores = np.asarray(out_scores, np.float32).reshape(-1, 1)
    if sent_ids.size == 0:
        sent_ids = np.zeros((0, 1), np.int64)
        sent_scores = np.zeros((0, 1), np.float32)
    l0 = np.asarray(l0, np.int64)
    l1 = np.asarray(l1, np.int64)
    return {
        "SentenceIds": [RaggedTensor(jnp.asarray(sent_ids), [l0, l1])],
        "SentenceScores": [RaggedTensor(jnp.asarray(sent_scores),
                                        [l0, l1])],
    }
