"""Random / init op kernels and dropout.

TPU-native equivalents of reference ops (paddle/operators/
uniform_random_op.cc, gaussian_random_op.cc, dropout_op.cc).  Randomness is
a functional PRNG stream threaded through compiled segments by the executor
(no stateful cuRAND analog); ops honoring the reference `seed` attr use a
fixed key for reproducibility.
"""

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad_kernel
from ..core.types import np_dtype
from ..core.ragged import RaggedTensor


def _key(ctx, attrs):
    seed = int(attrs.get("seed", 0) or 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.next_rng()


@register_op("uniform_random", uses_rng=True, stop_gradient_op=True)
def uniform_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dtype = np_dtype(attrs.get("dtype", "float32"))
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    out = jax.random.uniform(_key(ctx, attrs), shape, dtype=jnp.float32,
                             minval=lo, maxval=hi).astype(dtype)
    return {"Out": [out]}


@register_op("gaussian_random", uses_rng=True, stop_gradient_op=True)
def gaussian_random(ctx, ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    dtype = np_dtype(attrs.get("dtype", "float32"))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    out = mean + std * jax.random.normal(_key(ctx, attrs), shape,
                                         dtype=jnp.float32)
    return {"Out": [out.astype(dtype)]}


@register_op("dropout", uses_rng=True)
def dropout(ctx, ins, attrs):
    xr = ins["X"][0]
    x = xr.values if isinstance(xr, RaggedTensor) else xr
    prob = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False):
        # reference dropout_op.h: test mode scales by (1 - p)
        out = x * (1.0 - prob)
        mask = jnp.ones_like(x)
    else:
        if attrs.get("fix_seed", False):
            key = jax.random.PRNGKey(int(attrs.get("seed", 0)))
        else:
            key = ctx.next_rng()
        mask = (jax.random.uniform(key, x.shape) >= prob).astype(x.dtype)
        out = x * mask
    if isinstance(xr, RaggedTensor):
        return {"Out": [xr.with_values(out)], "Mask": [mask]}
    return {"Out": [out], "Mask": [mask]}


@register_grad_kernel("dropout")
def dropout_grad(ctx, ins, attrs):
    """Uses the saved forward Mask (reference: dropout_op.h DropoutGradKernel)
    — the RNG must not be replayed."""
    og = ins["OG@Out"][0]
    mask = ins["O@Mask"][0]
    ogr = og
    g = og.values if isinstance(og, RaggedTensor) else og
    if attrs.get("is_test", False):
        out = g * (1.0 - attrs.get("dropout_prob", 0.5))
    else:
        out = g * mask
    if isinstance(ogr, RaggedTensor):
        return {"X@GRAD": [ogr.with_values(out)]}
    return {"X@GRAD": [out]}
