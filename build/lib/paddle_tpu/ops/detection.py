"""Detection ops (SSD family).

TPU-native equivalents of the reference detection family
(reference: paddle/operators/prior_box_op.cc, iou_similarity_op.cc,
bipartite_match_op.cc, detection_output_op.cc).

prior_box and iou_similarity are pure XLA (vectorized, no loops).
bipartite_match and detection_output (NMS) are host ops: both are
greedy sequential algorithms with data-dependent trip counts, and the
reference runs bipartite_match CPU-only as well.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import RaggedTensor


@register_op("prior_box", stop_gradient_op=True,
             nondiff_inputs=("Input", "Image"))
def prior_box(ctx, ins, attrs):
    """reference: prior_box_op.h — boxes [H, W, num_priors, 4] in
    normalized (xmin, ymin, xmax, ymax)."""
    feat = ins["Input"][0]
    image = ins["Image"][0]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes") or []]
    aspect_ratios = [float(a) for a in attrs.get("aspect_ratios") or [1.0]]
    variances = [float(v) for v in
                 attrs.get("variances") or [0.1, 0.1, 0.2, 0.2]]
    flip = bool(attrs.get("flip", True))
    clip = bool(attrs.get("clip", True))
    offset = float(attrs.get("offset", 0.5))

    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w") or 0.0) or img_w / W
    step_h = float(attrs.get("step_h") or 0.0) or img_h / H

    # expanded aspect ratio list (reference: ExpandAspectRatios)
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    # per-position (w, h) of each prior
    pw, ph = [], []
    for s, ms in enumerate(min_sizes):
        pw.append(ms / 2.0)
        ph.append(ms / 2.0)
        if max_sizes:
            big = np.sqrt(ms * max_sizes[s])
            pw.append(big / 2.0)
            ph.append(big / 2.0)
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            pw.append(ms * np.sqrt(ar) / 2.0)
            ph.append(ms / np.sqrt(ar) / 2.0)
    num_priors = len(pw)
    pw = jnp.asarray(pw, jnp.float32)
    ph = jnp.asarray(ph, jnp.float32)

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cx = cx[None, :, None]  # [1, W, 1]
    cy = cy[:, None, None]  # [H, 1, 1]
    xmin = (cx - pw[None, None, :]) / img_w
    xmax = (cx + pw[None, None, :]) / img_w
    ymin = (cy - ph[None, None, :]) / img_h
    ymax = (cy + ph[None, None, :]) / img_h
    boxes = jnp.stack(
        [jnp.broadcast_to(xmin, (H, W, num_priors)),
         jnp.broadcast_to(ymin, (H, W, num_priors)),
         jnp.broadcast_to(xmax, (H, W, num_priors)),
         jnp.broadcast_to(ymax, (H, W, num_priors))], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, num_priors, 4))
    return {"Boxes": [boxes], "Variances": [var]}


def _iou(x, y):
    """x: [N, 4], y: [M, 4] -> [N, M] IoU (xmin, ymin, xmax, ymax)."""
    area_x = jnp.maximum(x[:, 2] - x[:, 0], 0) * \
        jnp.maximum(x[:, 3] - x[:, 1], 0)
    area_y = jnp.maximum(y[:, 2] - y[:, 0], 0) * \
        jnp.maximum(y[:, 3] - y[:, 1], 0)
    ix_min = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy_min = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix_max = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy_max = jnp.minimum(x[:, None, 3], y[None, :, 3])
    inter = jnp.maximum(ix_max - ix_min, 0) * \
        jnp.maximum(iy_max - iy_min, 0)
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", stop_gradient_op=True,
             nondiff_inputs=("X", "Y"))
def iou_similarity(ctx, ins, attrs):
    """reference: iou_similarity_op.h — X may be a ragged [N, 4] per-image
    box list; Y is [M, 4]."""
    x = ins["X"][0]
    y = ins["Y"][0]
    xv = x.values if isinstance(x, RaggedTensor) else x
    out = _iou(xv, y)
    if isinstance(x, RaggedTensor):
        return {"Out": [x.with_values(out)]}
    return {"Out": [out]}


@register_op("bipartite_match", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("DistMat",))
def bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching per image (reference:
    bipartite_match_op.cc:44 BipartiteMatch).  DistMat may be ragged
    (per-image row blocks)."""
    dist_t = ins["DistMat"][0]
    ragged = isinstance(dist_t, RaggedTensor)
    if ragged:
        splits = np.asarray(dist_t.last_splits())
        dist = np.asarray(dist_t.values)
    else:
        dist = np.asarray(dist_t)
        splits = np.asarray([0, dist.shape[0]], np.int64)
    n_img = len(splits) - 1
    col = dist.shape[1]
    match_indices = np.full((n_img, col), -1, np.int32)
    match_dist = np.zeros((n_img, col), np.float32)
    for i in range(n_img):
        sub = dist[int(splits[i]):int(splits[i + 1])]
        row_pool = list(range(sub.shape[0]))
        while row_pool:
            best = (-1, -1, -1.0)
            for j in range(col):
                if match_indices[i, j] != -1:
                    continue
                for m in row_pool:
                    d = sub[m, j]
                    if d < 1e-6:
                        continue
                    if d > best[2]:
                        best = (m, j, float(d))
            if best[0] < 0:
                break
            m, j, d = best
            match_indices[i, j] = m
            match_dist[i, j] = d
            row_pool.remove(m)
    return {"ColToRowMatchIndices": [match_indices],
            "ColToRowMatchDis": [match_dist]}


def _nms(boxes, scores, nms_threshold, top_k):
    """Greedy per-class NMS -> kept indices (reference:
    detection_output_op.h ApplyNMSFast)."""
    order = np.argsort(-scores)
    if top_k > 0:
        order = order[:top_k]
    keep = []
    while len(order):
        i = order[0]
        keep.append(int(i))
        if len(order) == 1:
            break
        rest = order[1:]
        ious = np.asarray(_iou(jnp.asarray(boxes[i][None]),
                               jnp.asarray(boxes[rest])))[0]
        order = rest[ious <= nms_threshold]
    return keep


@register_op("detection_output", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("Loc", "Conf", "PriorBox"))
def detection_output(ctx, ins, attrs):
    """SSD detection output: decode loc predictions against priors,
    per-class NMS, keep top_k (reference: detection_output_op.h).

    Loc:  [N, num_priors * 4] location predictions.
    Conf: [N, num_priors * num_classes] class scores (softmaxed here).
    PriorBox: [num_priors * 2, 4] — boxes then variances (reference
    stores priors and variances interleaved rows).
    Out: [M, 7] rows (image_id, label, score, xmin, ymin, xmax, ymax);
    M == 1 row of -1s when nothing passes (reference keeps shape [1, 7]).
    """
    loc = np.asarray(ins["Loc"][0])
    conf = np.asarray(ins["Conf"][0])
    prior = np.asarray(ins["PriorBox"][0]).reshape(-1, 4)
    num_classes = int(attrs["num_classes"])
    background = int(attrs.get("background_label_id", 0))
    nms_threshold = float(attrs.get("nms_threshold", 0.45))
    conf_threshold = float(attrs.get("confidence_threshold", 0.01))
    top_k = int(attrs.get("top_k", 100))
    nms_top_k = int(attrs.get("nms_top_k", 400))

    n_prior = prior.shape[0] // 2
    pboxes = prior[:n_prior]
    pvars = prior[n_prior:]
    N = loc.shape[0]
    loc = loc.reshape(N, n_prior, 4)
    conf = conf.reshape(N, n_prior, num_classes)
    # softmax over classes
    e = np.exp(conf - conf.max(axis=-1, keepdims=True))
    conf = e / e.sum(axis=-1, keepdims=True)

    # decode (reference: variance-encoded center-size decoding)
    pw = pboxes[:, 2] - pboxes[:, 0]
    ph = pboxes[:, 3] - pboxes[:, 1]
    pcx = (pboxes[:, 0] + pboxes[:, 2]) / 2
    pcy = (pboxes[:, 1] + pboxes[:, 3]) / 2
    dcx = pvars[:, 0] * loc[:, :, 0] * pw + pcx
    dcy = pvars[:, 1] * loc[:, :, 1] * ph + pcy
    dw = np.exp(pvars[:, 2] * loc[:, :, 2]) * pw
    dh = np.exp(pvars[:, 3] * loc[:, :, 3]) * ph
    decoded = np.stack([dcx - dw / 2, dcy - dh / 2,
                        dcx + dw / 2, dcy + dh / 2], axis=-1)

    rows = []
    for n in range(N):
        all_dets = []
        for c in range(num_classes):
            if c == background:
                continue
            scores = conf[n, :, c]
            mask = scores > conf_threshold
            if not mask.any():
                continue
            idx = np.where(mask)[0]
            keep = _nms(decoded[n, idx], scores[idx], nms_threshold,
                        nms_top_k)
            for k in keep:
                i = idx[k]
                all_dets.append((float(scores[i]), c, decoded[n, i]))
        all_dets.sort(key=lambda d: -d[0])
        for score, c, box in all_dets[:top_k]:
            rows.append([float(n), float(c), score,
                         float(box[0]), float(box[1]),
                         float(box[2]), float(box[3])])
    if not rows:
        rows = [[-1.0] * 7]
    return {"Out": [np.asarray(rows, np.float32)]}


@register_op("multibox_loss",
             nondiff_inputs=("PriorBox", "GtBox", "GtLabel"))
def multibox_loss(ctx, ins, attrs):
    """SSD training loss (reference: MultiBoxLossLayer.cpp via
    multibox_loss_layer, layers.py): per-prediction IoU matching,
    variance-encoded smooth-L1 location loss on positives, softmax
    confidence loss with 3:1 hard-negative mining.

    Unlike the reference's sequential CPU matching, everything here is
    a fixed-shape masked computation — matching, mining, and both
    losses trace into one XLA program, so the op is differentiable
    w.r.t. Loc/Conf and fuses into the training step.

    Loc: [N, P*4]; Conf: [N, P*C]; PriorBox: [2P, 4] (boxes then
    variances); GtBox: ragged [G, 4]; GtLabel: ragged [G, 1].
    Loss: [N, 1] per-image cost.
    """
    num_classes = int(attrs["num_classes"])
    overlap_threshold = float(attrs.get("overlap_threshold", 0.5))
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    background = int(attrs.get("background_label_id", 0))

    loc = ins["Loc"][0]
    conf = ins["Conf"][0]
    prior = ins["PriorBox"][0].reshape(-1, 4)
    gt_box_t = ins["GtBox"][0]
    gt_label_t = ins["GtLabel"][0]

    n_prior = prior.shape[0] // 2
    pboxes, pvars = prior[:n_prior], prior[n_prior:]
    N = loc.shape[0]
    loc = loc.reshape(N, n_prior, 4)
    conf = conf.reshape(N, n_prior, num_classes)

    gt_boxes = gt_box_t.values if isinstance(gt_box_t, RaggedTensor) \
        else gt_box_t
    gt_labels = (gt_label_t.values if isinstance(gt_label_t,
                                                 RaggedTensor)
                 else gt_label_t).reshape(-1).astype(jnp.int32)
    if isinstance(gt_box_t, RaggedTensor):
        splits = gt_box_t.last_splits()
    else:
        splits = jnp.asarray([0, gt_boxes.shape[0]], jnp.int32)
    G = gt_boxes.shape[0]
    # image membership of each gt row: img[g] = n iff splits[n] <= g
    img_of_gt = jnp.searchsorted(splits[1:], jnp.arange(G), side="right")

    iou = _iou(pboxes, gt_boxes)                      # [P, G]
    member = img_of_gt[None, :] == jnp.arange(N)[:, None, None]  # [N,1,G]
    iou_n = jnp.where(member, iou[None], -1.0)        # [N, P, G]
    best_gt = jnp.argmax(iou_n, axis=-1)              # [N, P]
    best_iou = jnp.take_along_axis(iou_n, best_gt[..., None],
                                   -1)[..., 0]        # [N, P]
    positive = best_iou >= overlap_threshold

    # bipartite step (reference: MultiBoxLossLayer.cpp matches each gt
    # to its best prior unconditionally BEFORE per-prediction
    # thresholding) — without it a gt whose best IoU is under the
    # threshold would contribute no gradient at all
    valid_gt = member[:, 0, :]                        # [N, G]
    best_prior = jnp.argmax(iou_n, axis=1)            # [N, G]
    gt_hits_prior = (jax.nn.one_hot(best_prior, n_prior, dtype=bool)
                     & valid_gt[..., None])           # [N, G, P]
    forced = jnp.any(gt_hits_prior, axis=1)           # [N, P]
    # a forced prior adopts its highest-IoU forcing gt
    forced_iou = jnp.where(jnp.swapaxes(gt_hits_prior, 1, 2),
                           iou[None], -1.0)           # [N, P, G]
    best_gt = jnp.where(forced, jnp.argmax(forced_iou, -1), best_gt)
    positive = positive | forced

    matched_box = gt_boxes[best_gt]                   # [N, P, 4]
    matched_label = gt_labels[best_gt]                # [N, P]

    # encode matched gt against priors (center-size, variance-scaled)
    pw = pboxes[:, 2] - pboxes[:, 0]
    ph = pboxes[:, 3] - pboxes[:, 1]
    pcx = (pboxes[:, 0] + pboxes[:, 2]) / 2
    pcy = (pboxes[:, 1] + pboxes[:, 3]) / 2
    gw = jnp.maximum(matched_box[..., 2] - matched_box[..., 0], 1e-6)
    gh = jnp.maximum(matched_box[..., 3] - matched_box[..., 1], 1e-6)
    gcx = (matched_box[..., 0] + matched_box[..., 2]) / 2
    gcy = (matched_box[..., 1] + matched_box[..., 3]) / 2
    target = jnp.stack(
        [(gcx - pcx) / pw / pvars[:, 0], (gcy - pcy) / ph / pvars[:, 1],
         jnp.log(gw / pw) / pvars[:, 2], jnp.log(gh / ph) / pvars[:, 3]],
        axis=-1)                                      # [N, P, 4]

    diff = jnp.abs(loc - target)
    smooth_l1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    loc_loss = jnp.sum(jnp.sum(smooth_l1, -1) * positive, -1)  # [N]

    # softmax CE per prior; positives use the matched label,
    # negatives the background class
    logp = jax.nn.log_softmax(conf, axis=-1)
    cls = jnp.where(positive, matched_label, background)
    ce = -jnp.take_along_axis(logp, cls[..., None], -1)[..., 0]  # [N,P]

    # hard negative mining: keep the neg_pos_ratio * npos highest-loss
    # negatives per image (rank via argsort-of-argsort, fixed shapes)
    npos = jnp.sum(positive, -1)                      # [N]
    neg_ce = jnp.where(positive, -jnp.inf, ce)
    order = jnp.argsort(-neg_ce, axis=-1)
    rank = jnp.argsort(order, axis=-1)
    n_neg = jnp.minimum(neg_pos_ratio * npos, n_prior - npos)
    negative = (~positive) & (rank < n_neg[:, None])
    conf_loss = jnp.sum(ce * (positive | negative), -1)  # [N]

    denom = jnp.maximum(npos.astype(loc.dtype), 1.0)
    loss = (loc_loss + conf_loss) / denom
    return {"Loss": [loss[:, None]]}


@register_op("detection_map", stop_gradient_op=True, jittable=False,
             nondiff_inputs=("DetectRes", "Label"))
def detection_map(ctx, ins, attrs):
    """Mean average precision over detection results (reference:
    gserver/evaluators/DetectionMAPEvaluator.cpp — 11point or integral
    AP, greedy best-IoU matching of score-ranked detections against
    per-image ground truth).

    DetectRes: ragged rows [label, score, xmin, ymin, xmax, ymax]
    (the detection_output op's layout minus the image column — image
    identity comes from the lod).  Label: ragged rows
    [label, xmin, ymin, xmax, ymax] (+ optional difficult flag last).
    MAP: [1] float.
    """
    overlap_threshold = float(attrs.get("overlap_threshold", 0.5))
    background = int(attrs.get("background_label_id", 0))
    ap_type = attrs.get("ap_type", "11point")
    evaluate_difficult = bool(attrs.get("evaluate_difficult", False))

    det_t, gt_t = ins["DetectRes"][0], ins["Label"][0]

    def unpack(t):
        if isinstance(t, RaggedTensor):
            return (np.asarray(t.values)[:int(np.asarray(t.nvalid))],
                    np.asarray(t.last_splits()))
        v = np.asarray(t)
        return v, np.asarray([0, v.shape[0]], np.int64)

    det, det_splits = unpack(det_t)
    gt, gt_splits = unpack(gt_t)
    n_img = len(det_splits) - 1
    has_difficult = gt.shape[1] >= 6

    # per-class pools: detections (img, score, box), gt (img, box, hard)
    by_class_det, by_class_gt = {}, {}
    for i in range(n_img):
        for r in det[det_splits[i]:det_splits[i + 1]]:
            c = int(r[0])
            if c != background:
                by_class_det.setdefault(c, []).append((i, float(r[1]),
                                                       r[2:6]))
        for r in gt[gt_splits[i]:gt_splits[i + 1]]:
            c = int(r[0])
            hard = bool(r[5]) if has_difficult else False
            if c != background:
                by_class_gt.setdefault(c, []).append((i, r[1:5], hard))

    def _iou_np(a, b):
        """numpy twin of _iou for this host op: [N,4]x[M,4] -> [N,M]."""
        area_a = np.maximum(a[:, 2] - a[:, 0], 0) * \
            np.maximum(a[:, 3] - a[:, 1], 0)
        area_b = np.maximum(b[:, 2] - b[:, 0], 0) * \
            np.maximum(b[:, 3] - b[:, 1], 0)
        ix = np.maximum(
            np.minimum(a[:, None, 2], b[None, :, 2])
            - np.maximum(a[:, None, 0], b[None, :, 0]), 0)
        iy = np.maximum(
            np.minimum(a[:, None, 3], b[None, :, 3])
            - np.maximum(a[:, None, 1], b[None, :, 1]), 0)
        inter = ix * iy
        union = area_a[:, None] + area_b[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)

    aps = []
    for c, gts in by_class_gt.items():
        npos = sum(1 for _, _, hard in gts
                   if evaluate_difficult or not hard)
        dets = sorted(by_class_det.get(c, []), key=lambda d: -d[1])
        # one IoU matrix per class (host numpy, no per-pair dispatch)
        iou_mat = None
        if dets:
            iou_mat = _iou_np(np.stack([d[2] for d in dets]),
                              np.stack([g[1] for g in gts]))
        gt_imgs = np.asarray([g[0] for g in gts])
        matched = set()
        tps, fps = [], []
        for di, (img, _score, _box) in enumerate(dets):
            # VOC protocol (reference DetectionMAPEvaluator): take the
            # best-IoU gt in the image regardless of matched state; a
            # duplicate detection of a matched gt is a FALSE POSITIVE,
            # never re-matched to a lesser gt
            cand = np.where(gt_imgs == img)[0]
            if cand.size == 0:
                tps.append(0.0)
                fps.append(1.0)
                continue
            ious = iou_mat[di, cand]
            k = int(np.argmax(ious))
            best_j, best_iou = int(cand[k]), float(ious[k])
            if best_iou >= overlap_threshold:
                hard = gts[best_j][2]
                if hard and not evaluate_difficult:
                    tps.append(0.0)  # difficult gt: neither tp nor fp
                    fps.append(0.0)
                elif best_j not in matched:
                    matched.add(best_j)
                    tps.append(1.0)
                    fps.append(0.0)
                else:  # duplicate detection
                    tps.append(0.0)
                    fps.append(1.0)
            else:
                tps.append(0.0)
                fps.append(1.0)
        if npos == 0:
            continue
        tp_cum = np.cumsum(tps)
        fp_cum = np.cumsum(fps)
        recall = tp_cum / npos
        precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
        if ap_type == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                mask = recall >= t
                ap += (precision[mask].max() if mask.any() else 0.0) / 11
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for p, r in zip(precision, recall):
                ap += p * (r - prev_r)
                prev_r = r
        aps.append(ap)
    m = float(np.mean(aps)) if aps else 0.0
    return {"MAP": [np.asarray([m], np.float32)]}
