"""MXU dtype policy helper for heavy-op kernels (see fluid/amp.py)."""

import jax.numpy as jnp

from ..utils import flags

__all__ = ["mxu_operands", "acc_kwargs", "conv_acc_kwargs", "ACC_DTYPE",
           "amp_result", "amp_harmonize", "keep_bf16_acts"]

ACC_DTYPE = jnp.float32


def acc_kwargs(*arrays):
    """preferred_element_type kwargs for a matmul/conv over `arrays`:
    force f32 accumulation only for bf16/f32 operands — integer and
    f64 matmuls keep their native exact accumulation."""
    if all(hasattr(a, "dtype") and
           a.dtype in (jnp.bfloat16, jnp.float32) for a in arrays):
        return {"preferred_element_type": ACC_DTYPE}
    return {}


def conv_acc_kwargs(*arrays):
    """acc_kwargs for convolutions.  Unlike dot_general, whose transpose
    rule casts for mixed dtypes, lax.conv_general_dilated's transpose
    feeds the f32 cotangent of a preferred_element_type=f32 conv back
    into a conv against the saved bf16 operand and rejects the mix.  So
    bf16 convs stay uniform-bf16 end to end (forward and both transpose
    convs); the MXU accumulates bf16 convs in f32 internally regardless,
    only the output rounds to bf16."""
    if any(hasattr(a, "dtype") and a.dtype == jnp.bfloat16 for a in arrays):
        return {}
    return acc_kwargs(*arrays)


def keep_bf16_acts():
    return (flags.get_flag("amp_bf16") and flags.get_flag("amp_bf16_act"))


def amp_result(out, ref_dtype):
    """Cast a heavy-op result to its reference dtype — unless the
    bf16-activation policy is on, in which case an f32-reference result
    stays (or becomes) bf16 so the downstream elementwise/norm chain
    reads and writes half the bytes.  Statistics, losses, and master
    weights never come through here."""
    if keep_bf16_acts() and ref_dtype == jnp.float32:
        return out if out.dtype == jnp.bfloat16 else out.astype(jnp.bfloat16)
    return out.astype(ref_dtype)


def amp_harmonize(x, y):
    """Under the bf16-activation policy, a binary elementwise op over a
    (bf16 activation, f32 side-input) pair computes in bf16 — without
    this, jnp promotion re-materializes the full activation in f32
    (e.g. the conv bias-add against an f32 bias parameter)."""
    if not keep_bf16_acts():
        return x, y
    if x.dtype == jnp.bfloat16 and y.dtype == jnp.float32:
        return x, y.astype(jnp.bfloat16)
    if x.dtype == jnp.float32 and y.dtype == jnp.bfloat16:
        return x.astype(jnp.bfloat16), y
    return x, y


def mxu_operands(*arrays):
    """Under FLAGS_amp_bf16, cast f32 matmul/conv operands to bf16 (the
    MXU's fast dtype); accumulation stays f32 via
    preferred_element_type at the call site."""
    if not flags.get_flag("amp_bf16"):
        return arrays
    return tuple(a.astype(jnp.bfloat16)
                 if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                 for a in arrays)
