"""Kernels completing the v2 layer zoo (reference:
paddle/gserver/layers/*.cpp behaviors exposed through
trainer_config_helpers/layers.py — hsigmoid, bilinear_interp,
sampling_id, kmax_seq_score, sub_nested_seq, scale_sub_region,
lambda_cost, cross_entropy selfnorm/multi-binary variants, rotate,
out_prod, linear_comb).

All dense kernels are pure JAX (jit-fused); ragged selectors that
restructure LoD are host ops like the rest of the sequence family.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.ragged import RaggedTensor


@register_op("bilinear_interp")
def bilinear_interp(ctx, ins, attrs):
    """reference: bilinear_interp_op.cc / BilinearInterpLayer.cpp —
    NCHW bilinear resize, lowered to jax.image.resize."""
    x = ins["X"][0]
    out_h = int(attrs["out_h"])
    out_w = int(attrs["out_w"])
    n, c = x.shape[0], x.shape[1]
    out = jax.image.resize(x, (n, c, out_h, out_w), method="bilinear")
    return {"Out": [out.astype(x.dtype)]}


def _hsigmoid_paths(num_classes, labels):
    """Complete-binary-tree bit codes (reference: MatrixBitCodeFunctor,
    matrix_bit_code.h).  Returns (node index [B, L], bit [B, L],
    mask [B, L]) with L = max path length."""
    code = labels + num_classes                  # leaves start at 2^?
    max_len = int(np.ceil(np.log2(max(num_classes, 2))))
    lengths = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(
        jnp.int32)
    js = jnp.arange(max_len, dtype=jnp.int32)
    valid = js[None, :] < lengths[:, None]
    shift_idx = lengths[:, None] - js[None, :]
    idx = (code[:, None] >> jnp.maximum(shift_idx, 1)) - 1
    bit = (code[:, None] >> jnp.maximum(shift_idx - 1, 0)) & 1
    idx = jnp.clip(idx, 0, num_classes - 2)
    return idx, bit.astype(jnp.float32), valid.astype(jnp.float32)


@register_op("hsigmoid", nondiff_inputs=("Label",))
def hsigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid cost over a complete binary tree
    (reference: hierarchical_sigmoid_op / HierarchicalSigmoidLayer.cpp).
    cost = sum_path log(1 + exp(x)) - bit * x, x = w_node . input + b."""
    x = ins["X"][0]                              # [B, D]
    w = ins["W"][0]                              # [num_classes-1, D]
    label = jnp.reshape(ins["Label"][0], (-1,)).astype(jnp.int32)
    bias = ins.get("Bias", [None])[0]            # [1, num_classes-1]
    num_classes = int(attrs["num_classes"])

    idx, bit, mask = _hsigmoid_paths(num_classes, label)   # [B, L]
    w_path = w[idx]                              # [B, L, D]
    logits = jnp.einsum("bld,bd->bl", w_path, x)
    if bias is not None:
        logits = logits + jnp.reshape(bias, (-1,))[idx]
    # log(1+e^x) - bit*x, numerically stable softplus
    cost = (jax.nn.softplus(logits) - bit * logits) * mask
    return {"Out": [jnp.sum(cost, axis=1, keepdims=True)]}


@register_op("sampling_id", stop_gradient_op=True, uses_rng=True)
def sampling_id(ctx, ins, attrs):
    """Sample one id per row from a probability matrix (reference:
    SamplingIdLayer.cpp)."""
    p = ins["X"][0]
    key = ctx.next_rng()
    logits = jnp.log(jnp.maximum(p, 1e-30))
    ids = jax.random.categorical(key, logits, axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register_op("kmax_seq_score", stop_gradient_op=True, jittable=False)
def kmax_seq_score(ctx, ins, attrs):
    """Top-k score indices within each sequence (reference:
    KmaxSeqScoreLayer.cpp).  Output: int32 sequence of k (or fewer)
    in-sequence indices per input sequence."""
    x = ins["X"][0]
    k = int(attrs["beam_size"])
    vals = np.asarray(x.values).reshape(-1)
    splits = np.asarray(x.last_splits())
    out_rows, out_splits = [], [0]
    for i in range(len(splits) - 1):
        seg = vals[splits[i]:splits[i + 1]]
        kk = min(k, len(seg))
        top = np.argsort(-seg, kind="stable")[:kk]
        out_rows.append(top.astype(np.int32))
        out_splits.append(out_splits[-1] + kk)
    flat = (np.concatenate(out_rows) if out_rows
            else np.zeros((0,), np.int32)).reshape(-1, 1)
    return {"Out": [RaggedTensor(jnp.asarray(flat),
                                 [np.asarray(out_splits, np.int32)])]}


@register_op("sub_nested_seq", stop_gradient_op=True, jittable=False)
def sub_nested_seq(ctx, ins, attrs):
    """Select inner sequences of a nested (lod_level 2) sequence by
    per-sample indices (reference: SubNestedSequenceLayer.cpp)."""
    x = ins["X"][0]
    sel = ins["S"][0]
    outer = np.asarray(x.row_splits[0])
    inner = np.asarray(x.row_splits[-1])
    vals = np.asarray(x.values)
    sel_vals = np.asarray(sel.values).reshape(-1).astype(np.int64)
    sel_splits = np.asarray(sel.last_splits())

    segs, splits = [], [0]
    for b in range(len(outer) - 1):
        picks = sel_vals[sel_splits[b]:sel_splits[b + 1]]
        for j in picks:
            ii = outer[b] + int(j)
            seg = vals[inner[ii]:inner[ii + 1]]
            segs.append(seg)
            splits.append(splits[-1] + len(seg))
    flat = np.concatenate(segs, 0) if segs else vals[:0]
    return {"Out": [RaggedTensor(jnp.asarray(flat),
                                 [np.asarray(splits, np.int32)])]}


@register_op("scale_sub_region", nondiff_inputs=("Indices",))
def scale_sub_region(ctx, ins, attrs):
    """Scale a per-sample [C,H,W] sub-region by `value` (reference:
    ScaleSubRegionLayer.cpp / scale_sub_region_op).  Indices rows are
    1-based [c0, c1, h0, h1, w0, w1] inclusive ranges."""
    x = ins["X"][0]
    idx = ins["Indices"][0].astype(jnp.int32)
    value = jnp.asarray(attrs.get("value", 1.0), x.dtype)
    _, C, H, W = x.shape
    c = jnp.arange(C, dtype=jnp.int32)
    h = jnp.arange(H, dtype=jnp.int32)
    w = jnp.arange(W, dtype=jnp.int32)
    in_c = (c[None, :] >= idx[:, 0:1] - 1) & (c[None, :] <= idx[:, 1:2] - 1)
    in_h = (h[None, :] >= idx[:, 2:3] - 1) & (h[None, :] <= idx[:, 3:4] - 1)
    in_w = (w[None, :] >= idx[:, 4:5] - 1) & (w[None, :] <= idx[:, 5:6] - 1)
    mask = (in_c[:, :, None, None] & in_h[:, None, :, None] &
            in_w[:, None, None, :])
    return {"Out": [jnp.where(mask, x * value, x)]}


@register_op("lambda_cost", nondiff_inputs=("Label",))
def lambda_cost(ctx, ins, attrs):
    """LambdaRank listwise cost over each sequence (reference:
    LambdaCost.cpp): pairwise logistic loss weighted by |delta NDCG|
    truncated at NDCG_num."""
    from .sequence import _seg_pos

    score = ins["Score"][0]
    label = ins["Label"][0]
    ndcg_num = int(attrs.get("NDCG_num", 5))
    s = jnp.reshape(score.values, (-1,))
    y = jnp.reshape(label.values, (-1,)).astype(jnp.float32)
    seg, inseq, valid = _seg_pos(score)
    T = s.shape[0]

    same = (seg[:, None] == seg[None, :]) & valid[:, None] & valid[None, :]
    # ideal DCG per sequence from sorted labels (approximate via rank of
    # each item's label within its sequence by value ordering)
    gain = (jnp.power(2.0, y) - 1.0)
    disc_pos = 1.0 / jnp.log2(2.0 + inseq.astype(jnp.float32))
    dcg_w = jnp.where(inseq < ndcg_num, disc_pos, 0.0)
    # |delta NDCG| for swapping i,j approximated with position discounts
    dw = jnp.abs(gain[:, None] - gain[None, :]) * \
        jnp.abs(dcg_w[:, None] - dcg_w[None, :])
    diff = s[:, None] - s[None, :]
    pair_loss = jax.nn.softplus(-diff)           # log(1+e^{-(si-sj)})
    rel = (y[:, None] > y[None, :]) & same
    loss_mat = jnp.where(rel, dw * pair_loss, 0.0)
    per_item = jnp.sum(loss_mat, axis=1, keepdims=True)
    return {"Out": [RaggedTensor(per_item, score.row_splits,
                                 score.nvalid)]}


@register_op("cross_entropy_selfnorm", nondiff_inputs=("Label",))
def cross_entropy_selfnorm(ctx, ins, attrs):
    """CE plus alpha * ln(Z)^2 self-normalization (reference:
    CostLayer.cpp CrossEntropyWithSelfNorm)."""
    p = ins["X"][0]
    pv = p.values if isinstance(p, RaggedTensor) else p
    label = ins["Label"][0]
    lv = label.values if isinstance(label, RaggedTensor) else label
    lv = jnp.reshape(lv, (-1,)).astype(jnp.int32)
    alpha = float(attrs.get("softmax_selfnorm_alpha", 0.1))
    z = jnp.sum(pv, axis=1)
    picked = pv[jnp.arange(pv.shape[0]), lv]
    cost = -jnp.log(jnp.maximum(picked / jnp.maximum(z, 1e-30), 1e-30))
    cost = cost + alpha * jnp.square(jnp.log(jnp.maximum(z, 1e-30)))
    cost = cost[:, None]
    if isinstance(p, RaggedTensor):
        return {"Out": [RaggedTensor(cost, p.row_splits, p.nvalid)]}
    return {"Out": [cost]}


@register_op("multi_binary_label_cross_entropy",
             nondiff_inputs=("Label",))
def multi_binary_label_cross_entropy(ctx, ins, attrs):
    """Multi-label binary CE on probabilities (reference: CostLayer.cpp
    MultiBinaryLabelCrossEntropy)."""
    p = ins["X"][0]
    pv = p.values if isinstance(p, RaggedTensor) else p
    y = ins["Label"][0]
    yv = (y.values if isinstance(y, RaggedTensor) else y).astype(
        pv.dtype)
    eps = 1e-8
    cost = -(yv * jnp.log(pv + eps) + (1 - yv) * jnp.log(1 - pv + eps))
    out = jnp.sum(cost, axis=1, keepdims=True)
    if isinstance(p, RaggedTensor):
        return {"Out": [RaggedTensor(out, p.row_splits, p.nvalid)]}
    return {"Out": [out]}


@register_op("rotate")
def rotate(ctx, ins, attrs):
    """Rotate each [C, H, W] feature map 90 degrees counter-clockwise
    (reference: RotateLayer.cpp).  Input arrives flattened [B, C*H*W]."""
    x = ins["X"][0]
    c, h, w = (int(attrs["channels"]), int(attrs["height"]),
               int(attrs["width"]))
    maps = jnp.reshape(x, (-1, c, h, w))
    rot = jnp.flip(jnp.swapaxes(maps, 2, 3), axis=2)   # ccw 90
    return {"Out": [jnp.reshape(rot, (x.shape[0], -1))]}


@register_op("out_prod")
def out_prod(ctx, ins, attrs):
    """Row-wise outer product, flattened (reference:
    OuterProdLayer.cpp)."""
    a, b = ins["X"][0], ins["Y"][0]
    out = jnp.einsum("bi,bj->bij", a, b)
    return {"Out": [jnp.reshape(out, (a.shape[0], -1))]}


@register_op("linear_comb")
def linear_comb(ctx, ins, attrs):
    """out = sum_k w[:, k] * x[:, k*size:(k+1)*size] (reference:
    LinearChainCombLayer / ConvexCombinationLayer.cpp)."""
    x = ins["X"][0]
    w = ins["W"][0]
    size = int(attrs["size"])
    k = w.shape[1]
    xs = jnp.reshape(x, (x.shape[0], k, size))
    return {"Out": [jnp.einsum("bk,bks->bs", w, xs)]}
