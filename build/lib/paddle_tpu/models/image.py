"""Image-model zoo (program builders).

TPU-native re-implementations of the reference benchmark/book CNNs
(reference: benchmark/paddle/image/{alexnet,vgg,resnet,googlenet,
smallnet_mnist_cifar}.py, tests/book/test_recognize_digits.py,
tests/book/test_image_classification_train.py).  All builders take an
`image` Variable in NCHW and return logits (pre-softmax) unless noted.

Design notes for TPU: convs and matmuls lower to XLA convolution /
dot-general on the MXU; batch_norm lowers to a fused normalize; nothing
here hand-schedules — the whole block is jitted by the Executor.
"""

from ..fluid import layers, nets
from ..fluid.param_attr import ParamAttr


# ---------------------------------------------------------------------------
# Small nets (MNIST / CIFAR quick)
# ---------------------------------------------------------------------------

def mlp(image, class_dim=10, hidden_sizes=(128, 64), act="relu"):
    """MLP from the reference MNIST book test
    (reference: tests/book/test_recognize_digits.py mlp variant)."""
    hidden = image
    for size in hidden_sizes:
        hidden = layers.fc(input=hidden, size=size, act=act)
    return layers.fc(input=hidden, size=class_dim, act=None)


def lenet5(image, class_dim=10):
    """Conv net from the reference MNIST book test
    (reference: tests/book/test_recognize_digits.py conv variant)."""
    conv1 = nets.simple_img_conv_pool(
        input=image, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv2 = nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return layers.fc(input=conv2, size=class_dim, act=None)


def smallnet_mnist_cifar(image, class_dim=10):
    """The 'SmallNet' CIFAR-quick benchmark config
    (reference: benchmark/paddle/image/smallnet_mnist_cifar.py —
    conv5(pad2)+maxpool3(s2,p1), conv5(pad2)+avgpool3(s2,p1),
    conv3(pad1)+avgpool3(s2,p1), fc64, fc; padded so 32x32 inputs
    survive all three stages)."""
    t = layers.conv2d(input=image, num_filters=32, filter_size=5,
                      padding=2, act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2,
                      pool_padding=1, pool_type="max")
    t = layers.conv2d(input=t, num_filters=32, filter_size=5,
                      padding=2, act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2,
                      pool_padding=1, pool_type="avg")
    t = layers.conv2d(input=t, num_filters=64, filter_size=3,
                      padding=1, act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2,
                      pool_padding=1, pool_type="avg")
    hidden = layers.fc(input=t, size=64, act="relu")
    return layers.fc(input=hidden, size=class_dim, act=None)


# ---------------------------------------------------------------------------
# AlexNet (reference: benchmark/paddle/image/alexnet.py)
# ---------------------------------------------------------------------------

def alexnet(image, class_dim=1000, use_lrn=True):
    t = layers.conv2d(input=image, num_filters=96, filter_size=11,
                      stride=4, padding=1, act="relu")
    if use_lrn:
        t = layers.lrn(input=t, n=5, alpha=0.0001, beta=0.75)
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = layers.conv2d(input=t, num_filters=256, filter_size=5, padding=2,
                      groups=2, act="relu")
    if use_lrn:
        t = layers.lrn(input=t, n=5, alpha=0.0001, beta=0.75)
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = layers.conv2d(input=t, num_filters=384, filter_size=3, padding=1,
                      act="relu")
    t = layers.conv2d(input=t, num_filters=384, filter_size=3, padding=1,
                      groups=2, act="relu")
    t = layers.conv2d(input=t, num_filters=256, filter_size=3, padding=1,
                      groups=2, act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = layers.fc(input=t, size=4096, act="relu")
    t = layers.dropout(x=t, dropout_prob=0.5)
    t = layers.fc(input=t, size=4096, act="relu")
    t = layers.dropout(x=t, dropout_prob=0.5)
    return layers.fc(input=t, size=class_dim, act=None)


# ---------------------------------------------------------------------------
# VGG (reference: benchmark/paddle/image/vgg.py,
#      tests/book/test_image_classification_train.py vgg16_bn_drop)
# ---------------------------------------------------------------------------

def vgg(image, class_dim=1000, depth=16, with_bn=False, drop_rate=0.0,
        fc_size=4096):
    cfg = {
        11: [1, 1, 2, 2, 2],
        13: [2, 2, 2, 2, 2],
        16: [2, 2, 3, 3, 3],
        19: [2, 2, 4, 4, 4],
    }[depth]
    channels = [64, 128, 256, 512, 512]

    t = image
    for n_convs, ch in zip(cfg, channels):
        t = nets.img_conv_group(
            input=t, conv_num_filter=[ch] * n_convs, pool_size=2,
            pool_stride=2, conv_filter_size=3, conv_act="relu",
            conv_with_batchnorm=with_bn,
            conv_batchnorm_drop_rate=drop_rate)

    t = layers.fc(input=t, size=fc_size, act="relu")
    if drop_rate:
        t = layers.dropout(x=t, dropout_prob=drop_rate)
    t = layers.fc(input=t, size=fc_size, act="relu")
    if drop_rate:
        t = layers.dropout(x=t, dropout_prob=drop_rate)
    return layers.fc(input=t, size=class_dim, act=None)


def vgg16(image, class_dim=1000, **kw):
    return vgg(image, class_dim, depth=16, **kw)


def vgg19(image, class_dim=1000, **kw):
    return vgg(image, class_dim, depth=19, **kw)


# ---------------------------------------------------------------------------
# ResNet (reference: benchmark/paddle/image/resnet.py — 50/101/152 via
# bottleneck blocks)
# ---------------------------------------------------------------------------

def _conv_bn(input, ch_out, filter_size, stride, padding, act="relu"):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act)


def _shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return _conv_bn(input, ch_out, 1, stride, 0, act=None)
    return input


def _basic_block(input, ch_out, stride):
    short = _shortcut(input, ch_out, stride)
    conv1 = _conv_bn(input, ch_out, 3, stride, 1)
    conv2 = _conv_bn(conv1, ch_out, 3, 1, 1, act=None)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def _bottleneck_block(input, ch_out, stride):
    short = _shortcut(input, ch_out * 4, stride)
    conv1 = _conv_bn(input, ch_out, 1, stride, 0)
    conv2 = _conv_bn(conv1, ch_out, 3, 1, 1)
    conv3 = _conv_bn(conv2, ch_out * 4, 1, 1, 0, act=None)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def _layer_group(block_fn, input, ch_out, count, stride):
    t = block_fn(input, ch_out, stride)
    for _ in range(count - 1):
        t = block_fn(t, ch_out, 1)
    return t


def resnet(image, class_dim=1000, depth=50):
    """ImageNet ResNet (reference: benchmark/paddle/image/resnet.py)."""
    cfg = {
        18: (_basic_block, [2, 2, 2, 2]),
        34: (_basic_block, [3, 4, 6, 3]),
        50: (_bottleneck_block, [3, 4, 6, 3]),
        101: (_bottleneck_block, [3, 4, 23, 3]),
        152: (_bottleneck_block, [3, 8, 36, 3]),
    }
    block_fn, counts = cfg[depth]

    t = _conv_bn(image, 64, 7, 2, 3)
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2, pool_padding=1)
    for i, (ch, count) in enumerate(zip([64, 128, 256, 512], counts)):
        t = _layer_group(block_fn, t, ch, count, 1 if i == 0 else 2)
    t = layers.pool2d(input=t, pool_size=7, pool_type="avg",
                      global_pooling=True)
    return layers.fc(input=t, size=class_dim, act=None)


def resnet50(image, class_dim=1000):
    return resnet(image, class_dim, depth=50)


def resnet101(image, class_dim=1000):
    return resnet(image, class_dim, depth=101)


def resnet_cifar10(image, class_dim=10, depth=32):
    """CIFAR ResNet (reference: tests/book/
    test_image_classification_train.py resnet_cifar10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    t = _conv_bn(image, 16, 3, 1, 1)
    t = _layer_group(_basic_block, t, 16, n, 1)
    t = _layer_group(_basic_block, t, 32, n, 2)
    t = _layer_group(_basic_block, t, 64, n, 2)
    t = layers.pool2d(input=t, pool_size=8, pool_type="avg",
                      global_pooling=True)
    return layers.fc(input=t, size=class_dim, act=None)


# ---------------------------------------------------------------------------
# GoogLeNet v1 (reference: benchmark/paddle/image/googlenet.py)
# ---------------------------------------------------------------------------

def _inception(input, ch1, ch3r, ch3, ch5r, ch5, proj):
    b1 = layers.conv2d(input=input, num_filters=ch1, filter_size=1,
                       act="relu")
    b2 = layers.conv2d(input=input, num_filters=ch3r, filter_size=1,
                       act="relu")
    b2 = layers.conv2d(input=b2, num_filters=ch3, filter_size=3, padding=1,
                       act="relu")
    b3 = layers.conv2d(input=input, num_filters=ch5r, filter_size=1,
                       act="relu")
    b3 = layers.conv2d(input=b3, num_filters=ch5, filter_size=5, padding=2,
                       act="relu")
    b4 = layers.pool2d(input=input, pool_size=3, pool_stride=1,
                       pool_padding=1)
    b4 = layers.conv2d(input=b4, num_filters=proj, filter_size=1,
                       act="relu")
    return layers.concat(input=[b1, b2, b3, b4], axis=1)


def googlenet(image, class_dim=1000):
    t = layers.conv2d(input=image, num_filters=64, filter_size=7, stride=2,
                      padding=3, act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)
    t = layers.conv2d(input=t, num_filters=64, filter_size=1, act="relu")
    t = layers.conv2d(input=t, num_filters=192, filter_size=3, padding=1,
                      act="relu")
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = _inception(t, 64, 96, 128, 16, 32, 32)       # 3a
    t = _inception(t, 128, 128, 192, 32, 96, 64)     # 3b
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = _inception(t, 192, 96, 208, 16, 48, 64)      # 4a
    t = _inception(t, 160, 112, 224, 24, 64, 64)     # 4b
    t = _inception(t, 128, 128, 256, 24, 64, 64)     # 4c
    t = _inception(t, 112, 144, 288, 32, 64, 64)     # 4d
    t = _inception(t, 256, 160, 320, 32, 128, 128)   # 4e
    t = layers.pool2d(input=t, pool_size=3, pool_stride=2)

    t = _inception(t, 256, 160, 320, 32, 128, 128)   # 5a
    t = _inception(t, 384, 192, 384, 48, 128, 128)   # 5b
    t = layers.pool2d(input=t, pool_size=7, pool_type="avg",
                      global_pooling=True)
    t = layers.dropout(x=t, dropout_prob=0.4)
    return layers.fc(input=t, size=class_dim, act=None)
