"""Text/sequence model zoo (program builders).

TPU-native re-implementations of the reference RNN benchmark and book
models (reference: benchmark/paddle/rnn/rnn.py,
tests/book/test_understand_sentiment_*.py, tests/book/test_word2vec.py).
Sequence inputs are RaggedTensors (the LoD equivalent) flowing through
sequence_* ops.
"""

from ..fluid import layers, nets


def stacked_lstm_text_classifier(data, dict_dim, class_dim=2,
                                 emb_dim=128, hid_dim=128, stacked_num=2):
    """Stacked-LSTM sentiment/text classifier (reference:
    benchmark/paddle/rnn/rnn.py — emb + 2 lstm layers + pooled fc;
    tests/book/test_understand_sentiment_dynamic_lstm.py stacked variant).

    `data` is a ragged int64 sequence of word ids; returns softmax
    probabilities [batch, class_dim].
    """
    emb = layers.embedding(input=data, size=[dict_dim, emb_dim])

    fc1 = layers.fc(input=emb, size=hid_dim * 4)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim * 4)

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = layers.fc(input=inputs, size=hid_dim * 4)
        lstm, cell = layers.dynamic_lstm(input=fc, size=hid_dim * 4,
                                         is_reverse=False)
        inputs = [fc, lstm]

    fc_last = layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type="max")
    return layers.fc(input=[fc_last, lstm_last], size=class_dim,
                     act="softmax")


def conv_text_classifier(data, dict_dim, class_dim=2, emb_dim=128,
                         hid_dim=128):
    """Sequence-conv text classifier (reference:
    tests/book/test_understand_sentiment_conv.py convolution_net)."""
    emb = layers.embedding(input=data, size=[dict_dim, emb_dim])
    conv_3 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                     filter_size=3, act="tanh",
                                     pool_type="max")
    conv_4 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                     filter_size=4, act="tanh",
                                     pool_type="max")
    return layers.fc(input=[conv_3, conv_4], size=class_dim, act="softmax")


def seq2seq(src, trg_in, src_dict_size, trg_dict_size, emb_dim=32,
            hidden_dim=32, encoder_depth=1):
    """Encoder-decoder translation model, teacher-forced training path
    (reference: tests/book/test_machine_translation.py — GRU/LSTM
    encoder, DynamicRNN decoder seeded from the encoder's last state).

    Returns per-step softmax over the target dictionary (ragged, aligned
    with ``trg_in``).
    """
    src_emb = layers.embedding(input=src, size=[src_dict_size, emb_dim])
    enc_proj = layers.fc(input=src_emb, size=hidden_dim * 4)
    enc_hidden, _ = layers.dynamic_lstm(input=enc_proj,
                                        size=hidden_dim * 4)
    for _ in range(1, encoder_depth):
        enc_proj = layers.fc(input=enc_hidden, size=hidden_dim * 4)
        enc_hidden, _ = layers.dynamic_lstm(input=enc_proj,
                                            size=hidden_dim * 4)
    enc_last = layers.sequence_last_step(input=enc_hidden)  # [B, hid]

    trg_emb = layers.embedding(input=trg_in, size=[trg_dict_size, emb_dim])

    rnn = layers.DynamicRNN()
    with rnn.block():
        cur = rnn.step_input(trg_emb)
        mem = rnn.memory(init=enc_last)
        out = layers.fc(input=[cur, mem], size=hidden_dim, act="tanh")
        prob = layers.fc(input=out, size=trg_dict_size, act="softmax")
        rnn.update_memory(mem, out)
        rnn.step_output(prob)
    return rnn.outputs[0]


def word2vec_ngram(words, dict_size, emb_dim=32, hidden_size=256,
                   shared_embedding=True):
    """N-gram neural language model (reference:
    tests/book/test_word2vec.py — 4 context words predict the next).

    `words` is a list of dense int64 Variables [batch, 1]; returns
    softmax probabilities over the dictionary.
    """
    embs = []
    shared_name = "shared_w" if shared_embedding else None
    for i, w in enumerate(words):
        attr = shared_name if shared_embedding else None
        embs.append(layers.embedding(
            input=w, size=[dict_size, emb_dim], param_attr=attr))
    concat = layers.concat(input=embs, axis=1)
    hidden = layers.fc(input=concat, size=hidden_size, act="sigmoid")
    return layers.fc(input=hidden, size=dict_size, act="softmax")
