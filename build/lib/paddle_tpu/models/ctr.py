"""CTR models: DeepFM over sparse categorical fields.

The reference's CTR workload (BASELINE.json configs[5]) is the
lookup_table sparse-gradient path (reference:
paddle/operators/lookup_table_op.cc SelectedRows grads) trained against
the pserver's sparse row updates (reference:
paddle/pserver/ParameterServer2.h:510 getParameterSparse,
SparseRemoteParameterUpdater).  DeepFM [Guo et al. 2017] is the
standard CTR architecture on that machinery: a factorization machine
and a deep MLP sharing one set of field embeddings.

TPU notes: the FM second-order term uses the O(F·D) identity
0.5 * ((Σ_f v_f)² − Σ_f v_f²) — two reductions over the [batch,
fields, dim] embedding block, which XLA fuses into one sweep — rather
than the O(F²·D) pairwise products.  All shapes are static; the only
sparsity is in the *gradient* representation (SelectedRows), which is
exactly what ships to the pserver.
"""

from ..fluid import layers

__all__ = ["deepfm", "deepfm_ctr"]


def deepfm(field_ids, num_features, num_fields, embed_dim=8,
           hidden_sizes=(64, 32), is_sparse=True):
    """DeepFM logits from a [batch, num_fields] int64 id tensor.

    Ids index one shared feature space (offset per field upstream, the
    usual CTR encoding).  Returns the [batch, 1] pre-sigmoid logit:
    first-order + FM second-order + deep MLP.
    """
    # shared second-order embeddings: [b, F, D]
    emb = layers.embedding(input=field_ids,
                           size=[num_features, embed_dim],
                           is_sparse=is_sparse)
    # first-order per-feature weights: [b, F, 1] -> [b, 1]
    first = layers.embedding(input=field_ids, size=[num_features, 1],
                             is_sparse=is_sparse)
    first_sum = layers.reduce_sum(first, dim=1)

    # FM second order: 0.5 * ((sum_f v)^2 - sum_f v^2), summed over D
    sum_v = layers.reduce_sum(emb, dim=1)                    # [b, D]
    sum_sq = layers.square(sum_v)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=1)    # [b, D]
    second = layers.scale(
        layers.reduce_sum(
            layers.elementwise_sub(x=sum_sq, y=sq_sum),
            dim=1, keep_dim=True),
        scale=0.5)                                           # [b, 1]

    # deep tower over the flattened embedding block
    deep = layers.reshape(x=emb,
                          shape=[-1, num_fields * embed_dim])
    for width in hidden_sizes:
        deep = layers.fc(input=deep, size=width, act="relu")
    deep_out = layers.fc(input=deep, size=1, act=None)

    return layers.elementwise_add(
        x=layers.elementwise_add(x=first_sum, y=second), y=deep_out)


def deepfm_ctr(field_ids, label, num_features, num_fields, embed_dim=8,
               hidden_sizes=(64, 32), is_sparse=True):
    """Full CTR head: (avg_logloss, predict_prob) for a float32 [b, 1]
    click label."""
    logit = deepfm(field_ids, num_features, num_fields,
                   embed_dim=embed_dim, hidden_sizes=hidden_sizes,
                   is_sparse=is_sparse)
    loss = layers.sigmoid_cross_entropy_with_logits(x=logit, label=label)
    avg_loss = layers.mean(x=loss)
    predict = layers.sigmoid(x=logit)
    return avg_loss, predict
