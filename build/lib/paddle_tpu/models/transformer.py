"""Transformer language model (functional, TPU-first).

Beyond the reference's capability set (its attention is composed ops —
reference: python/paddle/v2/fluid/nets.py:338
scaled_dot_product_attention); this is the long-context flagship: a
GPT-style decoder whose attention can run dense, flash (pallas), ring
(sequence-parallel over ICI), or Ulysses (all-to-all), with weights
laid out for dp x mp x sp meshes via GSPMD sharding constraints.

Pure functions over a params pytree (idiomatic JAX, not the fluid
program path — both coexist; the fluid stack covers the reference API,
this covers scale).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..kernels.flash_attention import flash_attention, reference_attention
from ..parallel.ring import ring_attention, ulysses_attention, sp_shard_map

__all__ = ["init_transformer", "transformer_forward", "transformer_loss",
           "transformer_param_specs", "TransformerMeta"]


@jax.tree_util.register_static
@functools.total_ordering
class TransformerMeta:
    """Static (non-traced) model config carried inside the params dict."""

    def __init__(self, n_layer, n_head, d_model):
        self.n_layer = n_layer
        self.n_head = n_head
        self.d_model = d_model

    def _key(self):
        return (self.n_layer, self.n_head, self.d_model)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, TransformerMeta) and \
            self._key() == other._key()

    def __lt__(self, other):
        return self._key() < other._key()

    def __getitem__(self, k):  # dict-style access compat
        return getattr(self, k)


def init_transformer(rng, vocab_size, n_layer=2, n_head=4, d_model=128,
                     d_ff=None, max_len=2048, dtype=np.float32):
    """Returns a params dict of numpy arrays."""
    if d_ff is None:
        d_ff = 4 * d_model
    rs = np.random.RandomState(rng) if isinstance(rng, int) else rng
    sd = 0.02

    def w(*shape):
        return (rs.randn(*shape) * sd).astype(dtype)

    params = {
        "wte": w(vocab_size, d_model),
        "wpe": w(max_len, d_model),
        "ln_f.g": np.ones(d_model, dtype),
        "ln_f.b": np.zeros(d_model, dtype),
    }
    for i in range(n_layer):
        p = "h%d." % i
        params.update({
            p + "ln1.g": np.ones(d_model, dtype),
            p + "ln1.b": np.zeros(d_model, dtype),
            p + "qkv.w": w(d_model, 3 * d_model),
            p + "qkv.b": np.zeros(3 * d_model, dtype),
            p + "proj.w": w(d_model, d_model),
            p + "proj.b": np.zeros(d_model, dtype),
            p + "ln2.g": np.ones(d_model, dtype),
            p + "ln2.b": np.zeros(d_model, dtype),
            p + "fc.w": w(d_model, d_ff),
            p + "fc.b": np.zeros(d_ff, dtype),
            p + "out.w": w(d_ff, d_model),
            p + "out.b": np.zeros(d_model, dtype),
        })
    params["_meta"] = TransformerMeta(n_layer=n_layer, n_head=n_head,
                                      d_model=d_model)
    return params


def transformer_param_specs(params, mp_axis="mp"):
    """PartitionSpecs for tensor parallelism: qkv/fc shard columns
    (heads / ff) over mp, proj/out shard rows — the Megatron layout, so
    each block needs one psum (inserted by GSPMD) per matmul pair."""
    specs = {}
    for name, v in params.items():
        if name == "_meta":
            continue
        spec = P()
        if name.endswith(("qkv.w", "fc.w")):
            spec = P(None, mp_axis)
        elif name.endswith(("qkv.b", "fc.b")):
            spec = P(mp_axis)
        elif name.endswith(("proj.w", "out.w")):
            spec = P(mp_axis, None)
        elif name == "wte":
            spec = P(mp_axis, None)
        specs[name] = spec
    return specs


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _attend(q, k, v, attn_impl, mesh, causal, sp_axis="sp"):
    """q,k,v: [B, H, T, D] (T globally; sharded over sp inside)."""
    if attn_impl == "dense":
        return reference_attention(q, k, v, None, causal)
    if attn_impl == "flash":
        return flash_attention(q, k, v, None, causal)
    if attn_impl == "ring":
        fn = sp_shard_map(
            lambda q, k, v: ring_attention(q, k, v, sp_axis, None,
                                           causal), mesh,
            axis_name=sp_axis)
        return fn(q, k, v)
    if attn_impl == "ulysses":
        fn = sp_shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, sp_axis, None,
                                              causal), mesh,
            axis_name=sp_axis)
        return fn(q, k, v)
    raise ValueError("unknown attn_impl %r" % attn_impl)


def transformer_forward(params, tokens, attn_impl="flash", mesh=None,
                        causal=True, sp_axis="sp"):
    """tokens: int32 [B, T] -> logits [B, T, vocab]."""
    meta = params["_meta"]
    H = meta["n_head"]
    d = meta["d_model"]
    B, T = tokens.shape

    x = params["wte"][tokens] + params["wpe"][:T]
    if mesh is not None and sp_axis in mesh.shape:
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, sp_axis, None)))

    for i in range(meta["n_layer"]):
        p = "h%d." % i
        h = _ln(x, params[p + "ln1.g"], params[p + "ln1.b"])
        qkv = h @ params[p + "qkv.w"] + params[p + "qkv.b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B,T,d] -> [B,H,T,hd]
            return t.reshape(B, T, H, d // H).transpose(0, 2, 1, 3)

        o = _attend(heads(q), heads(k), heads(v), attn_impl, mesh,
                    causal, sp_axis=sp_axis)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, d)
        x = x + o @ params[p + "proj.w"] + params[p + "proj.b"]

        h = _ln(x, params[p + "ln2.g"], params[p + "ln2.b"])
        h = jax.nn.gelu(h @ params[p + "fc.w"] + params[p + "fc.b"])
        x = x + h @ params[p + "out.w"] + params[p + "out.b"]

    x = _ln(x, params["ln_f.g"], params["ln_f.b"])
    return x @ params["wte"].T


def transformer_loss(params, tokens, targets, attn_impl="flash",
                     mesh=None):
    logits = transformer_forward(params, tokens, attn_impl=attn_impl,
                                 mesh=mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)
