"""Model zoo: program-builder functions for the reference's benchmark and
book-test model families (reference: benchmark/paddle/image/*.py,
benchmark/paddle/rnn/rnn.py, python/paddle/v2/fluid/tests/book/*.py).

Each builder appends ops to the current default program (use inside
`fluid.program_guard`) and returns output Variables; nothing executes.
"""

from .image import (lenet5, mlp, smallnet_mnist_cifar, alexnet, vgg,
                    vgg16, vgg19, resnet, resnet50, resnet101,
                    resnet_cifar10, googlenet)
from .text import (stacked_lstm_text_classifier, conv_text_classifier,
                   word2vec_ngram, seq2seq)

__all__ = [
    "lenet5", "mlp", "smallnet_mnist_cifar", "alexnet", "vgg", "vgg16",
    "vgg19", "resnet", "resnet50", "resnet101", "resnet_cifar10",
    "googlenet", "stacked_lstm_text_classifier", "conv_text_classifier",
    "word2vec_ngram", "seq2seq",
]
