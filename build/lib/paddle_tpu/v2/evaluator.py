"""v2 evaluator DSL (reference: trainer_config_helpers/evaluators.py —
17 `*_evaluator` functions attaching metrics/printers to the topology,
over gserver/evaluators/Evaluator.cpp, CTCErrorEvaluator.cpp,
DetectionMAPEvaluator.cpp).

Each function appends the metric ops to the default program and
returns the metric Variable(s); fetch them alongside the cost (the
reference prints them per batch/pass from inside the trainer — here
they are first-class fetchable outputs, and the printer evaluators
wrap the print op)."""

from ..fluid import layers as fl
from ..fluid.layer_helper import LayerHelper
from .recurrent import register_layer_output

__all__ = [
    "classification_error_evaluator", "auc_evaluator",
    "precision_recall_evaluator", "chunk_evaluator",
    "ctc_error_evaluator", "detection_map_evaluator",
    "pnpair_evaluator", "sum_evaluator", "column_sum_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
]


def _metric_op(op_type, inputs, attrs, out_slots, dtypes=None,
               lod_levels=None, name=None):
    helper = LayerHelper(op_type)
    outs = []
    for i, slot in enumerate(out_slots):
        outs.append(helper.create_tmp_variable(
            (dtypes or ["float32"] * len(out_slots))[i],
            stop_gradient=True,
            lod_level=(lod_levels or [0] * len(out_slots))[i]))
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={s: [o] for s, o in zip(out_slots, outs)},
                     attrs=attrs or {})
    if name:
        register_layer_output(name, outs[0])
    return outs[0] if len(outs) == 1 else outs


def classification_error_evaluator(input, label, name=None, top_k=1,
                                   **kw):
    """Error rate = 1 - accuracy (reference: evaluators.py
    classification_error_evaluator over ClassificationErrorEvaluator)."""
    acc = fl.accuracy(input=input, label=label, k=top_k)
    one = fl.fill_constant(shape=[1], dtype="float32", value=1.0)
    return register_layer_output(name, fl.elementwise_sub(x=one, y=acc))


def auc_evaluator(input, label, name=None, **kw):
    return _metric_op("auc", {"Out": [input], "Indices": [input],
                              "Label": [label]}, {}, ["AUC"], name=name)


def precision_recall_evaluator(input, label, positive_label=None,
                               name=None, **kw):
    """[macro P, R, F1, micro P, R, F1]; with `positive_label`, the
    binary [P, R, F1] for that class (reference: evaluators.py
    precision_recall_evaluator over PrecisionRecallEvaluator)."""
    cls = int(input.shape[-1])
    _, idx = fl.topk(input=input, k=1)
    if positive_label is not None:
        # binary stats for one class: tp / predicted-pos / actual-pos
        pos = fl.fill_constant(shape=[1], dtype="int64",
                               value=int(positive_label))
        pred_pos = fl.cast(fl.equal(x=idx, y=pos), dtype="float32")
        lab_pos = fl.cast(fl.equal(x=label, y=pos), dtype="float32")
        tp = fl.reduce_sum(input=fl.elementwise_mul(x=pred_pos,
                                                    y=lab_pos),
                           dim=None, keep_dim=False)
        eps = fl.fill_constant(shape=[1], dtype="float32", value=1e-6)
        npred = fl.elementwise_max(
            x=fl.reduce_sum(input=pred_pos, dim=None, keep_dim=False),
            y=eps)
        nlab = fl.elementwise_max(
            x=fl.reduce_sum(input=lab_pos, dim=None, keep_dim=False),
            y=eps)
        precision = fl.elementwise_div(x=tp, y=npred)
        recall = fl.elementwise_div(x=tp, y=nlab)
        two_pr = fl.scale(x=fl.elementwise_mul(x=precision, y=recall),
                          scale=2.0)
        f1 = fl.elementwise_div(
            x=two_pr,
            y=fl.elementwise_max(x=fl.elementwise_add(x=precision,
                                                      y=recall), y=eps))
        out = fl.concat(input=[precision, recall, f1], axis=0)
        return register_layer_output(name, out)
    outs = _metric_op(
        "precision_recall",
        {"MaxProbs": [input], "Indices": [idx], "Labels": [label]},
        {"class_number": cls},
        ["BatchMetrics", "AccumMetrics", "AccumStatesInfo"])
    return register_layer_output(name, outs[0])


def chunk_evaluator(input, label, chunk_scheme="IOB", num_chunk_types=1,
                    excluded_chunk_types=None, name=None, **kw):
    precision, recall, f1, _, _, _ = fl.chunk_eval(
        input=input, label=label, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)
    register_layer_output(name, f1)
    return precision, recall, f1


def ctc_error_evaluator(input, label, name=None, **kw):
    """Per-sequence edit distance of CTC decodes vs references
    (reference: evaluators.py ctc_error_evaluator over
    CTCErrorEvaluator.cpp)."""
    dist, _ = fl.edit_distance(input=input, label=label)
    return register_layer_output(name, fl.mean(x=dist))


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            background_id=0, ap_type="11point",
                            evaluate_difficult=False, name=None, **kw):
    """Batch mAP of detection output vs ground truth (reference:
    evaluators.py detection_map_evaluator over
    DetectionMAPEvaluator.cpp)."""
    return _metric_op(
        "detection_map", {"DetectRes": [input], "Label": [label]},
        {"overlap_threshold": float(overlap_threshold),
         "background_label_id": int(background_id),
         "ap_type": ap_type,
         "evaluate_difficult": bool(evaluate_difficult)},
        ["MAP"], name=name)


def pnpair_evaluator(input, label, query_id, weight=None, name=None,
                     **kw):
    """Positive-negative pair ratio per query (reference: evaluators.py
    pnpair_evaluator over PnpairEvaluator)."""
    inputs = {"Score": [input], "Label": [label], "QueryID": [query_id]}
    if weight is not None:
        inputs["Weight"] = [weight]
    return _metric_op("positive_negative_pair", inputs, {},
                      ["PositivePair", "NegativePair", "NeutralPair"],
                      name=name)


def sum_evaluator(input, name=None, **kw):
    return register_layer_output(
        name, fl.reduce_sum(input=input, dim=None, keep_dim=False))


def column_sum_evaluator(input, name=None, **kw):
    return register_layer_output(
        name, fl.reduce_sum(input=input, dim=0, keep_dim=False))


# -- printer evaluators (reference: the *_printer_evaluator family all
#    reduce to "print this tensor during execution") --------------------

def value_printer_evaluator(input, name=None, **kw):
    return fl.Print(input, message=name or "value")


def gradient_printer_evaluator(input, name=None, **kw):
    return fl.Print(input, message=name or "gradient",
                    print_phase="backward")


def maxid_printer_evaluator(input, name=None, **kw):
    _, idx = fl.topk(input=input, k=1)
    return fl.Print(idx, message=name or "maxid")


def maxframe_printer_evaluator(input, name=None, **kw):
    mx = fl.reduce_max(input=input, dim=-1, keep_dim=True)
    return fl.Print(mx, message=name or "maxframe")


def seqtext_printer_evaluator(input, result_file=None, name=None, **kw):
    return fl.Print(input, message=name or "seqtext")


def classification_error_printer_evaluator(input, label, name=None, **kw):
    err = classification_error_evaluator(input, label)
    return fl.Print(err, message=name or "classification_error")
