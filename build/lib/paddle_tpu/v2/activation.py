"""v2 activation objects (reference: python/paddle/v2/activation.py over
trainer_config_helpers/activations.py)."""

__all__ = ["Tanh", "Sigmoid", "Softmax", "Identity", "Linear", "Relu",
           "BRelu", "SoftRelu", "STanh", "Abs", "Square", "Exp", "Log",
           "SquareActivation"]


class BaseActivation:
    name = None

    def __repr__(self):
        return "activation.%s" % type(self).__name__


def _make(cls_name, act_name):
    cls = type(cls_name, (BaseActivation,), {"name": act_name})
    return cls


Tanh = _make("Tanh", "tanh")
Sigmoid = _make("Sigmoid", "sigmoid")
Softmax = _make("Softmax", "softmax")
Identity = _make("Identity", None)
Linear = Identity
Relu = _make("Relu", "relu")
BRelu = _make("BRelu", "brelu")
SoftRelu = _make("SoftRelu", "soft_relu")
STanh = _make("STanh", "stanh")
Abs = _make("Abs", "abs")
Square = _make("Square", "square")
SquareActivation = Square
Exp = _make("Exp", "exp")
Log = _make("Log", "log")
