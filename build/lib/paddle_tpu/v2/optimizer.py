"""v2 optimizer configs (reference: python/paddle/v2/optimizer.py —
thin configs handed to the trainer; here they carry a fluid optimizer
factory)."""

from ..fluid import optimizer as fluid_opt
from ..fluid import regularizer as fluid_reg

__all__ = ["Optimizer", "Momentum", "Adam", "Adamax", "AdaGrad",
           "DecayedAdaGrad", "AdaDelta", "RMSProp"]


def _regularization(rate):
    return fluid_reg.L2Decay(rate) if rate else None


class Optimizer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def to_fluid(self):
        raise NotImplementedError

    # v2 API compat (learning-rate schedules folded into the config)
    def enable_types(self):
        return []


class Momentum(Optimizer):
    def __init__(self, momentum=None, sparse=False, learning_rate=1e-3,
                 regularization_rate=0.0, **kw):
        Optimizer.__init__(self, **kw)
        self.momentum = momentum or 0.0
        self.learning_rate = learning_rate
        self.regularization_rate = regularization_rate

    def to_fluid(self):
        return fluid_opt.Momentum(
            learning_rate=self.learning_rate, momentum=self.momentum,
            regularization=_regularization(self.regularization_rate))


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 learning_rate=1e-3, regularization_rate=0.0, **kw):
        Optimizer.__init__(self, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.learning_rate = learning_rate
        self.regularization_rate = regularization_rate

    def to_fluid(self):
        return fluid_opt.Adam(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon,
            regularization=_regularization(self.regularization_rate))


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, learning_rate=1e-3, **kw):
        Optimizer.__init__(self, **kw)
        self.beta1, self.beta2 = beta1, beta2
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fluid_opt.Adamax(learning_rate=self.learning_rate,
                                beta1=self.beta1, beta2=self.beta2)


class AdaGrad(Optimizer):
    def __init__(self, learning_rate=1e-3, **kw):
        Optimizer.__init__(self, **kw)
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fluid_opt.Adagrad(learning_rate=self.learning_rate)


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kw):
        Optimizer.__init__(self, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fluid_opt.DecayedAdagrad(
            learning_rate=self.learning_rate, decay=self.rho,
            epsilon=self.epsilon)


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1.0, **kw):
        Optimizer.__init__(self, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fluid_opt.Adadelta(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon)


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kw):
        Optimizer.__init__(self, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fluid_opt.RMSProp(
            learning_rate=self.learning_rate, decay=self.rho,
            epsilon=self.epsilon)
