"""v2 Parameters: numpy views over the trained state + tar serialization
(reference: python/paddle/v2/parameters.py — Parameters, to_tar:300s,
from_tar; tar holds one raw file per parameter)."""

import io
import json
import tarfile

import numpy as np

from .. import fluid
from ..fluid import framework
from ..core import scope as scope_mod

__all__ = ["Parameters", "create"]


class Parameters:
    """Name -> numpy parameter view bound to a scope."""

    def __init__(self, program=None, scope=None):
        self._program = program or framework.default_main_program()
        self._scope = scope or scope_mod.global_scope()

    def _param_vars(self):
        out = {}
        for block in self._program.blocks:
            for var in block.vars.values():
                if isinstance(var, framework.Parameter):
                    out[var.name] = var
        return out

    def keys(self):
        return sorted(self._param_vars())

    def names(self):
        return self.keys()

    def has_key(self, key):
        return key in self._param_vars()

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self._param_vars())

    def get(self, name):
        val = self._scope.get(name)
        if val is None:
            raise ValueError("parameter %r has no value yet" % name)
        return np.asarray(val)

    __getitem__ = get

    def get_shape(self, name):
        return tuple(self._param_vars()[name].shape)

    def set(self, name, value):
        old = self._scope.get(name)
        value = np.asarray(value)
        if old is not None:
            old = np.asarray(old)
            value = value.reshape(old.shape).astype(old.dtype)
        self._scope.set(name, value)

    __setitem__ = set

    def to_tar(self, f):
        """One .npy member per parameter + a manifest (reference format
        is one raw buffer per param + proto config; .npy keeps dtype and
        shape self-describing)."""
        tar = tarfile.open(fileobj=f, mode="w")
        names = self.keys()
        manifest = json.dumps({"parameters": names}).encode()
        info = tarfile.TarInfo("manifest.json")
        info.size = len(manifest)
        tar.addfile(info, io.BytesIO(manifest))
        for name in names:
            buf = io.BytesIO()
            np.save(buf, self.get(name))
            data = buf.getvalue()
            info = tarfile.TarInfo(name + ".npy")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        tar.close()

    @classmethod
    def from_tar(cls, f, program=None, scope=None):
        params = cls(program=program, scope=scope)
        tar = tarfile.open(fileobj=f, mode="r")
        for member in tar.getmembers():
            if not member.name.endswith(".npy"):
                continue
            data = tar.extractfile(member).read()
            arr = np.load(io.BytesIO(data))
            params.set(member.name[:-4], arr)
        tar.close()
        return params

    def init_from_tar(self, f):
        tar = tarfile.open(fileobj=f, mode="r")
        for member in tar.getmembers():
            if not member.name.endswith(".npy"):
                continue
            name = member.name[:-4]
            if not self.has_key(name):
                continue
            arr = np.load(io.BytesIO(tar.extractfile(member).read()))
            self.set(name, arr)
        tar.close()


def create(cost_or_program=None):
    """Run the startup program and return a Parameters view (reference:
    parameters.create(topology) — topology here is the default
    program)."""
    from .config import _place

    program = None
    if cost_or_program is not None and hasattr(cost_or_program, "blocks"):
        program = cost_or_program
    exe = fluid.Executor(_place())
    exe.run(framework.default_startup_program())
    return Parameters(program=program)
