"""v2 parameter/extra attributes (reference: python/paddle/v2/attr.py
over trainer_config_helpers/attrs.py)."""

from ..fluid.param_attr import ParamAttr

__all__ = ["Param", "ParamAttr", "Extra", "ExtraAttr"]


def Param(name=None, initial_std=None, initial_mean=None, is_static=False,
          learning_rate=None, l2_rate=None, sparse_update=False, **kw):
    from ..fluid import initializer, regularizer

    init = None
    if initial_std is not None or initial_mean is not None:
        init = initializer.Normal(loc=initial_mean or 0.0,
                                  scale=initial_std
                                  if initial_std is not None else 0.01)
    reg = regularizer.L2Decay(l2_rate) if l2_rate else None
    return ParamAttr(name=name, initializer=init,
                     learning_rate=learning_rate
                     if learning_rate is not None else 1.0,
                     regularizer=reg,
                     trainable=not is_static)


class ExtraAttr:
    def __init__(self, drop_rate=None, device=None, **kw):
        self.drop_rate = drop_rate
        self.device = device


Extra = ExtraAttr
