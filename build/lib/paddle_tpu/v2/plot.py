"""Training-curve plotting (reference: python/paddle/v2/plot/plot.py
Ploter — matplotlib in notebooks, text fallback otherwise)."""

__all__ = ["Ploter"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    """Collects (step, value) series per title and plots/prints them
    (reference: v2/plot/plot.py — same append/plot/reset surface)."""

    def __init__(self, *titles):
        self.__args__ = titles
        self.__plot_data__ = {t: PlotData() for t in titles}
        self.__disable_plot__ = self._matplotlib_missing()

    @staticmethod
    def _matplotlib_missing():
        try:
            import matplotlib  # noqa: F401

            return False
        except ImportError:
            return True

    def append(self, title, step, value):
        assert title in self.__plot_data__, (
            "title %s not found in %s" % (title, list(self.__plot_data__)))
        self.__plot_data__[title].append(step, float(value))

    def plot(self, path=None):
        if self.__disable_plot__:
            for title, data in self.__plot_data__.items():
                if data.step:
                    print("%s: step=%d value=%f"
                          % (title, data.step[-1], data.value[-1]))
            return
        import matplotlib.pyplot as plt

        for title, data in self.__plot_data__.items():
            plt.plot(data.step, data.value, label=title)
        plt.legend()
        if path:
            plt.savefig(path)
        else:
            plt.draw()
        plt.clf()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
