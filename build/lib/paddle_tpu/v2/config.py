"""v2 process-level config state (reference: the gflags handled by
python/paddle/v2/__init__.py init)."""

_state = {"initialized": False, "use_tpu": False, "trainer_count": 1}


def init(use_gpu=False, use_tpu=None, trainer_count=1, **kwargs):
    _state["initialized"] = True
    _state["use_tpu"] = (bool(use_tpu) if use_tpu is not None
                         else bool(use_gpu))
    _state["trainer_count"] = trainer_count


def _place():
    from .. import fluid

    if _state["use_tpu"]:
        return fluid.TPUPlace(0)
    return fluid.CPUPlace()
