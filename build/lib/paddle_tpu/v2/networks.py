"""v2 network composites (reference: python/paddle/v2/networks.py over
trainer_config_helpers/networks.py — img/vgg composites :336-630,
lstmemory_unit/group :717-940, gru_unit/group :940-1226,
bidirectional_gru :1226, simple_attention :1400, dot_product_attention
:1498, multi_head_attention :1580).  Each composite is restated on this
framework's v2 DSL primitives; the recurrent units hang off the
recurrent_group/memory machinery in v2/recurrent.py (one masked
lax.scan), and the attention heads are sequence ops over the static
encoder sequence inside the decoder's step."""

from ..fluid import layers as fl
from ..fluid import nets as fluid_nets
from ..fluid.framework import unique_name
from . import layer as v2_layer
from . import activation as act_mod
from .recurrent import memory, recurrent_group, get_output_layer

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "simple_lstm", "bidirectional_lstm", "simple_gru",
           "simple_gru2", "lstmemory_unit", "lstmemory_group",
           "gru_unit", "gru_group", "bidirectional_gru",
           "simple_attention", "dot_product_attention",
           "multi_head_attention", "small_vgg", "vgg_16_network"]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kw):
    return fluid_nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=v2_layer._act_name(act))


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1,
                   pool_type=None, **kw):
    if pool_type is not None and not isinstance(pool_type, str):
        pool_type = pool_type.name
    return fluid_nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter,
        pool_size=pool_size, conv_padding=conv_padding,
        conv_filter_size=conv_filter_size,
        conv_act=v2_layer._act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
        pool_stride=pool_stride, pool_type=pool_type or "max")


def sequence_conv_pool(input, context_len, hidden_size, **kw):
    return fluid_nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len)


def simple_lstm(input, size, reverse=False, **kw):
    proj = v2_layer.fc(input=input, size=size * 4)
    return v2_layer.lstmemory(input=proj, size=size, reverse=reverse)


def bidirectional_lstm(input, size, return_unpooled=False, **kw):
    fwd = simple_lstm(input, size)
    bwd = simple_lstm(input, size, reverse=True)
    if return_unpooled:
        return fwd, bwd
    from . import pooling

    f = v2_layer.pool(fwd, pooling_type=pooling.Max)
    b = v2_layer.pool(bwd, pooling_type=pooling.Max)
    return v2_layer.concat(input=[f, b])


def simple_gru(input, size, reverse=False, **kw):
    proj = v2_layer.fc(input=input, size=size * 3)
    return v2_layer.grumemory(input=proj, size=size, reverse=reverse)


# ---------------------------------------------------------------------------
# step-level recurrent units (for use inside recurrent_group)
# ---------------------------------------------------------------------------

def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None,
                   state_act=None, lstm_bias_attr=None, **kw):
    """One LSTM time step for use inside a recurrent_group step function
    (reference: networks.py lstmemory_unit:717) — this is the
    attention-friendly spelling where the hidden/cell states are plain
    step tensors.  `input` is the 4*size input projection; the hidden
    recurrence adds W_h·h_{t-1} and the step kernel does the gate math.
    The new cell is registered under "<name>_state" so the cell memory
    links by name."""
    if size is None:
        size = int(input.shape[-1]) // 4
    if name is None:
        name = unique_name("lstmemory_unit")
    prev_h = out_memory if out_memory is not None \
        else memory(name=name, size=size)
    prev_c = memory(name="%s_state" % name, size=size)

    gates = v2_layer.mixed(
        size=size * 4,
        input=[v2_layer.identity_projection(input),
               v2_layer.full_matrix_projection(prev_h, size * 4,
                                               param_attr=param_attr)])
    out = v2_layer.lstm_step_layer(
        input=gates, state=prev_c, size=size, act=act,
        gate_act=gate_act, state_act=state_act,
        bias_attr=lstm_bias_attr, name=name)
    get_output_layer(out, "state", name="%s_state" % name)
    return out


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None,
                    gate_act=None, state_act=None, lstm_bias_attr=None,
                    **kw):
    """recurrent_group spelling of an LSTM layer (reference:
    networks.py lstmemory_group:836): same math as lstmemory, but every
    step's states are user-visible — the building block for attention
    decoders.  `input` must already be the 4*size projection."""
    if name is None:
        name = unique_name("lstm_group")

    def lstm_step(ipt):
        return lstmemory_unit(
            input=ipt, name=name, size=size, out_memory=out_memory,
            param_attr=param_attr, act=act, gate_act=gate_act,
            state_act=state_act, lstm_bias_attr=lstm_bias_attr)

    return recurrent_group(step=lstm_step, input=input, reverse=reverse,
                           name="%s_recurrent_group" % name)


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_bias_attr=None, gru_param_attr=None, act=None,
             gate_act=None, naive=False, **kw):
    """One GRU time step for use inside a recurrent_group step function
    (reference: networks.py gru_unit:940).  `input` is the 3*size
    projection."""
    if size is None:
        size = int(input.shape[-1]) // 3
    if name is None:
        name = unique_name("gru_unit")
    prev = memory(name=name, size=size, boot_layer=memory_boot)
    return v2_layer.gru_step_layer(
        input=input, output_mem=prev, size=size, act=act,
        gate_act=gate_act, param_attr=gru_param_attr,
        bias_attr=gru_bias_attr, name=name)


def gru_group(input, memory_boot=None, size=None, name=None,
              reverse=False, gru_bias_attr=None, gru_param_attr=None,
              act=None, gate_act=None, naive=False, **kw):
    """recurrent_group spelling of a GRU layer (reference:
    networks.py gru_group:1002); per-step hidden states stay visible."""
    if name is None:
        name = unique_name("gru_group")

    def gru_step(ipt):
        return gru_unit(
            input=ipt, memory_boot=memory_boot, name=name, size=size,
            gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
            act=act, gate_act=gate_act, naive=naive)

    return recurrent_group(step=gru_step, input=input, reverse=reverse,
                           name="%s_recurrent_group" % name)


def simple_gru2(input, size, name=None, reverse=False,
                mixed_param_attr=None, mixed_bias_attr=None,
                gru_param_attr=None, gru_bias_attr=None, act=None,
                gate_act=None, **kw):
    """fc projection + gru_group (reference: networks.py simple_gru2 —
    the group form of simple_gru, used by bidirectional_gru)."""
    proj = v2_layer.fc(input=input, size=size * 3,
                       param_attr=mixed_param_attr,
                       bias_attr=mixed_bias_attr)
    return gru_group(input=proj, size=size, name=name, reverse=reverse,
                     gru_param_attr=gru_param_attr,
                     gru_bias_attr=gru_bias_attr, act=act,
                     gate_act=gate_act)


def bidirectional_gru(input, size, name=None, return_seq=False, **kw):
    """Forward + backward GRU over the sequence (reference:
    networks.py bidirectional_gru:1226).  return_seq=False concatenates
    the forward's last step with the backward's first step (each is the
    full-context summary for its direction); return_seq=True
    concatenates the two step-aligned output sequences."""
    if name is None:
        name = unique_name("bidirectional_gru")
    fwd_kw = {k[len("fwd_"):]: v for k, v in kw.items()
              if k.startswith("fwd_")}
    bwd_kw = {k[len("bwd_"):]: v for k, v in kw.items()
              if k.startswith("bwd_")}
    fw = simple_gru2(input=input, size=size, name="%s_fw" % name,
                     **fwd_kw)
    bw = simple_gru2(input=input, size=size, name="%s_bw" % name,
                     reverse=True, **bwd_kw)
    if return_seq:
        return v2_layer.concat(input=[fw, bw], name=name)
    return v2_layer.concat(
        input=[v2_layer.last_seq(input=fw), v2_layer.first_seq(input=bw)],
        name=name)


# ---------------------------------------------------------------------------
# attention heads (for use inside a decoder's recurrent_group step)
# ---------------------------------------------------------------------------

def _sequence_attention_pool(scores, values, name):
    """Normalize per-sequence scores and sum-pool the weighted values:
    softmax over each sequence's steps, scale, sum."""
    weights = fl.sequence_softmax(x=scores)
    scaled = v2_layer.scaling(input=values, weight=weights)
    return v2_layer.pool(input=scaled, pooling_type="sum",
                         name="%s_pooling" % name)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None):
    """Additive (Bahdanau) attention context (reference:
    networks.py simple_attention:1400): score each encoder step by
    v·f(W·s_{t-1} + U·h_j) with f=tanh, softmax within the sequence,
    and return the weighted sum of encoded_sequence.  encoded_proj is
    the precomputed U·h_j (computed once outside the decoder loop —
    only the decoder-state projection runs per step)."""
    if name is None:
        name = unique_name("attention")
    proj_size = int(encoded_proj.shape[-1])
    state_proj = v2_layer.fc(input=decoder_state, size=proj_size,
                             bias_attr=False,
                             param_attr=transform_param_attr,
                             name="%s_transform" % name)
    expanded = v2_layer.expand(input=state_proj,
                               expand_as=encoded_sequence)
    combined = v2_layer.addto(input=[expanded, encoded_proj],
                              act=weight_act or act_mod.Tanh(),
                              name="%s_combine" % name)
    scores = v2_layer.fc(input=combined, size=1, bias_attr=False,
                         param_attr=softmax_param_attr,
                         name="%s_score" % name)
    return _sequence_attention_pool(scores, encoded_sequence, name)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None):
    """Dot-product attention context (reference:
    networks.py dot_product_attention:1498): score h_j by
    s_{t-1}ᵀ·h_j against encoded_sequence, softmax within the
    sequence, return the weighted sum of attended_sequence (scored and
    pooled sequences may differ)."""
    if name is None:
        name = unique_name("dot_product_attention")
    expanded = v2_layer.expand(input=transformed_state,
                               expand_as=encoded_sequence)
    scores = v2_layer.dot_prod(a=expanded, b=encoded_sequence,
                               name="%s_score" % name)
    return _sequence_attention_pool(scores, attended_sequence, name)


def multi_head_attention(query, key, value, key_proj_size,
                         value_proj_size, head_num, attention_type,
                         softmax_param_attr=None, name=None):
    """Multi-head attention context (reference:
    networks.py multi_head_attention:1580): project query/key/value
    once to head_num*proj_size, split per head, score each head by
    scaled dot-product or additive attention, pool each head's value
    slice, concat the per-head contexts."""
    if attention_type not in ("dot-product attention",
                              "additive attention"):
        raise ValueError("unknown attention_type %r" % attention_type)
    if name is None:
        name = unique_name("multi_head_attention")
    q = v2_layer.fc(input=query, size=key_proj_size * head_num,
                    bias_attr=False, name="%s_query_proj" % name)
    q = v2_layer.expand(input=q, expand_as=key)
    k = v2_layer.fc(input=key, size=key_proj_size * head_num,
                    bias_attr=False, name="%s_key_proj" % name)
    v = v2_layer.fc(input=value, size=value_proj_size * head_num,
                    bias_attr=False, name="%s_value_proj" % name)

    q_heads = fl.split(q, num_or_sections=head_num, dim=-1)
    k_heads = fl.split(k, num_or_sections=head_num, dim=-1)
    v_heads = fl.split(v, num_or_sections=head_num, dim=-1)

    contexts = []
    for i in range(head_num):
        if attention_type == "dot-product attention":
            scores = v2_layer.dot_prod(a=q_heads[i], b=k_heads[i])
            scores = v2_layer.slope_intercept(
                input=scores, slope=key_proj_size ** -0.5)
        else:
            combined = v2_layer.addto(input=[q_heads[i], k_heads[i]],
                                      act=act_mod.Tanh())
            scores = v2_layer.fc(input=combined, size=1,
                                 bias_attr=False,
                                 param_attr=softmax_param_attr)
        contexts.append(_sequence_attention_pool(
            scores, v_heads[i], "%s_head%d" % (name, i)))
    return v2_layer.concat(input=contexts, name=name)


# ---------------------------------------------------------------------------
# VGG image composites
# ---------------------------------------------------------------------------

def _vgg_block(x, num_filter, times, dropouts):
    return img_conv_group(
        input=x, conv_num_filter=[num_filter] * times,
        conv_filter_size=3, conv_padding=1,
        conv_act=act_mod.Relu(), conv_with_batchnorm=True,
        conv_batchnorm_drop_rate=dropouts,
        pool_size=2, pool_stride=2, pool_type="max")


def small_vgg(input_image, num_channels, num_classes):
    """CIFAR-sized VGG (reference: networks.py small_vgg:517): four
    BN'd conv blocks (64x2, 128x2, 256x3, 512x3) with in-block dropout,
    a final pool+dropout, one 512 fc with BN, softmax head."""
    x = input_image
    for width, times, drops in ((64, 2, [0.3, 0.0]),
                                (128, 2, [0.4, 0.0]),
                                (256, 3, [0.4, 0.4, 0.0]),
                                (512, 3, [0.4, 0.4, 0.0])):
        x = _vgg_block(x, width, times, drops)
    x = v2_layer.img_pool(input=x, pool_size=2, stride=2)
    x = v2_layer.dropout(input=x, dropout_rate=0.5)
    x = v2_layer.fc(input=x, size=512)
    x = v2_layer.batch_norm(input=x, act=act_mod.Relu())
    x = v2_layer.dropout(input=x, dropout_rate=0.5)
    return v2_layer.fc(input=x, size=num_classes,
                       act=act_mod.Softmax())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """The 16-layer VGG-D configuration (reference:
    networks.py vgg_16_network:547): five plain conv blocks
    (64x2, 128x2, 256x3, 512x3, 512x3), two dropout'd 4096 fcs,
    softmax head."""
    x = input_image
    for width, times in ((64, 2), (128, 2), (256, 3), (512, 3),
                         (512, 3)):
        x = img_conv_group(
            input=x, conv_num_filter=[width] * times,
            conv_filter_size=3, conv_padding=1,
            conv_act=act_mod.Relu(),
            pool_size=2, pool_stride=2, pool_type="max")
    for _ in range(2):
        x = v2_layer.fc(input=x, size=4096, act=act_mod.Relu())
        x = v2_layer.dropout(input=x, dropout_rate=0.5)
    return v2_layer.fc(input=x, size=num_classes,
                       act=act_mod.Softmax())
