"""v2 sequence pooling types (reference: python/paddle/v2/pooling.py)."""

__all__ = ["Max", "Avg", "Sum", "SquareRootN"]


class BasePoolingType:
    name = None


class Max(BasePoolingType):
    name = "max"


class Avg(BasePoolingType):
    name = "average"


class Sum(BasePoolingType):
    name = "sum"


class SquareRootN(BasePoolingType):
    name = "sqrt"
