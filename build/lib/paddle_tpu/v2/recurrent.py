"""v2 recurrent layer groups + beam-search sequence generation.

reference surface: trainer_config_helpers/layers.py recurrent_group:4082,
memory:3590, beam_search:4406, StaticInput:4051, GeneratedInput:4215,
get_output_layer, maxid_layer, eos_layer; the runtime they configure is
RecurrentGradientMachine (paddle/gserver/gradientmachines/
RecurrentGradientMachine.h:32,307-309 — per-timestep dynamic expansion
and generateSequence/beamSearch).

TPU-first redesign:
  * recurrent_group traces the user's step function once into a
    DynamicRNN sub-block which lowers to ONE lax.scan (compiled, masked
    memory carries) — not per-timestep graph expansion.
  * beam_search traces the same step into a generation sub-block; at
    inference the decode loop runs the compiled step over a dense
    [batch*beam] state with host top-k bookkeeping, the same loop
    structure as RecurrentGradientMachine::beamSearch but with each
    step XLA-jitted.  (The fully-jitted dense decoder lives in
    models/decode.py; this path keeps full LoD/attention generality.)
"""

import contextlib

import numpy as np

from .. import fluid
from ..fluid import framework
from ..fluid import layers as fl
from ..fluid.param_attr import ParamAttr
from ..core.ragged import RaggedTensor

__all__ = [
    "StaticInput", "SubsequenceInput", "GeneratedInput", "memory",
    "recurrent_group", "beam_search", "get_output_layer", "eos_layer",
    "maxid_layer", "register_layer_output",
]


# ---------------------------------------------------------------------------
# named layer outputs (v2 layers link memories by layer NAME)
# ---------------------------------------------------------------------------

def _named_layers(program=None):
    if program is None:
        program = framework.default_main_program()
    if not hasattr(program, "_v2_named_layers"):
        program._v2_named_layers = {}
    return program._v2_named_layers


def register_layer_output(name, var):
    """Record `var` as the output of the v2 layer called `name` (the
    reference links memory() to layers through these names)."""
    if name:
        _named_layers()[name] = var
    return var


def get_layer(name):
    return _named_layers().get(name)


# ---------------------------------------------------------------------------
# input markers
# ---------------------------------------------------------------------------

class StaticInput:
    """Imported unchanged into every time step (reference: layers.py
    StaticInput:4051)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq or getattr(input, "lod_level", 0) > 0
        self.size = size


class _SubseqInput:
    def __init__(self, input):
        self.input = input


def SubsequenceInput(input):
    """Scatter a nested (lod_level 2) sequence by outer sequence
    (reference: layers.py SubsequenceInput:4067)."""
    return _SubseqInput(input)


class BaseGeneratedInput:
    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """The previously generated word fed back through an embedding
    (reference: layers.py GeneratedInput:4215)."""

    def __init__(self, size, embedding_name, embedding_size):
        BaseGeneratedInput.__init__(self)
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


# ---------------------------------------------------------------------------
# memory()
# ---------------------------------------------------------------------------

_cur_group = None


@contextlib.contextmanager
def _activate(group):
    global _cur_group
    prev = _cur_group
    _cur_group = group
    try:
        yield
    finally:
        _cur_group = prev


def memory(name, size, memory_name=None, is_seq=False, boot_layer=None,
           boot_bias=None, boot_bias_active_type=None,
           boot_with_const_id=None):
    """The named layer's output at the previous time step (reference:
    layers.py memory:3590).  Must be called inside a recurrent_group /
    beam_search step function."""
    if _cur_group is None:
        raise RuntimeError(
            "memory() must be called inside a recurrent_group or "
            "beam_search step function")
    return _cur_group.add_memory(
        name or memory_name, size, boot_layer=boot_layer,
        boot_with_const_id=boot_with_const_id)


class _RecurrentGroup:
    """Training-path group: memories become DynamicRNN loop carries."""

    def __init__(self, drnn):
        self.drnn = drnn
        self._links = []       # (mem_var, layer_name)

    def add_memory(self, name, size, boot_layer=None,
                   boot_with_const_id=None):
        if boot_with_const_id is not None:
            raise NotImplementedError(
                "boot_with_const_id only applies to generation "
                "(beam_search)")
        if boot_layer is not None:
            mem = self.drnn.memory(init=boot_layer)
        else:
            if not self.drnn.seq_inputs:
                raise ValueError(
                    "memory(size=...) without boot_layer needs at least "
                    "one sequence input declared before it")
            batch_ref = self.drnn.seq_inputs[0][1]
            mem = self.drnn.memory(shape=[size], batch_ref=batch_ref,
                                   value=0.0)
        if name:
            self._links.append((mem, name))
        mem.set_input = lambda layer, _m=mem: self.link(_m, layer)
        mem._v2_memory_name = name
        return mem

    def link(self, mem, layer):
        self._links = [(m, n) for m, n in self._links if m is not mem]
        self.drnn.update_memory(mem, layer)

    def finalize(self):
        for mem, name in self._links:
            target = get_layer(name)
            if target is None:
                raise ValueError(
                    "memory(name=%r) was never linked: no layer output "
                    "registered under that name inside the step "
                    "(pass name=%r to the producing layer)" % (name, name))
            self.drnn.update_memory(mem, target)


class _NestedGroup:
    """Group for the flattened nested-sequence path: every inner
    sequence runs as an independent batch element, so there is no
    cross-subsequence recurrence to carry."""

    def add_memory(self, name, size, boot_layer=None,
                   boot_with_const_id=None):
        raise NotImplementedError(
            "memory() across subsequences is not supported by the "
            "flattened SubsequenceInput lowering; encode each "
            "subsequence here, then run an ordinary recurrent_group "
            "over the returned sentence-level sequence for the outer "
            "recurrence")

    def finalize(self):
        pass


def _nested_recurrent_group(step, inputs, name):
    """SubsequenceInput lowering (reference nested-sequence mode:
    RecurrentGradientMachine.h:32): unnest lod-2 inputs into a lod-1
    batch of inner sequences, trace `step` ONCE over that batch (inner
    recurrent_groups ride the normal lod-1 scan), and reattach the
    outer row_splits to every output — dense per-subsequence rows
    become a sentence-level sequence, sequence outputs become nested
    again."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper(name or "nested_recurrent_group")
    inners, outer_ref = {}, None
    for idx, i in enumerate(inputs):
        if not isinstance(i, _SubseqInput):
            continue
        x = i.input
        if getattr(x, "lod_level", 0) < 2:
            raise ValueError(
                "SubsequenceInput needs a nested (lod_level 2) "
                "sequence; got lod_level %d" % getattr(x, "lod_level", 0))
        inner = helper.create_tmp_variable(x.dtype, lod_level=1)
        oref = helper.create_tmp_variable("float32", lod_level=1)
        helper.append_op(type="seq_unnest", inputs={"X": [x]},
                         outputs={"Inner": [inner], "OuterRef": [oref]})
        inners[idx] = inner
        if outer_ref is None:
            outer_ref = oref

    args = []
    for idx, i in enumerate(inputs):
        if isinstance(i, _SubseqInput):
            args.append(inners[idx])
        elif isinstance(i, StaticInput):
            if i.is_seq:
                raise NotImplementedError(
                    "StaticInput(is_seq=True) inside a nested group")
            exp = helper.create_tmp_variable(i.input.dtype)
            helper.append_op(type="seq_outer_expand",
                             inputs={"X": [i.input],
                                     "OuterRef": [outer_ref]},
                             outputs={"Out": [exp]})
            args.append(exp)
        else:
            raise ValueError(
                "nested recurrent_group inputs must be SubsequenceInput "
                "or StaticInput (got %r)" % (i,))

    with _activate(_NestedGroup()):
        outs = step(*args)
    outs_list = list(outs) if isinstance(outs, (list, tuple)) else [outs]

    results = []
    for o in outs_list:
        lod = 2 if getattr(o, "lod_level", 0) else 1
        out = helper.create_tmp_variable(o.dtype, lod_level=lod)
        helper.append_op(type="seq_renest",
                         inputs={"X": [o], "OuterRef": [outer_ref]},
                         outputs={"Out": [out]})
        results.append(out)
    return results[0] if len(results) == 1 else results


def recurrent_group(step, input, reverse=False, name=None,
                    targetInlink=None):
    """Iterate `step` over the time steps of the sequence inputs
    (reference: layers.py recurrent_group:4082 over
    RecurrentGradientMachine).  Lowered to one masked lax.scan via
    DynamicRNN; StaticInput vars enter the scan closure unchanged.
    With SubsequenceInput (nested lod-2) inputs the group flattens the
    outer level into the batch instead (see _nested_recurrent_group);
    `reverse` is identity there since the flattened form has no
    cross-subsequence order dependence."""
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    if any(isinstance(i, _SubseqInput) for i in inputs):
        return _nested_recurrent_group(step, inputs, name)

    # reverse inlinks before the scan; outputs un-reversed after
    prepared = []
    for i in inputs:
        if isinstance(i, StaticInput):
            prepared.append(i)
        elif isinstance(i, framework.Variable):
            prepared.append(fl.sequence_reverse(i) if reverse else i)
        else:
            raise ValueError("recurrent_group inputs must be sequence "
                             "Variables or StaticInput (got %r)" % (i,))

    drnn = fl.DynamicRNN(name=name)
    group = _RecurrentGroup(drnn)
    with drnn.block():
        args = []
        for i in prepared:
            if isinstance(i, StaticInput):
                args.append(i.input)
            else:
                args.append(drnn.step_input(i))
        with _activate(group):
            outs = step(*args)
        outs_list = list(outs) if isinstance(outs, (list, tuple)) \
            else [outs]
        group.finalize()
        drnn.output(*outs_list)
    result = drnn()
    result = result if isinstance(result, list) else [result]
    if reverse:
        result = [fl.sequence_reverse(r) for r in result]
    return result[0] if len(result) == 1 else result


# ---------------------------------------------------------------------------
# misc layers of the recurrent surface
# ---------------------------------------------------------------------------

def get_output_layer(input, arg_name, name=None, **kw):
    """Extract a non-default output of a layer, e.g. the lstm step's
    cell state (reference: layers.py get_output_layer)."""
    extra = getattr(input, "_v2_extra_outputs", None)
    if not extra or arg_name not in extra:
        raise ValueError("layer has no extra output %r" % arg_name)
    return register_layer_output(name, extra[arg_name])


def maxid_layer(input, name=None, **kw):
    _, idx = fl.topk(input=input, k=1)
    return register_layer_output(name, idx)


def eos_layer(input, eos_id, name=None, **kw):
    """1.0 where the id equals eos_id (reference: layers.py
    eos_layer:4366)."""
    eos = fl.fill_constant(shape=[1], dtype=input.dtype,
                           value=float(eos_id))
    return register_layer_output(name, fl.equal(x=input, y=eos))


# ---------------------------------------------------------------------------
# beam_search generation
# ---------------------------------------------------------------------------

class _GenGroup:
    """Generation-path group: memories become decode-loop state fed into
    the traced step block each iteration."""

    def __init__(self, block):
        self.block = block
        self.mems = []         # dicts: var, name, size, boot (outer var
        #                        name or None), const_id, new (var name)
        self._links = []

    def add_memory(self, name, size, boot_layer=None,
                   boot_with_const_id=None):
        dtype = "int64" if boot_with_const_id is not None else "float32"
        var = self.block.create_var(
            name=framework.unique_name("@".join(["gen_mem", name or "m"])),
            dtype=dtype,
            shape=(-1, 1) if boot_with_const_id is not None
            else (-1, size))
        rec = {"var": var, "name": name, "size": size,
               "boot": boot_layer.name if boot_layer is not None else None,
               "const_id": boot_with_const_id, "new": None}
        self.mems.append(rec)
        if name:
            self._links.append((rec, name))
        var.set_input = lambda layer, _r=rec: _r.update(
            {"new": layer.name})
        return var

    def finalize(self):
        for rec, name in self._links:
            if rec["new"] is not None:
                continue
            target = get_layer(name)
            if target is not None:
                rec["new"] = target.name
        for rec in self.mems:
            if rec["const_id"] is None and rec["new"] is None:
                raise ValueError(
                    "generation memory %r never updated: register a "
                    "layer output under its name or call set_input()"
                    % (rec["name"] or rec["var"].name))


class _BeamGenSpec:
    def __init__(self, program, block_idx, prev_ids_name, probs_name,
                 mems, statics, bos_id, eos_id, beam_size, max_length,
                 num_results_per_sample):
        self.program = program
        self.block_idx = block_idx
        self.prev_ids_name = prev_ids_name
        self.probs_name = probs_name
        self.mems = mems             # list of dicts (see _GenGroup)
        self.statics = statics       # [(sub var name == outer name?, ...)]
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.beam_size = beam_size
        self.max_length = max_length
        self.num_results_per_sample = num_results_per_sample


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=500,
                name=None, num_results_per_sample=None):
    """Configure beam-search generation over `step` (reference: layers.py
    beam_search:4406 over RecurrentGradientMachine::beamSearch).

    Returns a handle Variable; run it with paddle.infer(
    output_layer=handle, field=['prob', 'id']) — 'prob' is a
    [batch, num_results] score array, 'id' the flat id stream with each
    result as bos ... eos -1 (the reference's output format)."""
    if num_results_per_sample is None:
        num_results_per_sample = beam_size
    num_results_per_sample = min(num_results_per_sample, beam_size)

    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    gen = None
    for i in inputs:
        if isinstance(i, BaseGeneratedInput):
            if gen is not None:
                raise ValueError("beam_search accepts exactly one "
                                 "GeneratedInput")
            gen = i
    if gen is None:
        raise ValueError("beam_search needs a GeneratedInput")
    gen.bos_id, gen.eos_id = bos_id, eos_id

    prog = framework.default_main_program()
    parent = prog.current_block()
    sub = prog.create_block()
    try:
        group = _GenGroup(sub)
        prev_ids = group.add_memory("__beam_search_predict__", gen.size,
                                    boot_with_const_id=bos_id)

        statics = []
        args = []
        for i in inputs:
            if isinstance(i, BaseGeneratedInput):
                emb = fl.embedding(
                    input=prev_ids,
                    size=[gen.size, gen.embedding_size],
                    param_attr=ParamAttr(name=gen.embedding_name))
                args.append(emb)
            elif isinstance(i, StaticInput):
                statics.append(i.input.name)
                args.append(i.input)
            else:
                raise ValueError(
                    "beam_search inputs must be StaticInput or "
                    "GeneratedInput (got %r)" % (i,))

        with _activate(group):
            outs = step(*args)
        outs_list = list(outs) if isinstance(outs, (list, tuple)) \
            else [outs]
        group.finalize()
        probs = outs_list[0]
    finally:
        prog.rollback()

    handle = parent.create_var(
        name=framework.unique_name("beam_gen"), dtype="int64")
    handle._v2_beam_spec = _BeamGenSpec(
        prog, sub.idx, prev_ids.name, probs.name,
        [m for m in group.mems if m["const_id"] is None],
        statics, bos_id, eos_id, beam_size, max_length,
        num_results_per_sample)
    return handle


# ---------------------------------------------------------------------------
# the generation loop (RecurrentGradientMachine::beamSearch analog)
# ---------------------------------------------------------------------------

def _ragged_repeat(rt, k):
    """Repeat each sequence k times consecutively (beam expansion of a
    ragged static input)."""
    vals = np.asarray(rt.values)
    splits = np.asarray(rt.last_splits())
    n = len(splits) - 1
    segs, new_splits = [], [0]
    for i in range(n):
        seg = vals[splits[i]:splits[i + 1]]
        for _ in range(k):
            segs.append(seg)
            new_splits.append(new_splits[-1] + len(seg))
    out_vals = np.concatenate(segs, 0) if segs else vals[:0]
    return RaggedTensor(out_vals, [np.asarray(new_splits, np.int32)])


def _is_persistable(program, block_idx, name):
    bd = program.desc.block(block_idx)
    while True:
        if name in bd.vars:
            return bool(bd.vars[name].persistable)
        if bd.parent_idx < 0:
            return False
        bd = program.desc.block(bd.parent_idx)


def run_beam_search(spec, boot_values, static_values, batch_size,
                    scope=None, rng_seed=0):
    """Run the decode loop.  boot_values: {mem name: [B, size] np},
    static_values: {outer var name: value}.  Returns
    (scores [B, num_results], id stream list with -1 separators)."""
    import jax
    import jax.numpy as jnp

    from ..core.scope import global_scope
    from ..fluid.executor import ExecContext

    scope = scope or global_scope()
    B, K, V = batch_size, spec.beam_size, None
    N = B * K
    NEG = -1e30

    # state: per-beam memories [N, size]
    mems = {}
    for m in spec.mems:
        if m["boot"] is not None:
            boot = np.asarray(boot_values[m["var"].name])
        else:
            boot = np.zeros((B, m["size"]), np.float32)
        mems[m["var"].name] = np.repeat(boot, K, axis=0)

    statics = {}
    for name in spec.statics:
        v = static_values[name]
        if isinstance(v, RaggedTensor):
            statics[name] = _ragged_repeat(v, K)
        else:
            statics[name] = np.repeat(np.asarray(v), K, axis=0)

    # params + anything persistable
    base_env = {n: scope.get(n) for n in scope.local_var_names()
                if scope.get(n) is not None}

    # params created only by the generation topology (built after the
    # training startup ran) initialize into a throwaway scope so trained
    # weights are never clobbered
    block_desc = spec.program.desc.block(spec.block_idx)
    needed = set()
    for od in block_desc.ops:
        needed.update(od.input_names())
    missing = [n for n in needed
               if n not in base_env
               and _is_persistable(spec.program, spec.block_idx, n)]
    if missing:
        from ..core.scope import Scope
        from ..fluid.executor import Executor, CPUPlace

        tmp = Scope()
        Executor(CPUPlace()).run(framework.default_startup_program(),
                                 scope=tmp)
        for n in missing:
            v = tmp.get(n)
            if v is None:
                raise KeyError(
                    "generation step needs %r but it is neither in the "
                    "scope nor produced by the startup program" % n)
            scope.set(n, v)
            base_env[n] = v

    base_env.update(statics)

    prev = np.full((N, 1), spec.bos_id, np.int64)
    scores = np.tile(
        np.concatenate([np.zeros(1, np.float32),
                        np.full(K - 1, NEG, np.float32)]), B)
    done = np.zeros(N, bool)
    tok_hist, parent_hist = [], []

    rng = jax.random.PRNGKey(rng_seed)
    for t in range(spec.max_length):
        env = dict(base_env)
        env.update(mems)
        env[spec.prev_ids_name] = jnp.asarray(prev)
        ctx = ExecContext(None, spec.program, spec.block_idx, env,
                          rng=rng)
        ctx.run_block(spec.block_idx, env)
        rng = ctx.rng

        probs = np.asarray(env[spec.probs_name]).reshape(N, -1)
        V = probs.shape[1]
        logp = np.log(np.maximum(probs, 1e-30))
        eos_only = np.full((V,), NEG, np.float32)
        eos_only[spec.eos_id] = 0.0
        logp = np.where(done[:, None], eos_only[None, :], logp)
        total = (scores[:, None] + logp).reshape(B, K * V)
        top_idx = np.argsort(-total, axis=1)[:, :K]        # [B, K]
        top_scores = np.take_along_axis(total, top_idx, axis=1)
        beam_idx = top_idx // V
        tok_idx = (top_idx % V).astype(np.int64)
        flat_src = (np.arange(B)[:, None] * K + beam_idx).reshape(-1)

        for m in spec.mems:
            nm = m["var"].name
            new = np.asarray(env[m["new"]]).reshape(N, -1)
            mems[nm] = new[flat_src]
        prev = tok_idx.reshape(N, 1)
        scores = top_scores.reshape(-1)
        done = done[flat_src] | (prev.reshape(-1) == spec.eos_id)
        tok_hist.append(tok_idx)
        parent_hist.append(beam_idx)
        if done.all():
            break

    # backtrack parents (reference: beam_search_decode PackAllSteps)
    T = len(tok_hist)
    beams = np.tile(np.arange(K)[None, :], (B, 1))
    rev = []
    for t in range(T - 1, -1, -1):
        rev.append(np.take_along_axis(tok_hist[t], beams, axis=1))
        beams = np.take_along_axis(parent_hist[t], beams, axis=1)
    seqs = np.stack(rev[::-1], axis=2) if rev else \
        np.zeros((B, K, 0), np.int64)                    # [B, K, T]

    final = scores.reshape(B, K)
    order = np.argsort(-final, axis=1)
    final = np.take_along_axis(final, order, axis=1)
    seqs = np.take_along_axis(seqs, order[:, :, None], axis=1)

    R = spec.num_results_per_sample
    id_stream = []
    for b in range(B):
        for r in range(R):
            ids = [spec.bos_id]
            for t in range(seqs.shape[2]):
                w = int(seqs[b, r, t])
                ids.append(w)
                if w == spec.eos_id:
                    break
            if ids[-1] != spec.eos_id:
                ids.append(spec.eos_id)
            id_stream.extend(ids)
            id_stream.append(-1)
    return final[:, :R], id_stream
