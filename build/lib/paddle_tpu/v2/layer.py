"""v2 layer builders (reference: python/paddle/v2/layer.py auto-wrapping
trainer_config_helpers/layers.py).

Each function appends fluid ops to the default Program and returns the
fluid Variable; ``data`` additionally records declaration order so the
trainer can map reader tuple slots without an explicit ``feeding``.
"""

from .. import fluid
from ..fluid import layers as fl
from . import activation as act_mod
from .recurrent import (StaticInput, SubsequenceInput, GeneratedInput,
                        memory, recurrent_group, beam_search,
                        get_output_layer, eos_layer, maxid_layer,
                        register_layer_output)

__all__ = [
    "data", "fc", "embedding", "img_conv", "img_pool", "batch_norm",
    "lstmemory", "grumemory", "pool", "first_seq", "last_seq", "concat",
    "dropout", "addto", "classification_cost", "cross_entropy_cost",
    "square_error_cost", "regression_cost", "mse_cost", "crf",
    "crf_decoding", "max_id", "seq_concat", "expand", "cos_sim",
    "scaling", "slope_intercept", "sum_cost", "trans", "mixed",
    # projections / operators (mixed-layer family)
    "full_matrix_projection", "identity_projection", "table_projection",
    "dotmul_projection", "context_projection",
    "trans_full_matrix_projection", "scaling_projection",
    "slice_projection", "conv_projection", "dotmul_operator",
    "conv_operator",
    # recurrent surface
    "StaticInput", "SubsequenceInput", "GeneratedInput", "memory",
    "recurrent_group", "beam_search", "get_output_layer", "eos_layer",
    "maxid_layer", "gru_step_layer", "gru_step_naive_layer",
    "lstm_step_layer", "recurrent",
    # extended zoo
    "repeat", "seq_reshape", "interpolation", "power",
    "sum_to_one_norm", "row_l2_norm", "dot_prod", "l2_distance",
    "clip", "resize", "switch_order", "scale_shift", "sub_seq",
    "seq_slice", "kmax_seq_score", "sub_nested_seq",
    "factorization_machine", "gated_unit", "tensor", "selective_fc",
    "maxout", "spp", "img_cmrnorm", "cross_channel_norm", "img_pool3d",
    "img_conv3d", "block_expand", "bilinear_interp", "rotate",
    "out_prod", "linear_comb", "convex_comb", "conv_shift", "pad",
    "crop", "scale_sub_region", "prelu", "multiplex", "row_conv",
    "dropout_layer", "sampling_id", "printer",
    # costs
    "hsigmoid", "nce", "ctc", "warp_ctc", "rank_cost", "lambda_cost",
    "cross_entropy_with_selfnorm", "multi_binary_label_cross_entropy",
    "huber_regression_cost", "huber_classification_cost",
    "smooth_l1_cost",
    # detection
    "priorbox", "roi_pool", "detection_output", "multibox_loss",
]

def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type):
        act = act()
    return act.name


def _program_data_layers(program=None):
    """Data layers in declaration order, tracked per Program so a second
    topology in the same process doesn't inherit stale feed slots."""
    from ..fluid import framework

    if program is None:
        program = framework.default_main_program()
    if not hasattr(program, "_v2_data_layers"):
        program._v2_data_layers = []
    return program._v2_data_layers


def data(name, type, **kw):
    """reference: trainer_config_helpers data_layer; `type` is a
    v2 data_type.InputType."""
    v = fl.data(name=name, shape=list(type.shape), dtype=type.dtype,
                lod_level=type.seq_level)
    v._v2_input_type = type
    registry = _program_data_layers()
    if all(d.name != name for d in registry):
        registry.append(v)
    return v


def data_layers_for_feeding(feeding, program=None):
    """Resolve reader tuple order: declaration order by default,
    reordered by an explicit {name: index} feeding map."""
    layers = list(_program_data_layers(program))
    if feeding is not None:
        by_name = {d.name: d for d in layers}
        layers = [by_name[name]
                  for name, _ in sorted(feeding.items(),
                                        key=lambda kv: kv[1])]
    return layers


def _reset_data_layers(program=None):
    del _program_data_layers(program)[:]


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None,
       **kw):
    out = fl.fc(input=input, size=size, act=_act_name(act),
                param_attr=param_attr, bias_attr=bias_attr)
    return register_layer_output(name, out)


def embedding(input, size, param_attr=None, name=None, **kw):
    dim = input._v2_input_type.dim if hasattr(input, "_v2_input_type") \
        else kw.pop("vocab_size")
    return register_layer_output(
        name, fl.embedding(input=input, size=[dim, size],
                           param_attr=param_attr))


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=None, act=None, param_attr=None, bias_attr=None,
             name=None, **kw):
    if padding is None:
        padding = (filter_size - 1) // 2
    return register_layer_output(name, fl.conv2d(
        input=input, num_filters=num_filters,
        filter_size=filter_size, stride=stride,
        padding=padding, act=_act_name(act),
        param_attr=param_attr, bias_attr=bias_attr))


def img_pool(input, pool_size, pool_type=None, stride=None, padding=0,
             name=None, **kw):
    from . import pooling

    if pool_type is None:
        pool_type = pooling.Max
    pt = pool_type.name if not isinstance(pool_type, str) else pool_type
    pt = {"average": "avg"}.get(pt, pt)
    return register_layer_output(name, fl.pool2d(
        input=input, pool_size=pool_size, pool_type=pt,
        pool_stride=stride or pool_size, pool_padding=padding))


def batch_norm(input, act=None, name=None, **kw):
    return register_layer_output(
        name, fl.batch_norm(input=input, act=_act_name(act)))


def lstmemory(input, size=None, reverse=False, act=None, **kw):
    """v2 lstmemory: `size` is the hidden width and `input` the 4*size
    projection (reference: trainer_config_helpers lstmemory — hidden
    size, matching grumemory; fluid dynamic_lstm instead takes 4h)."""
    if size is None:
        size = input.shape[-1] // 4
    hidden, _ = fl.dynamic_lstm(
        input=input, size=size * 4, is_reverse=reverse,
        candidate_activation=_act_name(act) or "tanh")
    return register_layer_output(kw.get("name"), hidden)


def grumemory(input, size=None, reverse=False, act=None, **kw):
    if size is None:
        size = input.shape[-1] // 3
    return register_layer_output(kw.get("name"), fl.dynamic_gru(
        input=input, size=size, is_reverse=reverse,
        candidate_activation=_act_name(act) or "tanh"))


def pool(input, pooling_type=None, name=None, **kw):
    from . import pooling

    if pooling_type is None:
        pooling_type = pooling.Max
    pt = pooling_type.name if not isinstance(pooling_type, str) \
        else pooling_type
    return register_layer_output(
        name, fl.sequence_pool(input=input, pool_type=pt))


def first_seq(input, name=None, **kw):
    return register_layer_output(name,
                                 fl.sequence_first_step(input=input))


def last_seq(input, name=None, **kw):
    return register_layer_output(name,
                                 fl.sequence_last_step(input=input))


def concat(input, act=None, name=None, **kw):
    out = fl.concat(input=input, axis=-1)
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def seq_concat(a, b, name=None, **kw):
    return register_layer_output(name, fl.sequence_concat(input=[a, b]))


def dropout(input, dropout_rate, name=None, **kw):
    return register_layer_output(
        name, fl.dropout(x=input, dropout_prob=dropout_rate))


def addto(input, act=None, bias_attr=None, name=None, **kw):
    if not isinstance(input, (list, tuple)):
        input = [input]
    out = fl.sums(input=list(input))
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def classification_cost(input, label, **kw):
    """softmax-prob input + int label -> mean cross-entropy (reference:
    trainer_config_helpers classification_cost)."""
    cost = fl.cross_entropy(input=input, label=label)
    return fl.mean(x=cost)


def cross_entropy_cost(input, label, **kw):
    return classification_cost(input, label)


def square_error_cost(input, label, **kw):
    cost = fl.square_error_cost(input=input, label=label)
    return fl.mean(x=cost)


regression_cost = square_error_cost
mse_cost = square_error_cost


def sum_cost(input, **kw):
    return fl.mean(x=input)


def crf(size, input, label, param_attr=None, **kw):
    ll = fl.linear_chain_crf(input=input, label=label,
                             param_attr=param_attr)
    return fl.mean(x=ll)


def crf_decoding(size, input, param_attr=None, label=None, **kw):
    return fl.crf_decoding(input=input, param_attr=param_attr,
                           label=label)


def max_id(input, **kw):
    _, idx = fl.topk(input=input, k=1)
    return idx


def expand(input, expand_as, **kw):
    return fl.sequence_expand(x=input, y=expand_as)


def cos_sim(a, b, scale=1.0, **kw):
    out = fl.cos_sim(X=a, Y=b)
    if scale != 1.0:
        out = fl.scale(x=out, scale=float(scale))
    return out


def scaling(input, weight, **kw):
    return fl.elementwise_mul(x=input, y=weight)


def slope_intercept(input, slope=1.0, intercept=0.0, **kw):
    out = fl.scale(x=input, scale=float(slope))
    if intercept:
        out = out + float(intercept)
    return out


def trans(input, **kw):
    return fl.transpose(x=input, perm=[1, 0])


# ---------------------------------------------------------------------------
# mixed layer + projections (reference: trainer_config_helpers
# mixed_layer + FullMatrixProjection/TableProjection/... — a mixed layer
# sums its projections; here each projection is a deferred builder)
# ---------------------------------------------------------------------------

class _Projection:
    def __init__(self, build):
        self.build = build


def full_matrix_projection(input, size, param_attr=None):
    return _Projection(lambda: fl.fc(input=input, size=size,
                                     bias_attr=False,
                                     param_attr=param_attr))


def identity_projection(input, offset=None):
    if offset:
        raise NotImplementedError("identity_projection offset")
    return _Projection(lambda: input)


def table_projection(input, size, param_attr=None):
    dim = input._v2_input_type.dim
    return _Projection(lambda: fl.embedding(input=input, size=[dim, size],
                                            param_attr=param_attr))


def dotmul_projection(input, param_attr=None):
    def build():
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("dotmul_projection",
                             param_attr=param_attr)
        w = helper.create_parameter(helper.param_attr,
                                    shape=[input.shape[-1]],
                                    dtype=input.dtype)
        return fl.elementwise_mul(x=input, y=w)

    return _Projection(build)


def context_projection(input, context_len, context_start=None):
    return _Projection(lambda: fl.sequence_conv(
        input=input, num_filters=input.shape[-1],
        filter_size=context_len, bias_attr=False))


def trans_full_matrix_projection(input, size, param_attr=None):
    """out = x W^T with W [size, in] (reference: layers.py
    trans_full_matrix_projection / TransposedFullMatrixProjection) —
    lets tied weights be shared with an ordinary projection."""

    def build():
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("trans_fm_projection", param_attr=param_attr)
        w = helper.create_parameter(helper.param_attr,
                                    shape=[size, input.shape[-1]],
                                    dtype=input.dtype)
        return fl.matmul(x=input, y=w, transpose_y=True)

    return _Projection(build)


def scaling_projection(input, param_attr=None):
    """out = w * x with one learned scalar w (reference: layers.py
    scaling_projection over ScalingProjection.cpp)."""

    def build():
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("scaling_projection", param_attr=param_attr)
        w = helper.create_parameter(helper.param_attr, shape=[1],
                                    dtype=input.dtype)
        return fl.elementwise_mul(x=input, y=w)

    return _Projection(build)


def slice_projection(input, slices):
    """Concatenation of column ranges [(start, end), ...] of the input
    (reference: layers.py slice_projection over SliceProjection.cpp).
    Lowered to transpose + one gather of the selected columns."""
    for s, e in slices:
        if not (0 <= s < e <= input.shape[-1]):
            raise ValueError("bad slice (%d, %d) for width %d"
                             % (s, e, input.shape[-1]))

    def build():
        from ..fluid.layer_helper import LayerHelper

        cols = [c for s, e in slices for c in range(s, e)]
        helper = LayerHelper("slice_projection")
        idx = helper.create_tmp_variable("int32")
        idx.stop_gradient = True
        helper.append_op(type="assign_value", inputs={},
                         outputs={"Out": [idx]},
                         attrs={"shape": [len(cols)], "dtype": "int32",
                                "values": cols})
        t = fl.transpose(x=input, perm=[1, 0])
        picked = helper.create_tmp_variable(input.dtype)
        helper.append_op(type="gather",
                         inputs={"X": [t], "Index": [idx]},
                         outputs={"Out": [picked]})
        return fl.transpose(x=picked, perm=[1, 0])

    return _Projection(build)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None):
    """Learned-filter conv feature map for a mixed layer (reference:
    layers.py conv_projection; bias/activation belong to the mixed)."""

    def build():
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("conv_projection", param_attr=param_attr)
        cin = num_channels or input.shape[1]
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 2
        s = stride if isinstance(stride, (list, tuple)) else [stride] * 2
        p = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 2
        w = helper.create_parameter(helper.param_attr,
                                    shape=[num_filters, cin] + list(k),
                                    dtype=input.dtype)
        out = helper.create_tmp_variable(input.dtype)
        helper.append_op(type="conv2d",
                         inputs={"Input": [input], "Filter": [w]},
                         outputs={"Output": [out]},
                         attrs={"strides": list(s), "paddings": list(p),
                                "dilations": [1, 1], "groups": 1})
        return out

    return _Projection(build)


def dotmul_operator(a, b, scale=1.0):
    """Elementwise a .* b operator for a mixed layer (reference:
    layers.py dotmul_operator over DotMulOperator.cpp)."""

    def build():
        out = fl.elementwise_mul(x=a, y=b)
        if scale != 1.0:
            out = fl.scale(x=out, scale=float(scale))
        return out

    return _Projection(build)


def conv_operator(img, filter, filter_size, num_filters,
                  num_channels=None, stride=1, padding=0,
                  filter_size_y=None, stride_y=None, padding_y=None):
    """Convolve each sample of `img` with its own filter row produced
    by another layer (reference: layers.py conv_operator over
    ConvOperator.cpp — per-sample dynamic filters)."""

    def build():
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("conv_operator")
        kx = filter_size
        ky = filter_size if filter_size_y is None else filter_size_y
        s = [stride if stride_y is None else stride_y, stride]
        p = [padding if padding_y is None else padding_y, padding]
        out = helper.create_tmp_variable(img.dtype)
        helper.append_op(type="conv2d_dynamic_filter",
                         inputs={"Input": [img], "Filter": [filter]},
                         outputs={"Output": [out]},
                         attrs={"strides": s, "paddings": p,
                                "num_filters": int(num_filters),
                                "ksize": [ky, kx]})
        return out

    return _Projection(build)


def mixed(size=None, input=None, act=None, bias_attr=None, name=None,
          **kw):
    outs = [p.build() if isinstance(p, _Projection) else p
            for p in (input if isinstance(input, (list, tuple))
                      else [input])]
    out = outs[0] if len(outs) == 1 else fl.sums(input=outs)
    if bias_attr not in (None, False):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("mixed_bias", bias_attr=bias_attr)
        out = helper.append_bias_op(out)
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def gru_step_layer(input, output_mem, size=None, act=None,
                   gate_act=None, name=None, param_attr=None,
                   bias_attr=None, **kw):
    """One GRU step: input is the [B, 3*size] projection, output_mem the
    previous hidden state (reference: layers.py gru_step_layer over
    GruStepLayer.cpp)."""
    if size is None:
        size = output_mem.shape[-1]
    hidden, _, _ = fl.gru_unit(
        input=input, hidden=output_mem, size=size * 3,
        param_attr=param_attr, bias_attr=bias_attr,
        activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid")
    return register_layer_output(name, hidden)


gru_step_naive_layer = gru_step_layer


def lstm_step_layer(input, state, size=None, act=None, gate_act=None,
                    state_act=None, name=None, bias_attr=None, **kw):
    """One LSTM step: input is the [B, 4*size] gate projection, state
    the previous cell (reference: layers.py lstm_step_layer over
    LstmStepLayer.cpp: c' = sigma(f)*c + sigma(i)*act(z);
    h = sigma(o)*state_act(c')).  The returned layer is the hidden
    output; the new cell is reachable via
    get_output_layer(..., arg_name='state')."""
    from ..fluid.layer_helper import LayerHelper

    if size is None:
        size = state.shape[-1]
    act_n = _act_name(act) or "tanh"
    gate_n = _act_name(gate_act) or "sigmoid"
    state_n = _act_name(state_act) or "tanh"

    gates = input
    if bias_attr not in (None, False):
        helper = LayerHelper("lstm_step_bias", bias_attr=bias_attr)
        gates = helper.append_bias_op(gates)
    z, i, f, o = fl.split(gates, num_or_sections=4, dim=-1)
    new_c = fl.elementwise_add(
        x=fl.elementwise_mul(x=getattr(fl, gate_n)(f), y=state),
        y=fl.elementwise_mul(x=getattr(fl, gate_n)(i),
                             y=getattr(fl, act_n)(z)))
    h = fl.elementwise_mul(x=getattr(fl, gate_n)(o),
                           y=getattr(fl, state_n)(new_c))
    h._v2_extra_outputs = {"state": new_c}
    return register_layer_output(name, h)


def recurrent(input, act=None, bias_attr=None, param_attr=None,
              reverse=False, name=None, **kw):
    """Simple fully-connected recurrence: out_t = act(in_t + W out_{t-1}
    + b) — the input enters unprojected, one [size, size] recurrent
    weight (reference: layers.py recurrent_layer over
    RecurrentLayer.cpp)."""
    size = input.shape[-1]

    act_name = "tanh" if act is None else _act_name(act)

    def _step(y):
        mem = memory(name=None, size=size)
        proj = fl.fc(input=mem, size=size, act=None,
                     param_attr=param_attr, bias_attr=bias_attr)
        out = fl.sums(input=[y, proj])
        if act_name:
            out = getattr(fl, act_name)(out)
        mem.set_input(out)
        return out

    out = recurrent_group(_step, input, reverse=reverse)
    return register_layer_output(name, out)


# ---------------------------------------------------------------------------
# extended layer zoo (reference: trainer_config_helpers/layers.py — the
# remaining *_layer functions, mapped onto the one TPU-native op set)
# ---------------------------------------------------------------------------

def _helper_op(op_type, inputs, attrs=None, name=None, dtype="float32",
               lod_level=0, n_outs=1, out_slots=("Out",),
               stop_gradient=False):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper(op_type)
    outs = [helper.create_tmp_variable(dtype, lod_level=lod_level)
            for _ in range(n_outs)]
    if stop_gradient:
        for o in outs:
            o.stop_gradient = True
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={s: [o] for s, o in zip(out_slots, outs)},
                     attrs=attrs or {})
    out = outs[0] if n_outs == 1 else outs
    return register_layer_output(name, out if n_outs == 1 else outs[0]) \
        if n_outs == 1 else outs


def repeat(input, num_repeats, as_row_vector=True, act=None, name=None,
           **kw):
    """reference: repeat_layer — tile features num_repeats times
    (as_row_vector: [a b] -> [a b a b]; else [a a b b])."""
    if as_row_vector:
        out = fl.concat(input=[input] * num_repeats, axis=-1)
    else:
        d = input.shape[-1]
        r = fl.reshape(x=input, shape=[-1, d, 1])
        r = fl.concat(input=[r] * num_repeats, axis=-1)
        out = fl.reshape(x=r, shape=[-1, d * num_repeats])
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def seq_reshape(input, reshape_size, name=None, **kw):
    return register_layer_output(
        name, fl.sequence_reshape(input=input, new_dim=reshape_size))


def interpolation(input, weight, name=None, **kw):
    """out = w*x + (1-w)*y (reference: interpolation_layer over
    InterpolationLayer.cpp); weight is [B, 1]."""
    x, y = input
    wx = fl.elementwise_mul(x=x, y=weight)
    one_minus = fl.scale(x=weight, scale=-1.0) + 1.0
    wy = fl.elementwise_mul(x=y, y=one_minus)
    return register_layer_output(name, fl.elementwise_add(x=wx, y=wy))


def power(input, weight, name=None, **kw):
    """out = x ** w, per-sample scalar exponent (reference:
    power_layer)."""
    return register_layer_output(
        name, fl.elementwise_pow(x=input, y=weight))


def sum_to_one_norm(input, name=None, **kw):
    s = fl.reduce_sum(input=input, dim=1, keep_dim=True)
    return register_layer_output(name, fl.elementwise_div(x=input, y=s))


def row_l2_norm(input, name=None, **kw):
    return register_layer_output(name, fl.l2_normalize(x=input, axis=1))


def dot_prod(a, b, name=None, **kw):
    prod = fl.elementwise_mul(x=a, y=b)
    return register_layer_output(
        name, fl.reduce_sum(input=prod, dim=1, keep_dim=True))


def l2_distance(a, b, name=None, **kw):
    sq = _helper_op("squared_l2_distance", {"X": [a], "Y": [b]})
    return register_layer_output(name, fl.sqrt(sq))


def clip(input, min, max, name=None, **kw):
    return register_layer_output(
        name, fl.clip(x=input, min=float(min), max=float(max)))


def resize(input, size, name=None, **kw):
    return register_layer_output(name, fl.reshape(x=input,
                                                  shape=[-1, size]))


def switch_order(input, reshape_from="NCHW", reshape_to="NHWC",
                 name=None, **kw):
    perm = [reshape_from.index(ax) for ax in reshape_to]
    return register_layer_output(name, fl.transpose(x=input, perm=perm))


def scale_shift(input, param_attr=None, bias_attr=None, name=None, **kw):
    """out = w * x + b with scalar learned w, b (reference:
    ScaleShiftLayer.cpp)."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.param_attr import ParamAttr

    helper = LayerHelper("scale_shift", param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(helper.param_attr, shape=[1],
                                dtype=input.dtype)
    out = fl.elementwise_mul(x=input, y=w)
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[1], dtype=input.dtype,
                                    is_bias=True)
        out = fl.elementwise_add(x=out, y=b)
    return register_layer_output(name, out)


def sub_seq(input, offsets, sizes, name=None, **kw):
    return register_layer_output(
        name, fl.sequence_slice(input=input, offset=offsets,
                                length=sizes))


seq_slice = sub_seq


def kmax_seq_score(input, beam_size=1, name=None, **kw):
    return _helper_op("kmax_seq_score", {"X": [input]},
                      {"beam_size": int(beam_size)}, name=name,
                      dtype="int32", lod_level=1, stop_gradient=True)


def sub_nested_seq(input, selected_indices, name=None, **kw):
    return _helper_op("sub_nested_seq",
                      {"X": [input], "S": [selected_indices]},
                      name=name, dtype=input.dtype, lod_level=1)


def factorization_machine(input, factor_size, param_attr=None,
                          act=None, name=None, **kw):
    """0.5 * sum((xV)^2 - (x^2)(V^2)) (reference:
    FactorizationMachineLayer.cpp)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("factorization_machine", param_attr=param_attr)
    d = input.shape[-1]
    v = helper.create_parameter(helper.param_attr,
                                shape=[d, factor_size],
                                dtype=input.dtype)
    xv = fl.matmul(x=input, y=v)
    x2 = fl.square(input)
    v2 = fl.square(v)
    x2v2 = fl.matmul(x=x2, y=v2)
    diff = fl.elementwise_sub(x=fl.square(xv), y=x2v2)
    out = fl.scale(x=fl.reduce_sum(input=diff, dim=1, keep_dim=True),
                   scale=0.5)
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def gated_unit(input, size, act=None, name=None, gate_attr=None,
               gate_param_attr=None, inproj_attr=None,
               inproj_param_attr=None, **kw):
    """act(fc(x)) * sigmoid(fc(x)) (reference: gated_unit_layer)."""
    proj = fl.fc(input=input, size=size, act=_act_name(act),
                 param_attr=inproj_param_attr)
    gate = fl.fc(input=input, size=size, act="sigmoid",
                 param_attr=gate_param_attr)
    return register_layer_output(name,
                                 fl.elementwise_mul(x=proj, y=gate))


def tensor(a, b, size, act=None, param_attr=None, name=None, **kw):
    """Bilinear tensor product a W_k b (reference: tensor_layer over
    TensorLayer.cpp)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("tensor_layer", param_attr=param_attr)
    w = helper.create_parameter(
        helper.param_attr, shape=[size, a.shape[-1], b.shape[-1]],
        dtype=a.dtype)
    out = _helper_op("bilinear_tensor_product",
                     {"X": [a], "Y": [b], "Weight": [w]})
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def selective_fc(input, size, select=None, act=None, param_attr=None,
                 bias_attr=None, name=None, **kw):
    """Full fc; when `select` (0/1 mask) is given the unselected
    outputs are zeroed (reference: selective_fc_layer — the reference
    computes only selected columns; numerically identical result)."""
    out = fl.fc(input=input, size=size, act=_act_name(act),
                param_attr=param_attr, bias_attr=bias_attr)
    if select is not None:
        out = fl.elementwise_mul(x=out, y=select)
    return register_layer_output(name, out)


def maxout(input, groups, num_channels=None, name=None, **kw):
    return _helper_op("maxout", {"X": [input]}, {"groups": int(groups)},
                      name=name, dtype=input.dtype)


def spp(input, pyramid_height=3, pool_type=None, name=None, **kw):
    from . import pooling

    pt = "max" if pool_type is None else (
        pool_type.name if not isinstance(pool_type, str) else pool_type)
    return _helper_op("spp", {"X": [input]},
                      {"pyramid_height": int(pyramid_height),
                       "pooling_type": {"average": "avg"}.get(pt, pt)},
                      name=name, dtype=input.dtype)


def img_cmrnorm(input, size, scale=0.0128, power=0.75, name=None, **kw):
    """Cross-map response norm = LRN (reference: img_cmrnorm_layer over
    CMRProjectionNormLayer)."""
    return register_layer_output(
        name, fl.lrn(input=input, n=size, alpha=scale, beta=power))


def cross_channel_norm(input, param_attr=None, name=None, **kw):
    """L2 norm across channels with learned per-channel scale
    (reference: cross_channel_norm_layer over NormProjectionLayer)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("cross_channel_norm", param_attr=param_attr)
    c = input.shape[1]
    scale = helper.create_parameter(helper.param_attr, shape=[1, c, 1, 1],
                                    dtype=input.dtype)
    normed = _helper_op("norm", {"X": [input]}, {"axis": 1})
    return register_layer_output(
        name, fl.elementwise_mul(x=normed, y=scale))


def img_pool3d(input, pool_size, pool_type=None, stride=None,
               padding=0, name=None, **kw):
    from . import pooling

    if pool_type is None:
        pool_type = pooling.Max
    pt = pool_type.name if not isinstance(pool_type, str) else pool_type
    pt = {"average": "avg"}.get(pt, pt)
    k = pool_size if isinstance(pool_size, (list, tuple)) \
        else [pool_size] * 3
    s = stride if isinstance(stride, (list, tuple)) \
        else [stride or pool_size] * 3
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 3
    return _helper_op("pool3d", {"X": [input]},
                      {"pooling_type": pt, "ksize": list(k),
                       "strides": list(s), "paddings": list(p)},
                      name=name, dtype=input.dtype)


def img_conv3d(input, filter_size, num_filters, num_channels=None,
               stride=1, padding=0, act=None, param_attr=None,
               bias_attr=None, name=None, **kw):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("conv3d", param_attr=param_attr,
                         bias_attr=bias_attr)
    cin = num_channels or input.shape[1]
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    s = stride if isinstance(stride, (list, tuple)) else [stride] * 3
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 3
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_filters, cin] + list(k),
                                dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": list(s), "paddings": list(p),
                            "dilations": [1, 1, 1], "groups": 1})
    out = helper.append_bias_op(out, dim_start=1, dim_end=2) \
        if bias_attr is not False else out
    act_n = _act_name(act)
    if act_n:
        out = getattr(fl, act_n)(out)
    return register_layer_output(name, out)


def block_expand(input, block_x, block_y, stride_x=1, stride_y=1,
                 padding_x=0, padding_y=0, num_channels=None, name=None,
                 **kw):
    """Image to sequence of blocks (reference: block_expand_layer over
    BlockExpandLayer.cpp -> im2sequence)."""
    return register_layer_output(
        name, fl.im2sequence(input=input,
                             filter_size=[block_y, block_x],
                             stride=[stride_y, stride_x],
                             padding=[padding_y, padding_x]))


def bilinear_interp(input, out_size_x, out_size_y, name=None, **kw):
    return _helper_op("bilinear_interp", {"X": [input]},
                      {"out_h": int(out_size_y), "out_w": int(out_size_x)},
                      name=name, dtype=input.dtype)


def rotate(input, height, width, name=None, **kw):
    c = input.shape[-1] // (height * width)
    return _helper_op("rotate", {"X": [input]},
                      {"channels": int(c), "height": int(height),
                       "width": int(width)}, name=name,
                      dtype=input.dtype)


def out_prod(a, b, name=None, **kw):
    return _helper_op("out_prod", {"X": [a], "Y": [b]}, name=name,
                      dtype=a.dtype)


def linear_comb(weights, vectors, size, name=None, **kw):
    return _helper_op("linear_comb",
                      {"X": [vectors], "W": [weights]},
                      {"size": int(size)}, name=name,
                      dtype=vectors.dtype)


convex_comb = linear_comb


def conv_shift(a, b, name=None, **kw):
    return _helper_op("conv_shift", {"X": [a], "Y": [b]}, name=name,
                      dtype=a.dtype)


def pad(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kw):
    """Zero-pad [B,C,H,W] per dimension (reference: pad_layer)."""
    paddings = []
    for p in ((0, 0), tuple(pad_c or (0, 0)), tuple(pad_h or (0, 0)),
              tuple(pad_w or (0, 0))):
        paddings.extend(p)
    return _helper_op("pad", {"X": [input]}, {"paddings": paddings},
                      name=name, dtype=input.dtype)


def crop(input, shape=None, offsets=None, axis=0, name=None, **kw):
    return _helper_op("crop", {"X": [input]},
                      {"shape": list(shape), "offsets": list(offsets or
                                                             [0] * 4)},
                      name=name, dtype=input.dtype)


def scale_sub_region(input, indices, value=1.0, name=None, **kw):
    return _helper_op("scale_sub_region",
                      {"X": [input], "Indices": [indices]},
                      {"value": float(value)}, name=name,
                      dtype=input.dtype)


def prelu(input, param_attr=None, name=None, **kw):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("prelu", param_attr=param_attr)
    alpha = helper.create_parameter(helper.param_attr,
                                    shape=[input.shape[-1]],
                                    dtype=input.dtype)
    return _helper_op("prelu", {"X": [input], "Alpha": [alpha]},
                      name=name, dtype=input.dtype)


def multiplex(input, index=None, name=None, **kw):
    if index is None:
        index, input = input[0], input[1:]
    return register_layer_output(
        name, fl.multiplex(inputs=list(input), index=index))


def row_conv(input, context_len, act=None, param_attr=None, name=None,
             **kw):
    return register_layer_output(
        name, fl.row_conv(input=input,
                          future_context_size=context_len - 1,
                          param_attr=param_attr, act=_act_name(act)))


def dropout_layer(input, dropout_rate, name=None, **kw):
    return dropout(input, dropout_rate, name=name)


def sampling_id(input, name=None, **kw):
    return _helper_op("sampling_id", {"X": [input]}, name=name,
                      dtype="int64", stop_gradient=True)


def printer(input, format=None, name=None, **kw):
    outs = input if isinstance(input, (list, tuple)) else [input]
    return [fl.Print(o) for o in outs][0]


# -- costs -------------------------------------------------------------------

def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, **kw):
    """Hierarchical sigmoid cost (reference: hsigmoid over
    HierarchicalSigmoidLayer.cpp)."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.param_attr import ParamAttr

    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr)
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_classes - 1, d],
                                dtype=input.dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[1, num_classes - 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = _helper_op("hsigmoid", inputs,
                     {"num_classes": int(num_classes)})
    return register_layer_output(name, fl.mean(x=out))


def nce(input, label, num_classes, param_attr=None, bias_attr=None,
        num_neg_samples=10, name=None, **kw):
    out = fl.nce(input=input, label=label,
                 num_total_classes=num_classes, param_attr=param_attr,
                 bias_attr=bias_attr, num_neg_samples=num_neg_samples)
    return register_layer_output(name, fl.mean(x=out))


def ctc(input, label, size=None, norm_by_times=False, name=None, **kw):
    """CTC cost (reference: ctc_layer over CTCLayer.cpp; lowered to the
    same native CTC as warp_ctc)."""
    cost = fl.warpctc(input=input, label=label,
                      norm_by_times=norm_by_times)
    return register_layer_output(name, fl.mean(x=cost))


def warp_ctc(input, label, size=None, blank=0, norm_by_times=False,
             name=None, **kw):
    cost = fl.warpctc(input=input, label=label, blank=blank,
                      norm_by_times=norm_by_times)
    return register_layer_output(name, fl.mean(x=cost))


def rank_cost(left, right, label, weight=None, name=None, **kw):
    """Pairwise ranking cost (reference: rank_cost over
    RankingCost.cpp -> rank_loss_op)."""
    out = _helper_op("rank_loss",
                     {"Left": [left], "Right": [right],
                      "Label": [label]})
    if weight is not None:
        out = fl.elementwise_mul(x=out, y=weight)
    return register_layer_output(name, fl.mean(x=out))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None,
                **kw):
    out = _helper_op("lambda_cost",
                     {"Score": [input], "Label": [score]},
                     {"NDCG_num": int(NDCG_num)}, lod_level=1)
    return register_layer_output(name, fl.mean(x=out))


def cross_entropy_with_selfnorm(input, label,
                                softmax_selfnorm_alpha=0.1,
                                name=None, **kw):
    out = _helper_op("cross_entropy_selfnorm",
                     {"X": [input], "Label": [label]},
                     {"softmax_selfnorm_alpha":
                      float(softmax_selfnorm_alpha)},
                     lod_level=getattr(input, "lod_level", 0))
    return register_layer_output(name, fl.mean(x=out))


def multi_binary_label_cross_entropy(input, label, name=None, **kw):
    out = _helper_op("multi_binary_label_cross_entropy",
                     {"X": [input], "Label": [label]},
                     lod_level=getattr(input, "lod_level", 0))
    return register_layer_output(name, fl.mean(x=out))


def huber_regression_cost(input, label, delta=1.0, name=None, **kw):
    out = _helper_op("huber_loss", {"X": [input], "Y": [label]},
                     {"delta": float(delta)}, n_outs=2,
                     out_slots=("Out", "Residual"))
    return register_layer_output(name, fl.mean(x=out[0]))


def huber_classification_cost(input, label, name=None, **kw):
    out = _helper_op("modified_huber_loss",
                     {"X": [input], "Y": [label]}, n_outs=2,
                     out_slots=("Out", "IntermediateVal"))
    return register_layer_output(name, fl.mean(x=out[0]))


def smooth_l1_cost(input, label, name=None, **kw):
    return register_layer_output(
        name, fl.mean(x=fl.smooth_l1(x=input, y=label)))


# -- detection ---------------------------------------------------------------

def priorbox(input, image, min_size, max_size=(), aspect_ratio=(),
             variance=(0.1, 0.1, 0.2, 0.2), name=None, **kw):
    out = _helper_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"min_sizes": list(min_size) if isinstance(
            min_size, (list, tuple)) else [min_size],
         "max_sizes": list(max_size), "aspect_ratios":
         list(aspect_ratio) or [1.0], "variances": list(variance)},
        n_outs=2, out_slots=("Boxes", "Variances"), stop_gradient=True)
    return out


def roi_pool(input, rois, pooled_width, pooled_height, spatial_scale,
             name=None, **kw):
    out = _helper_op("roi_pool", {"X": [input], "ROIs": [rois]},
                     {"pooled_height": int(pooled_height),
                      "pooled_width": int(pooled_width),
                      "spatial_scale": float(spatial_scale)},
                     n_outs=2, out_slots=("Out", "Argmax"))
    return register_layer_output(name, out[0])


def detection_output(input_loc, input_conf, priorbox, num_classes,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     confidence_threshold=0.01, background_id=0,
                     name=None, **kw):
    return _helper_op(
        "detection_output",
        {"Loc": [input_loc], "Scores": [input_conf],
         "PriorBox": [priorbox]},
        {"nms_threshold": float(nms_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "score_threshold": float(confidence_threshold),
         "background_label": int(background_id)},
        name=name, lod_level=1, stop_gradient=True)


def multibox_loss(input_loc, input_conf, priorbox, label, gt_box,
                  num_classes, overlap_threshold=0.5,
                  neg_pos_ratio=3.0, background_id=0, name=None, **kw):
    """SSD training cost (reference: layers.py multibox_loss_layer over
    MultiBoxLossLayer.cpp).  `gt_box` is the ragged [G, 4] ground-truth
    box sequence and `label` its ragged [G, 1] class ids — the
    reference packs both into one label blob; they are separate data
    layers here.  Returns the mean per-image loss."""
    out = _helper_op(
        "multibox_loss",
        {"Loc": [input_loc], "Conf": [input_conf],
         "PriorBox": [priorbox], "GtBox": [gt_box],
         "GtLabel": [label]},
        {"num_classes": int(num_classes),
         "overlap_threshold": float(overlap_threshold),
         "neg_pos_ratio": float(neg_pos_ratio),
         "background_label_id": int(background_id)},
        out_slots=("Loss",))
    return register_layer_output(name, fl.mean(x=out))
