"""v2 training events (reference: python/paddle/v2/event.py)."""

__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "EndForwardBackward", "TestResult"]


class WithMetric:
    def __init__(self, evaluator=None):
        self.evaluator = evaluator


class TestResult(WithMetric):
    def __init__(self, evaluator=None, cost=None):
        WithMetric.__init__(self, evaluator)
        self.cost = cost


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator=None, gm=None):
        WithMetric.__init__(self, evaluator)
        self.pass_id = pass_id
        self.gm = gm


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward:
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.gm = gm


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator=None, gm=None):
        WithMetric.__init__(self, evaluator)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.gm = gm
