"""v2 input type declarations (reference: python/paddle/v2/data_type.py
over paddle/trainer/PyDataProviderWrapper InputType)."""

__all__ = [
    "dense_vector", "dense_array", "dense_vector_sequence",
    "dense_vector_sub_sequence", "integer_value",
    "integer_value_sequence", "integer_value_sub_sequence",
    "sparse_binary_vector", "sparse_float_vector", "InputType",
]


class InputType:
    def __init__(self, dim, seq_level, dtype, shape=None):
        self.dim = dim
        self.seq_level = seq_level
        self.dtype = dtype
        self.shape = shape if shape is not None else [dim]


def dense_vector(dim, seq_type=0):
    return InputType(dim, seq_type, "float32")


def dense_array(dim, shape, seq_type=0):
    return InputType(dim, seq_type, "float32", shape=list(shape))


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def dense_vector_sub_sequence(dim):
    """Nested sequence of dense vectors (reference: data_type.py
    seq_type=2 — sequence of subsequences)."""
    return InputType(dim, 2, "float32")


def integer_value(value_range, seq_type=0):
    return InputType(value_range, seq_type, "int64", shape=[1])


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64", shape=[1])


def integer_value_sub_sequence(value_range):
    return InputType(value_range, 2, "int64", shape=[1])


def sparse_binary_vector(dim, seq_type=0):
    # sparse inputs feed as integer id lists (lookup-table style)
    return InputType(dim, max(seq_type, 1), "int64", shape=[1])


def sparse_float_vector(dim, seq_type=0):
    return InputType(dim, max(seq_type, 1), "float32")
