"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio; the recordio path feeds from the native
chunk files that the master leases out)."""

import pickle

__all__ = ["np_array", "text_file", "recordio", "cloud_reader"]


def np_array(x):
    """reference: creator.py np_array — yield rows of an ndarray."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    """reference: creator.py text_file — yield lines without newline."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=None):
    """Read pickled samples from native RecordIO chunk files
    (reference: creator.py recordio over recordio.reader; here the
    container is native/recordio.cc with per-record CRC)."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        from ..native import RecordIOReader

        for path in paths:
            rd = RecordIOReader(path)
            try:
                for rec in rd:
                    yield pickle.loads(rec)
            finally:
                rd.close()

    return reader


def recordio_writer(path, samples):
    """Write an iterable of picklable samples as one chunk file."""
    from ..native import RecordIOWriter

    w = RecordIOWriter(path)
    try:
        for s in samples:
            w.write(pickle.dumps(s))
    finally:
        w.close()


def cloud_reader(master_endpoint, pass_num=1):
    """Fault-tolerant distributed reader: lease chunk tasks from the
    master, read their records, report finish/failure (reference:
    python/paddle/v2/master/client.py next_record + reader integration;
    task lease/timeout semantics of go/master/service.go).
    """
    host, port = master_endpoint.rsplit(":", 1)

    def reader():
        from ..native import MasterClient

        c = MasterClient(host, int(port))
        try:
            passes = 0
            while passes < pass_num:
                tid, chunks = c.get_task()
                if tid == MasterClient.PASS_FINISHED:
                    passes += 1
                    continue
                if tid == MasterClient.NO_TASK:
                    import time

                    time.sleep(0.05)
                    continue
                try:
                    for sample in recordio(chunks)():
                        yield sample
                except Exception:
                    c.task_failed(tid)
                    raise
                c.task_finished(tid)
        finally:
            c.close()

    return reader
