"""Reader decorators + creators (reference: python/paddle/v2/reader/)."""

from .decorator import *  # noqa: F401,F403
from .decorator import __all__ as _dec_all
from . import creator  # noqa: F401
from .prefetch import device_prefetch, host_prefetch  # noqa: F401

__all__ = list(_dec_all) + ["creator", "device_prefetch",
                            "host_prefetch"]
