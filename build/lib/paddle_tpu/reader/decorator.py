"""Composable reader decorators.

A "reader" is a zero-arg callable returning an iterable of samples —
the lazy data-pipeline contract shared with the reference API
(reference: python/paddle/v2/reader/decorator.py, minibatch.py).  The
implementations here are built from two local primitives: generator
composition for the synchronous decorators, and a queue-fed background
stage (:func:`_spawn_stage`) for the threaded ones.  Ordered parallel
map uses a heap + condition variable rather than a spin-wait.
"""

import heapq
import itertools
import random
import threading
from queue import Queue

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "batch"]

# unique end-of-stream marker for queue-based stages (identity compare)
_STOP = object()


class _Failure:
    """An exception captured in a pipeline stage, to be re-raised in
    the consumer (a dead daemon thread would otherwise leave the
    consumer blocked on q.get() forever, with no traceback)."""

    def __init__(self, exc):
        self.exc = exc


def _spawn_stage(target, *args, fail_q):
    """Run `target(*args)` on a daemon thread (a pipeline stage);
    failures are forwarded to `fail_q`, the queue the consumer drains."""

    def guarded():
        try:
            target(*args)
        except BaseException as exc:  # noqa: BLE001 — forwarded, not dropped
            fail_q.put(_Failure(exc))

    t = threading.Thread(target=guarded, daemon=True)
    t.start()
    return t


def _drain(q):
    """Yield items from queue `q` until the _STOP marker arrives;
    re-raise any stage failure here, in the consuming thread."""
    while True:
        item = q.get()
        if item is _STOP:
            return
        if isinstance(item, _Failure):
            raise item.exc
        yield item


def map_readers(func, *readers):
    """Reader yielding func(a, b, ...) over parallel-zipped readers."""

    def mapped():
        return map(func, *(r() for r in readers))

    return mapped


def shuffle(reader, buf_size):
    """Shuffle within a sliding window of `buf_size` samples."""

    def shuffled():
        window = []
        for sample in reader():
            window.append(sample)
            if len(window) >= buf_size:
                random.shuffle(window)
                yield from window
                window.clear()
        random.shuffle(window)
        yield from window

    return shuffled


def chain(*readers):
    """Concatenate readers end to end."""

    def chained():
        return itertools.chain.from_iterable(r() for r in readers)

    return chained


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different sample counts."""


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, (b, c)) -> (a, b, c).

    With check_alignment (default), unequal lengths raise
    ComposeNotAligned; otherwise the longest-exhausted prefix is used.
    """
    check_alignment = kwargs.pop("check_alignment", True)

    def as_tuple(sample):
        return sample if isinstance(sample, tuple) else (sample,)

    def composed():
        iters = [r() for r in readers]
        if check_alignment:
            rows = itertools.zip_longest(*iters, fillvalue=_STOP)
        else:
            rows = zip(*iters)
        for row in rows:
            # identity check: samples may be numpy arrays, where ==
            # broadcasts and `in` would raise
            if any(s is _STOP for s in row):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield tuple(itertools.chain.from_iterable(map(as_tuple, row)))

    return composed


def buffered(reader, size):
    """Decouple production from consumption via a bounded queue."""

    def produce(src, q):
        for sample in src:
            q.put(sample)
        q.put(_STOP)

    def buffered_reader():
        q = Queue(maxsize=size)
        _spawn_stage(produce, reader(), q, fail_q=q)
        yield from _drain(q)

    return buffered_reader


def firstn(reader, n):
    """Truncate a reader to its first n samples."""

    def truncated():
        return itertools.islice(reader(), n)

    return truncated


def cache(reader):
    """Materialize the reader once; replay from memory thereafter."""
    samples = tuple(reader())

    def replay():
        return iter(samples)

    return replay


class _OrderedEmitter:
    """Re-serialize (seq, value) pairs from racing workers.

    Workers hand results in any order; emit() releases them to the
    output queue strictly by sequence number, parking early arrivals
    in a heap.  A worker that has raced more than `bound` results
    ahead of the release point blocks until the head of line moves —
    without this, one slow sample would let the heap buffer the whole
    mapped dataset (the bounded queues give no backpressure while the
    output queue stays empty)."""

    def __init__(self, out_queue, bound):
        self._out = out_queue
        self._bound = max(int(bound), 1)
        self._next = 0
        self._parked = []
        self._cv = threading.Condition()

    def emit(self, seq, value):
        with self._cv:
            # the worker holding the next-needed seq never waits
            while seq - self._next >= self._bound and seq != self._next:
                self._cv.wait()
            heapq.heappush(self._parked, (seq, value))
            released = False
            while self._parked and self._parked[0][0] == self._next:
                _, ready = heapq.heappop(self._parked)
                self._out.put(ready)
                self._next += 1
                released = True
            if released:
                self._cv.notify_all()


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` to samples on `process_num` worker threads.

    With order=True, output order matches input order (at the cost of
    head-of-line buffering); otherwise results stream as completed.
    """

    def feed(src, in_q):
        for seq, sample in enumerate(src):
            in_q.put((seq, sample))
        for _ in range(process_num):
            in_q.put(_STOP)  # one stop marker per worker

    def work(in_q, out_q, emitter, done):
        for seq, sample in _drain(in_q):
            result = mapper(sample)
            if emitter is not None:
                emitter.emit(seq, result)
            else:
                out_q.put(result)
        with done["lock"]:
            done["count"] += 1
            if done["count"] == process_num:
                out_q.put(_STOP)

    def xmapped():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)
        emitter = _OrderedEmitter(out_q, buffer_size) if order else None
        done = {"lock": threading.Lock(), "count": 0}
        # failures (reader or mapper) surface on out_q: the consumer
        # re-raises; remaining daemon workers are abandoned
        _spawn_stage(feed, reader(), in_q, fail_q=out_q)
        for _ in range(process_num):
            _spawn_stage(work, in_q, out_q, emitter, done, fail_q=out_q)
        yield from _drain(out_q)

    return xmapped


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists of `batch_size`.

    drop_last defaults True on TPU: a ragged tail batch would change
    the feed shape and force an XLA recompile.
    """

    def batched():
        it = iter(reader())
        while True:
            group = list(itertools.islice(it, batch_size))
            if len(group) == batch_size:
                yield group
            else:
                if group and not drop_last:
                    yield group
                return

    return batched
