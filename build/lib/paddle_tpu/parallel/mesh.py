"""Device mesh construction.

Replaces the reference's device enumeration + communicator setup
(reference: operators/get_places_op.cc, operators/nccl/nccl_gpu_common.h:35
platform::Communicator, MultiGradientMachine device threads).  A Mesh with
named axes is the TPU-native "communicator": collectives are implied by
shardings over its axes and ride ICI.
"""

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "MeshConfig"]


class MeshConfig:
    """Axis layout for a training job.

    dp: data parallel (batch) — gradient all-reduce rides this axis.
    mp: model/tensor parallel — weight shards; matmul partials reduce here.
    Extended axes (pp pipeline, sp sequence) are carved out of the same
    device list by callers that need them.
    """

    def __init__(self, dp=None, mp=1, axes=("dp", "mp")):
        self.dp = dp
        self.mp = mp
        self.axes = tuple(axes)


def make_mesh(n_devices=None, dp=None, mp=1, sp=1, pp=1, ep=1,
              axes=None, devices=None, drop_unit_axes=False):
    """Build a Mesh over the five parallelism axes.

    dp defaults to n_devices // (mp*sp*pp*ep).  With mp=1 this is pure
    data parallelism (the MultiGradientMachine/parallel_do capability);
    mp>1 shards weights (tensor parallelism), sp shards sequences
    (ring/Ulysses attention), pp pipelines stages, ep shards experts.
    By default the mesh keeps the ("dp", "mp") axes even at size 1
    (back-compat with ParallelTrainer); extended axes appear when
    requested, and drop_unit_axes=True trims every size-1 axis
    (at least "dp" always remains).
    """
    sizes = {"dp": dp, "mp": mp, "sp": sp, "pp": pp, "ep": ep}
    if axes is None:
        axes = ("dp", "mp") if (sp == pp == ep == 1) else tuple(
            a for a in ("dp", "mp", "sp", "pp", "ep")
            if a == "dp" or sizes[a] > 1)
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # asked for more chips than the default platform has (e.g.
            # a dry run on a host with one real TPU): fall back to the
            # virtual CPU devices ONLY when the caller deliberately
            # provisioned enough of them via
            # xla_force_host_platform_device_count; otherwise this is a
            # genuine under-provisioning error — say so.
            try:
                cpu_devices = jax.devices("cpu")
            except RuntimeError:  # cpu backend excluded by JAX_PLATFORMS
                cpu_devices = []
            if len(cpu_devices) >= n_devices:
                devices = cpu_devices
            else:
                raise ValueError(
                    "requested a %d-device mesh but only %d %s device(s)"
                    " are available (and %d virtual CPU devices); set "
                    "xla_force_host_platform_device_count for a CPU dry "
                    "run or pass devices= explicitly"
                    % (n_devices, len(devices), devices[0].platform,
                       len(cpu_devices)))
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if any(a not in sizes for a in axes):
        # custom axis NAMES with (dp, mp) semantics, e.g.
        # axes=("data", "model"): sizes map positionally
        if len(axes) != 2:
            raise ValueError("custom axis names are only supported for "
                             "two-axis (dp, mp)-shaped meshes; got %r"
                             % (axes,))
        if sp != 1 or pp != 1 or ep != 1:
            raise ValueError("sp/pp/ep cannot combine with custom axis "
                             "names %r" % (axes,))
        sizes = {axes[0]: dp, axes[1]: mp}
        dp_name = axes[0]
    else:
        dp_name = "dp"
        dropped = [a for a, s in sizes.items()
                   if a not in axes and s not in (None, 1)]
        if dropped:
            raise ValueError(
                "axis size(s) %s requested but axes=%r omits them — an "
                "explicit axes tuple must name every non-unit axis"
                % ({a: sizes[a] for a in dropped}, tuple(axes)))
    denom = int(np.prod([sizes[a] for a in axes if a != dp_name]))
    if dp is None:
        if n_devices % denom != 0:
            raise ValueError("n_devices %d not divisible by %d (product "
                             "of non-dp axes)" % (n_devices, denom))
        dp = n_devices // denom
    if dp * denom != n_devices:
        raise ValueError("axis product (%d*%d) != n_devices %d"
                         % (dp, denom, n_devices))
    sizes[dp_name] = dp
    if drop_unit_axes:
        # "dp" always survives: batch_spec / trainer / moe default to a
        # dp axis existing, and a dp=1 axis costs nothing
        axes = tuple(a for a in axes if sizes[a] > 1 or a == dp_name)
    dev_array = np.array(devices).reshape([sizes[a] for a in axes])
    return Mesh(dev_array, axis_names=tuple(axes))
