"""Flash attention (pallas TPU kernel, online softmax).

No reference counterpart (the 2018 snapshot predates flash attention;
its attention is composed ops — reference: python/paddle/v2/fluid/
nets.py:338 scaled_dot_product_attention materializes the full [T,T]
probability matrix).  This kernel never materializes T×T in HBM: K/V
stream through VMEM in blocks with running max/sum accumulation, the
MXU sees [block_q, d] x [d, block_k] matmuls, and the backward pass
recomputes probabilities blockwise (custom VJP).

On CPU (tests) the same kernel runs under pallas interpret mode.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _needs_interpret():
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *, block_k,
                sm_scale, causal, q_offset):
    """One (batch*head, q_block) program: stream K/V blocks with online
    softmax accumulation."""
    q = q_ref[...] * sm_scale                    # [bq, d]
    bq, d = q.shape
    kt = k_ref[...]                              # [Tk, d]
    vt = v_ref[...]                              # [Tk, d]
    Tk = kt.shape[0]
    q_idx = pl.program_id(1)

    m = jnp.full((bq,), NEG_INF, jnp.float32)
    l = jnp.zeros((bq,), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    nblocks = Tk // block_k

    def body(i, carry):
        m, l, acc = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kt, i * block_k, block_k)
        v_blk = jax.lax.dynamic_slice_in_dim(vt, i * block_k, block_k)
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = q_offset + q_idx * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p.astype(vt.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nblocks, body, (m, l, acc))
    safe_l = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / safe_l[:, None]).astype(o_ref.dtype)
    m_ref[...] = m
    l_ref[...] = l


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, q_offset):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    while Tq % bq:
        bq //= 2
    while Tk % bk:
        bk //= 2
    bq, bk = max(bq, 1), max(bk, 1)

    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)

    grid = (B * H, Tq // bq)
    kernel = functools.partial(_fwd_kernel, block_k=bk, sm_scale=sm_scale,
                               causal=causal, q_offset=q_offset)
    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bq), lambda b, i: (b, i)),
            pl.BlockSpec((None, bq), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq), jnp.float32),
            jax.ShapeDtypeStruct((B * H, Tq), jnp.float32),
        ],
        interpret=_needs_interpret(),
    )(qf, kf, vf)
    return (o.reshape(B, H, Tq, D), m.reshape(B, H, Tq),
            l.reshape(B, H, Tq))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, sm_scale=None, causal=False, block_q=128,
                    block_k=128, q_offset=0):
    """softmax(q k^T * scale [+ causal mask]) v without materializing
    the score matrix.  q,k,v: [B, H, T, D]; q_offset shifts the causal
    diagonal (used by ring attention where q is a sequence shard)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, _, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, q_offset)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k,
                    q_offset):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, m, l = _fwd(q, k, v, sm_scale, causal, block_q, block_k, q_offset)
    return o, (q, k, v, o, m, l)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, q_offset, res,
                    do):
    """Blockwise recompute backward (the standard flash-attention VJP):
    dv = p^T do; dp = do v^T; ds = p*(dp - rowsum(do*o)); dq = ds k;
    dk = ds^T q.  Runs as plain XLA over k-blocks via scan — the
    recompute keeps memory at O(T*block) like the forward."""
    q, k, v, o, m, l = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bk = min(block_k, Tk)
    while Tk % bk:
        bk //= 2
    bk = max(bk, 1)

    safe_l = jnp.where(l > 0, l, 1.0)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                           # [B,H,Tq]
    qs = q.astype(jnp.float32) * sm_scale
    q_pos = q_offset + jnp.arange(Tq)

    def per_block(carry, i):
        dq = carry
        k_blk = jax.lax.dynamic_slice_in_dim(k, i * bk, bk, axis=2)
        v_blk = jax.lax.dynamic_slice_in_dim(v, i * bk, bk, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_blk.astype(jnp.float32))
        if causal:
            k_pos = i * bk + jnp.arange(bk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - m[..., None]) / safe_l[..., None]   # [B,H,q,k]
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p,
                            do.astype(jnp.float32))
        dp = jnp.einsum("bhqd,bhkd->bhqk", do.astype(jnp.float32),
                        v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])                    # [B,H,q,k]
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_blk.astype(jnp.float32)) * sm_scale
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qs)
        return dq, (dk_blk, dv_blk)

    nblocks = Tk // bk
    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(per_block, dq0, jnp.arange(nblocks))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, Tk, D)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, Tk, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def reference_attention(q, k, v, sm_scale=None, causal=False, q_offset=0):
    """Dense O(T^2)-memory attention for parity tests."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        mask = (q_offset + jnp.arange(Tq))[:, None] >= jnp.arange(Tk)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
