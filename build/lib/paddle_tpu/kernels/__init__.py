"""Pallas TPU kernels for hot ops.

The reference hand-writes CUDA for its hot paths (reference:
paddle/cuda/src/hl_cuda_lstm.cu fused cells, paddle/operators/math/*.cu);
here XLA fusion covers most of that, and pallas carries the kernels XLA
can't schedule optimally — attention (online softmax) first.
"""

from .flash_attention import flash_attention

__all__ = ["flash_attention"]
