"""ctypes bindings to the native C++ runtime (native/libpaddle_tpu_rt.so).

The native library provides the services the reference implements in
C++/Go rather than Python (reference: paddle/pserver/ParameterServer2,
go/master/service.go, recordio, paddle/memory BuddyAllocator); the TPU
compute path stays in XLA — this layer is the host/DCN runtime around
it.  Built on demand with `make` (g++, no external deps).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["lib", "ParameterServer", "PServerClient", "Master",
           "MasterClient", "RecordIOWriter", "RecordIOReader",
           "BuddyAllocator", "OPT_SGD", "OPT_MOMENTUM", "OPT_ADAGRAD",
           "OPT_ADAM"]

OPT_SGD = 0
OPT_MOMENTUM = 1
OPT_ADAGRAD = 2
OPT_ADAM = 3

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

if not os.path.isdir(_NATIVE_DIR):
    # installed-wheel layout: sources land under
    # <prefix>/paddle_tpu_native (setup.py data_files); build in a
    # writable per-user cache instead of the checkout
    import sys as _sys

    _installed = os.path.join(_sys.prefix, "paddle_tpu_native", "native")
    _cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "paddle_tpu", "native")
    if os.path.isdir(_installed) and not os.path.isdir(_cache):
        import shutil as _shutil

        os.makedirs(os.path.dirname(_cache), exist_ok=True)
        _shutil.copytree(_installed, _cache)
    if os.path.isdir(_cache):
        _NATIVE_DIR = _cache

_SO_PATH = os.path.join(_NATIVE_DIR, "libpaddle_tpu_rt.so")
_build_lock = threading.Lock()
_lib = None


def _build():
    proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    if proc.returncode != 0:
        raise RuntimeError(
            "native runtime build failed (make -C %s):\n%s"
            % (_NATIVE_DIR, proc.stdout.decode(errors="replace")))


def _stale():
    """True when any native source/header is newer than the built .so
    (binaries are not committed; make is cheap and a no-op when fresh)."""
    so_mtime = os.path.getmtime(_SO_PATH)
    for f in os.listdir(_NATIVE_DIR):
        if (f.endswith((".cc", ".h")) or f == "Makefile") and \
                os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > so_mtime:
            return True
    return False


def lib():
    """Load (building if needed) the native runtime library."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) or _stale():
            _build()
        L = ctypes.CDLL(_SO_PATH)
        c = ctypes
        sigs = {
            "ptrt_pserver_start":
                (c.c_void_p, [c.c_int, c.c_int, c.c_int, c.c_int]),
            "ptrt_pserver_stop": (None, [c.c_void_p]),
            "ptrt_pserver_port": (c.c_int, [c.c_void_p]),
            "ptrt_pserver_save": (c.c_int, [c.c_void_p, c.c_char_p]),
            "ptrt_pserver_load": (c.c_int, [c.c_void_p, c.c_char_p]),
            "ptrt_pserver_num_updates": (c.c_int64, [c.c_void_p]),
            "ptrt_pserver_num_lagged": (c.c_int64, [c.c_void_p]),
            "ptrt_pserver_num_sparse_rows": (c.c_int64, [c.c_void_p]),
            "ptrt_client_connect": (c.c_void_p, [c.c_char_p, c.c_int]),
            "ptrt_client_close": (None, [c.c_void_p]),
            "ptrt_client_init_param":
                (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64,
                           c.c_int, c.c_double, c.c_double, c.c_double,
                           c.c_double]),
            "ptrt_client_send_grad":
                (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64,
                           c.c_void_p, c.c_int64,
                           c.POINTER(c.c_int64)]),
            "ptrt_client_get_param":
                (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_int64,
                           c.POINTER(c.c_int64)]),
            "ptrt_client_send_sparse_grad":
                (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_void_p,
                           c.c_int64, c.c_int64]),
            "ptrt_client_get_rows":
                (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_void_p,
                           c.c_int64, c.c_int64]),
            "ptrt_client_barrier": (c.c_int, [c.c_void_p]),
            "ptrt_master_start": (c.c_void_p, [c.c_int, c.c_int, c.c_int]),
            "ptrt_master_stop": (None, [c.c_void_p]),
            "ptrt_master_port": (c.c_int, [c.c_void_p]),
            "ptrt_master_snapshot": (c.c_int, [c.c_void_p, c.c_char_p]),
            "ptrt_master_recover": (c.c_int, [c.c_void_p, c.c_char_p]),
            "ptrt_mclient_connect": (c.c_void_p, [c.c_char_p, c.c_int]),
            "ptrt_mclient_close": (None, [c.c_void_p]),
            "ptrt_mclient_set_dataset":
                (c.c_int, [c.c_void_p, c.POINTER(c.c_char_p), c.c_int,
                           c.c_int]),
            "ptrt_mclient_get_task":
                (c.c_int64, [c.c_void_p, c.c_char_p, c.c_int64]),
            "ptrt_mclient_task_finished": (c.c_int, [c.c_void_p, c.c_int64]),
            "ptrt_mclient_task_failed": (c.c_int, [c.c_void_p, c.c_int64]),
            "ptrt_mclient_register":
                (c.c_int64, [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]),
            "ptrt_mclient_keepalive": (c.c_int, [c.c_void_p, c.c_int64]),
            "ptrt_mclient_unregister": (c.c_int, [c.c_void_p, c.c_int64]),
            "ptrt_mclient_list":
                (c.c_int64, [c.c_void_p, c.c_char_p, c.c_char_p,
                             c.c_int64]),
            "ptrt_recordio_writer_open": (c.c_void_p, [c.c_char_p]),
            "ptrt_recordio_write":
                (c.c_int, [c.c_void_p, c.c_void_p, c.c_int64]),
            "ptrt_recordio_writer_close": (c.c_int, [c.c_void_p]),
            "ptrt_recordio_reader_open": (c.c_void_p, [c.c_char_p]),
            "ptrt_recordio_read":
                (c.c_int64, [c.c_void_p, c.c_void_p, c.c_int64]),
            "ptrt_recordio_reader_close": (None, [c.c_void_p]),
            "ptrt_buddy_create": (c.c_void_p, [c.c_int64, c.c_int64]),
            "ptrt_buddy_alloc": (c.c_void_p, [c.c_void_p, c.c_int64]),
            "ptrt_buddy_free": (None, [c.c_void_p, c.c_void_p]),
            "ptrt_buddy_used": (c.c_int64, [c.c_void_p]),
            "ptrt_buddy_destroy": (None, [c.c_void_p]),
        }
        for name, (restype, argtypes) in sigs.items():
            fn = getattr(L, name)
            fn.restype = restype
            fn.argtypes = argtypes
        _lib = L
        return _lib


def _f32(a):
    return np.ascontiguousarray(a, dtype=np.float32)


class ParameterServer:
    """In-process pserver (reference: ParameterServerController starts
    pservers in-process for tests; production runs one per host)."""

    def __init__(self, port=0, num_trainers=1, sync=True,
                 async_lagged_threshold=0):
        """async_lagged_threshold > 0 discards async gradients computed
        against parameters at least that many versions old (reference:
        ParameterServer2.h:243 lagged-async commit control; 0 keeps
        the unbounded legacy behavior)."""
        self._h = lib().ptrt_pserver_start(port, num_trainers,
                                           1 if sync else 0,
                                           int(async_lagged_threshold))

    @property
    def port(self):
        return lib().ptrt_pserver_port(self._h)

    def num_updates(self):
        return lib().ptrt_pserver_num_updates(self._h)

    def num_lagged(self):
        """Async gradients discarded by the staleness bound."""
        return lib().ptrt_pserver_num_lagged(self._h)

    def num_sparse_rows(self):
        """Total sparse rows applied via send_sparse_grad — proves the
        embedding updates shipped as rows, not dense tensors."""
        return lib().ptrt_pserver_num_sparse_rows(self._h)

    def save(self, path):
        return lib().ptrt_pserver_save(self._h, path.encode())

    def load(self, path):
        return lib().ptrt_pserver_load(self._h, path.encode())

    def stop(self):
        if self._h:
            lib().ptrt_pserver_stop(self._h)
            self._h = None


class PServerClient:
    def __init__(self, host, port):
        self._h = lib().ptrt_client_connect(host.encode(), port)
        if not self._h:
            raise ConnectionError("cannot connect to pserver %s:%d"
                                  % (host, port))
        # last server version seen per param: the base version stamped
        # onto outgoing gradients for the async staleness bound
        self._versions = {}
        self.last_grad_applied = True

    def init_param(self, name, value, opt_kind=OPT_SGD, lr=0.01,
                   hp1=0.0, hp2=0.0, hp3=0.0):
        v = _f32(value).reshape(-1)
        rc = lib().ptrt_client_init_param(
            self._h, name.encode(), v.ctypes.data_as(ctypes.c_void_p),
            v.size, opt_kind, lr, hp1, hp2, hp3)
        if rc != 0:
            raise RuntimeError("init_param(%s) rc=%d" % (name, rc))

    def send_grad(self, name, grad):
        """Blocking: returns the freshly updated parameter (sync mode
        waits for all trainers' gradients).  In async mode a gradient
        older than the server's staleness bound is discarded
        (last_grad_applied False); the returned parameter is fresh
        either way, so the trainer resynchronizes."""
        g = _f32(grad).reshape(-1)
        out = np.empty_like(g)
        new_ver = ctypes.c_int64(0)
        rc = lib().ptrt_client_send_grad(
            self._h, name.encode(), g.ctypes.data_as(ctypes.c_void_p),
            g.size, out.ctypes.data_as(ctypes.c_void_p),
            self._versions.get(name, 0), ctypes.byref(new_ver))
        if rc not in (0, 4):
            raise RuntimeError("send_grad(%s) rc=%d" % (name, rc))
        self._versions[name] = new_ver.value
        self.last_grad_applied = rc == 0
        return out

    def get_param(self, name, size):
        out = np.empty(size, np.float32)
        ver = ctypes.c_int64(0)
        rc = lib().ptrt_client_get_param(
            self._h, name.encode(), out.ctypes.data_as(ctypes.c_void_p),
            out.size, ctypes.byref(ver))
        if rc != 0:
            raise RuntimeError("get_param(%s) rc=%d" % (name, rc))
        self._versions[name] = ver.value
        return out

    def send_sparse_grad(self, name, rows, values):
        rows = np.ascontiguousarray(rows, np.int32)
        vals = _f32(values)
        assert vals.ndim == 2 and vals.shape[0] == rows.size
        rc = lib().ptrt_client_send_sparse_grad(
            self._h, name.encode(),
            rows.ctypes.data_as(ctypes.c_void_p),
            vals.ctypes.data_as(ctypes.c_void_p), rows.size,
            vals.shape[1])
        if rc != 0:
            raise RuntimeError("send_sparse_grad(%s) rc=%d" % (name, rc))

    def get_rows(self, name, rows, width):
        rows = np.ascontiguousarray(rows, np.int32)
        out = np.empty((rows.size, width), np.float32)
        rc = lib().ptrt_client_get_rows(
            self._h, name.encode(),
            rows.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), rows.size, width)
        if rc != 0:
            raise RuntimeError("get_rows(%s) rc=%d" % (name, rc))
        return out

    def barrier(self):
        rc = lib().ptrt_client_barrier(self._h)
        if rc != 0:
            raise RuntimeError("barrier rc=%d" % rc)

    def close(self):
        if self._h:
            lib().ptrt_client_close(self._h)
            self._h = None


class Master:
    def __init__(self, port=0, timeout_ms=5000, failure_max=3):
        self._h = lib().ptrt_master_start(port, timeout_ms, failure_max)

    @property
    def port(self):
        return lib().ptrt_master_port(self._h)

    def snapshot(self, path):
        return lib().ptrt_master_snapshot(self._h, path.encode())

    def recover(self, path):
        return lib().ptrt_master_recover(self._h, path.encode())

    def stop(self):
        if self._h:
            lib().ptrt_master_stop(self._h)
            self._h = None


class MasterClient:
    PASS_FINISHED = -2
    NO_TASK = -1

    def __init__(self, host, port):
        self._h = lib().ptrt_mclient_connect(host.encode(), port)
        if not self._h:
            raise ConnectionError("cannot connect to master %s:%d"
                                  % (host, port))

    def set_dataset(self, chunk_paths, chunks_per_task=1):
        arr = (ctypes.c_char_p * len(chunk_paths))(
            *[p.encode() for p in chunk_paths])
        rc = lib().ptrt_mclient_set_dataset(self._h, arr,
                                            len(chunk_paths),
                                            chunks_per_task)
        if rc != 0:
            raise RuntimeError("set_dataset rc=%d" % rc)

    def get_task(self):
        """Returns (task_id, [chunk paths]); task_id is NO_TASK/-1 when
        tasks are all leased out, PASS_FINISHED/-2 exactly once when a
        pass drains (the queue then recycles for the next pass).
        Raises ConnectionError if the master is unreachable."""
        buf = ctypes.create_string_buffer(1 << 20)
        tid = lib().ptrt_mclient_get_task(self._h, buf, len(buf))
        if tid == -3:
            raise ConnectionError("master unreachable")
        if tid == -4:
            raise ValueError("task chunk list exceeds client buffer")
        if tid < 0:
            return tid, []
        chunks = buf.value.decode().split("\n") if buf.value else []
        return tid, chunks

    def task_finished(self, task_id):
        lib().ptrt_mclient_task_finished(self._h, task_id)

    def task_failed(self, task_id):
        lib().ptrt_mclient_task_failed(self._h, task_id)

    # -- TTL-lease registry (reference: go/pserver/etcd_client.go) ------

    def register(self, key, value, ttl_ms):
        """Claim `key` with a TTL lease; returns the lease id, or None
        if a live lease already holds the key."""
        lease = lib().ptrt_mclient_register(self._h, key.encode(),
                                            value.encode(), int(ttl_ms))
        if lease == -2:
            raise ConnectionError("master unreachable")
        return None if lease < 0 else lease

    def keep_alive(self, lease):
        """Renew; returns False when the lease already lapsed (the
        holder must re-register)."""
        rc = lib().ptrt_mclient_keepalive(self._h, int(lease))
        if rc == -2:
            raise ConnectionError("master unreachable")
        return rc == 0

    def unregister(self, lease):
        lib().ptrt_mclient_unregister(self._h, int(lease))

    def list_prefix(self, prefix):
        """{key: value} of unexpired leases under `prefix`."""
        buf = ctypes.create_string_buffer(1 << 20)
        n = lib().ptrt_mclient_list(self._h, prefix.encode(), buf,
                                    len(buf))
        if n == -2:
            raise ConnectionError("master unreachable")
        if n < 0:
            raise RuntimeError("list_prefix rc=%d" % n)
        out = {}
        if buf.value:
            for line in buf.value.decode().split("\n"):
                k, _, v = line.partition("=")
                out[k] = v
        return out

    def close(self):
        if self._h:
            lib().ptrt_mclient_close(self._h)
            self._h = None


class RecordIOWriter:
    def __init__(self, path):
        self._h = lib().ptrt_recordio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def write(self, data):
        if isinstance(data, str):
            data = data.encode()
        rc = lib().ptrt_recordio_write(self._h, data, len(data))
        if rc != 0:
            raise IOError("write failed rc=%d" % rc)

    def close(self):
        if self._h:
            lib().ptrt_recordio_writer_close(self._h)
            self._h = None


class RecordIOReader:
    def __init__(self, path, max_record=1 << 24):
        self._h = lib().ptrt_recordio_reader_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)
        self._buf = ctypes.create_string_buffer(max_record)

    def __iter__(self):
        return self

    def __next__(self):
        n = lib().ptrt_recordio_read(self._h, self._buf, len(self._buf))
        if n == -1:
            raise StopIteration
        if n < 0:
            raise IOError("corrupt record (rc=%d)" % n)
        return self._buf.raw[:n]

    def close(self):
        if self._h:
            lib().ptrt_recordio_reader_close(self._h)
            self._h = None


class BuddyAllocator:
    def __init__(self, total_bytes, min_block=64):
        self._h = lib().ptrt_buddy_create(total_bytes, min_block)

    def alloc(self, n):
        p = lib().ptrt_buddy_alloc(self._h, n)
        if not p:
            raise MemoryError("buddy pool exhausted (%d bytes)" % n)
        return p

    def free(self, p):
        lib().ptrt_buddy_free(self._h, p)

    @property
    def used(self):
        return lib().ptrt_buddy_used(self._h)

    def destroy(self):
        if self._h:
            lib().ptrt_buddy_destroy(self._h)
            self._h = None
