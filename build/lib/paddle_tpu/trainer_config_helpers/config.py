"""Config-file protocol for the paddle_trainer-style CLI.

The reference CLI (`paddle train --config=conf.py`, reference:
paddle/trainer/TrainerMain.cpp:32 + trainer/config_parser.py) embeds
Python to evaluate a config script that calls `settings(...)`,
`define_py_data_sources2(...)`, builds layers, and declares
`outputs(cost)`; the trainer then drives that topology.  Here the same
three calls record into a per-process config registry that
`paddle_tpu.tools.trainer_cli` consumes — the topology itself is the
default fluid Program the DSL layers already build into.

Data-provider convention (replaces the reference's @provider
decorators): `module.obj` must be a callable
`obj(file_list, **(args or {})) -> reader`, where reader() yields
sample tuples in data-layer declaration order.
"""

import importlib

__all__ = ["settings", "outputs", "define_py_data_sources2",
           "get_config", "reset_config"]


class TrainerConfig:
    def __init__(self):
        self.batch_size = 32
        self.learning_rate = 1e-3
        self.lr_explicit = False        # settings() gave learning_rate
        self.learning_method = None     # v2 optimizer object
        self.outputs = []               # declared output/cost layers
        self.train_source = None        # (file_list, module, obj, args)
        self.test_source = None
        self.extra = {}                 # unrecognized settings() kwargs


_config = TrainerConfig()


def get_config():
    return _config


def reset_config():
    global _config
    _config = TrainerConfig()
    return _config


def settings(batch_size=None, learning_rate=None, learning_method=None,
             **kwargs):
    """reference: trainer_config_helpers/optimizers.py settings — batch
    size, learning rate, and the optimization method for the run."""
    if batch_size is not None:
        _config.batch_size = int(batch_size)
    if learning_rate is not None:
        _config.learning_rate = float(learning_rate)
        _config.lr_explicit = True
    if learning_method is not None:
        _config.learning_method = learning_method
    _config.extra.update(kwargs)


def outputs(*layers):
    """Declare the topology's output layers; training uses the first as
    the cost (reference: config_parser outputs())."""
    _config.outputs = [l for group in layers
                       for l in (group if isinstance(group, (list, tuple))
                                 else [group])]


def define_py_data_sources2(train_list, test_list, module, obj,
                            args=None):
    """Register train/test data providers (reference:
    trainer_config_helpers/data_sources.py:158).  `module`/`obj` may
    each be a single name or a (train, test) pair."""
    def pick(v, idx):
        return v[idx] if isinstance(v, (list, tuple)) else v

    if train_list is not None:
        _config.train_source = (train_list, pick(module, 0),
                                pick(obj, 0), pick(args, 0))
    if test_list is not None:
        _config.test_source = (test_list, pick(module, 1),
                               pick(obj, 1), pick(args, 1))


def build_reader(source):
    """(file_list, module, obj, args) -> reader callable."""
    if source is None:
        return None
    file_list, module, obj, args = source
    mod = importlib.import_module(module)
    provider = getattr(mod, obj)
    return provider(file_list, **(args or {}))
