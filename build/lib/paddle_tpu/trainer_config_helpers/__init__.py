"""trainer_config_helpers — the original v2 config DSL surface.

reference: python/paddle/trainer_config_helpers/layers.py (7.5k LoC of
`*_layer` functions), activations.py, poolings.py, attrs.py,
optimizers.py, networks.py.  Here every `*_layer` name maps onto the
one TPU-native stack via paddle_tpu.v2.layer — same call signatures for
the common arguments, one implementation underneath.
"""

from ..v2 import activation as _act
from ..v2 import attr as _attr
from ..v2 import layer as _layer
from ..v2 import networks as _networks
from ..v2 import optimizer as _optimizer
from ..v2 import pooling as _pooling
from ..v2.data_type import (dense_vector, integer_value,  # noqa: F401
                            integer_value_sequence, dense_vector_sequence)
from .config import (settings, outputs,  # noqa: F401
                     define_py_data_sources2)

# optimizers (reference: trainer_config_helpers/optimizers.py)
MomentumOptimizer = _optimizer.Momentum
AdamOptimizer = _optimizer.Adam
AdamaxOptimizer = _optimizer.Adamax
AdaGradOptimizer = _optimizer.AdaGrad
DecayedAdaGradOptimizer = _optimizer.DecayedAdaGrad
AdaDeltaOptimizer = _optimizer.AdaDelta
RMSPropOptimizer = _optimizer.RMSProp

# activations (reference: trainer_config_helpers/activations.py)
TanhActivation = _act.Tanh
SigmoidActivation = _act.Sigmoid
SoftmaxActivation = _act.Softmax
IdentityActivation = _act.Identity
LinearActivation = _act.Linear
ReluActivation = _act.Relu
BReluActivation = _act.BRelu
SoftReluActivation = _act.SoftRelu
STanhActivation = _act.STanh
AbsActivation = _act.Abs
SquareActivation = _act.Square
ExpActivation = _act.Exp
LogActivation = _act.Log

# poolings (reference: trainer_config_helpers/poolings.py)
MaxPooling = _pooling.Max
AvgPooling = _pooling.Avg
SumPooling = _pooling.Sum
SqrtNPooling = _pooling.SquareRootN

# attrs (reference: trainer_config_helpers/attrs.py)
ParamAttr = _attr.Param
ParameterAttribute = _attr.Param
ExtraAttr = _attr.Extra
ExtraLayerAttribute = _attr.Extra

# layers (reference: trainer_config_helpers/layers.py *_layer funcs)
def data_layer(name, size=None, type=None, height=None, width=None,
               depth=None, **kw):
    """reference: layers.py data_layer(name, size[, depth, height,
    width]) — the DSL spelling takes a flat size (+ optional
    volumetric/image dims); the v2 spelling takes an InputType.  Both
    accepted here."""
    if type is None:
        if size is None:
            raise ValueError("data_layer needs size= or type=")
        if height and width:
            spatial = (depth or 1) * height * width
            if size % spatial:
                raise ValueError(
                    "data_layer size %d is not divisible by the "
                    "%s dims %s" % (size,
                                    "depth*height*width" if depth
                                    else "height*width", spatial))
            channels = size // spatial
            from ..v2.data_type import dense_array

            shape = ([channels, depth, height, width] if depth
                     else [channels, height, width])
            type = dense_array(size, shape)
        else:
            type = dense_vector(size)
    return _layer.data(name=name, type=type, **kw)
fc_layer = _layer.fc
embedding_layer = _layer.embedding
img_conv_layer = _layer.img_conv
img_pool_layer = _layer.img_pool
batch_norm_layer = _layer.batch_norm
lstmemory = _layer.lstmemory
grumemory = _layer.grumemory
pooling_layer = _layer.pool
first_seq = _layer.first_seq
last_seq = _layer.last_seq
concat_layer = _layer.concat
seq_concat_layer = _layer.seq_concat
dropout_layer = _layer.dropout
addto_layer = _layer.addto
classification_cost = _layer.classification_cost
cross_entropy = _layer.cross_entropy_cost
cross_entropy_cost = _layer.cross_entropy_cost
regression_cost = _layer.regression_cost
square_error_cost = _layer.square_error_cost
mse_cost = _layer.mse_cost
crf_layer = _layer.crf
crf_decoding_layer = _layer.crf_decoding
maxid_layer = _layer.max_id
expand_layer = _layer.expand
cos_sim = _layer.cos_sim
scaling_layer = _layer.scaling
slope_intercept_layer = _layer.slope_intercept
sum_cost = _layer.sum_cost
trans_layer = _layer.trans
mixed_layer = _layer.mixed
full_matrix_projection = _layer.full_matrix_projection
identity_projection = _layer.identity_projection
table_projection = _layer.table_projection
dotmul_projection = _layer.dotmul_projection
context_projection = _layer.context_projection

trans_full_matrix_projection = _layer.trans_full_matrix_projection
scaling_projection = _layer.scaling_projection
slice_projection = _layer.slice_projection
conv_projection = _layer.conv_projection
dotmul_operator = _layer.dotmul_operator
conv_operator = _layer.conv_operator

# recurrent surface
StaticInput = _layer.StaticInput
SubsequenceInput = _layer.SubsequenceInput
GeneratedInput = _layer.GeneratedInput
memory = _layer.memory
recurrent_group = _layer.recurrent_group
beam_search = _layer.beam_search
get_output_layer = _layer.get_output_layer
eos_layer = _layer.eos_layer
gru_step_layer = _layer.gru_step_layer
gru_step_naive_layer = _layer.gru_step_naive_layer
lstm_step_layer = _layer.lstm_step_layer
recurrent_layer = _layer.recurrent

# extended zoo (reference *_layer names)
repeat_layer = _layer.repeat
seq_reshape_layer = _layer.seq_reshape
interpolation_layer = _layer.interpolation
power_layer = _layer.power
sum_to_one_norm_layer = _layer.sum_to_one_norm
row_l2_norm_layer = _layer.row_l2_norm
dot_prod_layer = _layer.dot_prod
l2_distance_layer = _layer.l2_distance
clip_layer = _layer.clip
resize_layer = _layer.resize
switch_order_layer = _layer.switch_order
scale_shift_layer = _layer.scale_shift
sub_seq_layer = _layer.sub_seq
seq_slice_layer = _layer.seq_slice
kmax_seq_score_layer = _layer.kmax_seq_score
sub_nested_seq_layer = _layer.sub_nested_seq
factorization_machine = _layer.factorization_machine
gated_unit_layer = _layer.gated_unit
tensor_layer = _layer.tensor
selective_fc_layer = _layer.selective_fc
maxout_layer = _layer.maxout
spp_layer = _layer.spp
img_cmrnorm_layer = _layer.img_cmrnorm
cross_channel_norm_layer = _layer.cross_channel_norm
img_pool3d_layer = _layer.img_pool3d
img_conv3d_layer = _layer.img_conv3d
block_expand_layer = _layer.block_expand
bilinear_interp_layer = _layer.bilinear_interp
rotate_layer = _layer.rotate
out_prod_layer = _layer.out_prod
linear_comb_layer = _layer.linear_comb
convex_comb_layer = _layer.convex_comb
conv_shift_layer = _layer.conv_shift
pad_layer = _layer.pad
crop_layer = _layer.crop
scale_sub_region_layer = _layer.scale_sub_region
prelu_layer = _layer.prelu
multiplex_layer = _layer.multiplex
row_conv_layer = _layer.row_conv
sampling_id_layer = _layer.sampling_id
printer_layer = _layer.printer

# costs
hsigmoid = _layer.hsigmoid
nce_layer = _layer.nce
ctc_layer = _layer.ctc
warp_ctc_layer = _layer.warp_ctc
rank_cost = _layer.rank_cost
lambda_cost = _layer.lambda_cost
cross_entropy_with_selfnorm = _layer.cross_entropy_with_selfnorm
multi_binary_label_cross_entropy = _layer.multi_binary_label_cross_entropy
huber_regression_cost = _layer.huber_regression_cost
huber_classification_cost = _layer.huber_classification_cost
smooth_l1_cost = _layer.smooth_l1_cost

# detection
priorbox_layer = _layer.priorbox
roi_pool_layer = _layer.roi_pool
detection_output_layer = _layer.detection_output
multibox_loss_layer = _layer.multibox_loss

# networks (reference: trainer_config_helpers/networks.py)
simple_img_conv_pool = _networks.simple_img_conv_pool
img_conv_group = _networks.img_conv_group
sequence_conv_pool = _networks.sequence_conv_pool
simple_lstm = _networks.simple_lstm
bidirectional_lstm = _networks.bidirectional_lstm
simple_gru = _networks.simple_gru
simple_gru2 = _networks.simple_gru2
lstmemory_unit = _networks.lstmemory_unit
lstmemory_group = _networks.lstmemory_group
gru_unit = _networks.gru_unit
gru_group = _networks.gru_group
bidirectional_gru = _networks.bidirectional_gru
simple_attention = _networks.simple_attention
dot_product_attention = _networks.dot_product_attention
multi_head_attention = _networks.multi_head_attention
small_vgg = _networks.small_vgg
vgg_16_network = _networks.vgg_16_network

__all__ = [n for n in dir() if not n.startswith("_")]

# evaluators (reference: trainer_config_helpers/evaluators.py) — every
# name in the v2 evaluator DSL, kept in sync automatically
from ..v2 import evaluator as _evaluator  # noqa: E402

globals().update({n: getattr(_evaluator, n)
                  for n in _evaluator.__all__})

__all__ = [n for n in dir() if not n.startswith("_")]
