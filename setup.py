"""Packaging for paddle_tpu (reference: the CMake superbuild +
manylinux wheel pipeline, CMakeLists.txt:38-62 + tools/manylinux1).

The TPU build needs no compiled extension at wheel time: the compute
path is JAX/XLA, and the native runtime (pserver/master/recordio/
allocator) ships as C++ sources that `paddle_tpu.native` compiles once
at first use with the host toolchain (see native/Makefile).  So the
wheel is pure-Python plus the native/ source tree as package data.

    pip wheel .            # build a wheel
    pip install .          # or install straight into the env
"""

import os

from setuptools import setup, find_packages

_HERE = os.path.dirname(os.path.abspath(__file__))


def _native_sources():
    out = []
    for root, _dirs, files in os.walk(os.path.join(_HERE, "native")):
        for f in files:
            if f.endswith((".cc", ".h", "Makefile")) or f == "Makefile":
                out.append(os.path.relpath(os.path.join(root, f), _HERE))
    return out


setup(
    name="paddle_tpu",
    version="0.4.0",
    description="TPU-native deep learning framework with the "
                "PaddlePaddle v2/early-Fluid capability surface",
    packages=find_packages(include=["paddle_tpu", "paddle_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    # the native runtime builds from these at first use (installed
    # flat under <prefix>/paddle_tpu_native/native; paddle_tpu.native
    # copies them into a writable cache and makes there)
    data_files=[("paddle_tpu_native/native", _native_sources())],
    entry_points={
        "console_scripts": [
            "paddle_trainer=paddle_tpu.tools.trainer_cli:main",
            "paddle_serve=paddle_tpu.tools.serve_cli:main",
            "pperf=paddle_tpu.tools.perf_cli:main",
            "pmem=paddle_tpu.tools.mem_cli:main",
            "ptune=paddle_tpu.tools.tune_cli:main",
            "pshard=paddle_tpu.tools.shard_cli:main",
            "pcomm=paddle_tpu.tools.comm_cli:main",
            "pload=paddle_tpu.tools.load_cli:main",
            "pelastic=paddle_tpu.tools.elastic_cli:main",
        ],
    },
)
