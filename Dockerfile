# paddle_tpu runtime image (reference: the reference's Docker build
# pipeline, paddle/scripts/docker/build.sh — there it compiles the
# whole C++ tree; here the image is a Python env + host toolchain, and
# the small native runtime compiles at first import).
#
#   docker build -t paddle-tpu .
#   docker run --rm paddle-tpu python -m pytest tests/ -q
#
# For real TPUs use a TPU-VM base image that ships libtpu and install
# jax[tpu] instead of jax[cpu].
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /workspace/paddle_tpu
COPY . .

RUN pip install --no-cache-dir "jax[cpu]" numpy pytest && \
    pip install --no-cache-dir .

# build the native runtime now so first use in containers is instant
RUN make -C native

ENV JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8

CMD ["python", "-m", "pytest", "tests/", "-q"]
