"""Device mesh construction.

Replaces the reference's device enumeration + communicator setup
(reference: operators/get_places_op.cc, operators/nccl/nccl_gpu_common.h:35
platform::Communicator, MultiGradientMachine device threads).  A Mesh with
named axes is the TPU-native "communicator": collectives are implied by
shardings over its axes and ride ICI.
"""

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "MeshConfig"]


class MeshConfig:
    """Axis layout for a training job.

    dp: data parallel (batch) — gradient all-reduce rides this axis.
    mp: model/tensor parallel — weight shards; matmul partials reduce here.
    Extended axes (pp pipeline, sp sequence) are carved out of the same
    device list by callers that need them.
    """

    def __init__(self, dp=None, mp=1, axes=("dp", "mp")):
        self.dp = dp
        self.mp = mp
        self.axes = tuple(axes)


def make_mesh(n_devices=None, dp=None, mp=1, axes=("dp", "mp"),
              devices=None):
    """Build a Mesh of `n_devices` with shape (dp, mp).

    dp defaults to n_devices // mp.  With mp=1 this is pure data
    parallelism (the MultiGradientMachine/parallel_do capability); mp>1
    shards weights (tensor parallelism — new capability beyond the
    reference's per-layer ParallelNeuralNetwork placement).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # asked for more chips than the default platform has (e.g.
            # a dry run on a host with one real TPU): fall back to the
            # virtual CPU devices ONLY when the caller deliberately
            # provisioned enough of them via
            # xla_force_host_platform_device_count; otherwise this is a
            # genuine under-provisioning error — say so.
            try:
                cpu_devices = jax.devices("cpu")
            except RuntimeError:  # cpu backend excluded by JAX_PLATFORMS
                cpu_devices = []
            if len(cpu_devices) >= n_devices:
                devices = cpu_devices
            else:
                raise ValueError(
                    "requested a %d-device mesh but only %d %s device(s)"
                    " are available (and %d virtual CPU devices); set "
                    "xla_force_host_platform_device_count for a CPU dry "
                    "run or pass devices= explicitly"
                    % (n_devices, len(devices), devices[0].platform,
                       len(cpu_devices)))
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        if n_devices % mp != 0:
            raise ValueError("n_devices %d not divisible by mp %d"
                             % (n_devices, mp))
        dp = n_devices // mp
    if dp * mp != n_devices:
        raise ValueError("dp*mp (%d*%d) != n_devices %d"
                         % (dp, mp, n_devices))
    dev_array = np.array(devices).reshape(dp, mp)
    return Mesh(dev_array, axis_names=tuple(axes))
