"""Device mesh construction.

Replaces the reference's device enumeration + communicator setup
(reference: operators/get_places_op.cc, operators/nccl/nccl_gpu_common.h:35
platform::Communicator, MultiGradientMachine device threads).  A Mesh with
named axes is the TPU-native "communicator": collectives are implied by
shardings over its axes and ride ICI.
"""

from collections import OrderedDict

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "MeshConfig", "parse_mesh_spec"]

# the canonical axis vocabulary (docs/ANALYSIS.md "mesh axes"):
# dp data, mp model/tensor, sp sequence, pp pipeline, ep expert
AXIS_NAMES = ("dp", "mp", "sp", "pp", "ep")


class MeshConfig:
    """Axis layout for a training job.

    dp: data parallel (batch) — gradient all-reduce rides this axis.
    mp: model/tensor parallel — weight shards; matmul partials reduce here.
    sp/pp/ep: sequence / pipeline / expert parallelism over the same
    device list.

    A MeshConfig is a *static* mesh description: `.shape` exposes the
    same axis-name -> size mapping a built `jax.sharding.Mesh` has, so
    the sharding analyzer (`paddle_tpu.analysis.shard`) and the spec
    helpers in `sharding.py` accept either one — no devices needed to
    reason about a layout.
    """

    def __init__(self, dp=None, mp=1, sp=1, pp=1, ep=1, axes=None):
        self.dp = dp
        self.mp = mp
        self.sp = sp
        self.pp = pp
        self.ep = ep
        sizes = {"dp": dp, "mp": mp, "sp": sp, "pp": pp, "ep": ep}
        if axes is None:
            axes = ("dp", "mp") if (sp == pp == ep == 1) else tuple(
                a for a in AXIS_NAMES
                if a == "dp" or (sizes[a] or 1) > 1)
        self.axes = tuple(axes)

    @property
    def shape(self):
        """axis name -> size, in axis order (a dp of None means
        'whatever devices remain' and reads as size 1 here)."""
        sizes = {"dp": self.dp, "mp": self.mp, "sp": self.sp,
                 "pp": self.pp, "ep": self.ep}
        return OrderedDict(
            (a, int(sizes.get(a) or 1)) for a in self.axes)

    def validate(self, n_devices):
        """Check the axis product against a device count; raises a
        ValueError NAMING the axes (instead of the opaque numpy
        reshape error a bad product used to surface as)."""
        shape = self.shape
        product = int(np.prod(list(shape.values()))) if shape else 1
        if self.dp is None:
            denom = int(np.prod(
                [s for a, s in shape.items() if a != "dp"]))
            if denom == 0 or n_devices % denom:
                raise ValueError(
                    "%d device(s) not divisible by the non-dp axis "
                    "product %s = %d" % (n_devices, _axis_product_str(
                        {a: s for a, s in shape.items() if a != "dp"}),
                        denom))
        elif product != n_devices:
            raise ValueError(
                "mesh axis product %s = %d != %d device(s); resize an "
                "axis or the device set" % (_axis_product_str(shape),
                                            product, n_devices))
        return self

    @classmethod
    def parse(cls, spec):
        """Parse "dp=4,mp=2"-style mesh specs (the proglint --mesh
        syntax) into a MeshConfig with that exact axis order."""
        return parse_mesh_spec(spec)

    def __repr__(self):
        return "MeshConfig(%s)" % ",".join(
            "%s=%d" % (a, s) for a, s in self.shape.items())


def _axis_product_str(shape):
    return " * ".join("%s=%s" % (a, s) for a, s in shape.items()) \
        or "(no axes)"


def parse_mesh_spec(spec):
    """"dp=4,mp=2" -> MeshConfig(dp=4, mp=2, axes=("dp", "mp"))."""
    if isinstance(spec, MeshConfig):
        return spec
    sizes, axes = {}, []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                "bad mesh spec %r: expected comma-separated axis=size "
                "pairs like 'dp=4,mp=2'" % (spec,))
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in AXIS_NAMES:
            raise ValueError(
                "bad mesh spec %r: unknown axis %r (axes are %s)"
                % (spec, name, "/".join(AXIS_NAMES)))
        try:
            size = int(val)
        except ValueError:
            raise ValueError("bad mesh spec %r: size of axis %r is not "
                             "an integer" % (spec, name))
        if size < 1:
            raise ValueError("bad mesh spec %r: axis %r must be >= 1"
                             % (spec, name))
        if name in sizes:
            raise ValueError("bad mesh spec %r: axis %r named twice"
                             % (spec, name))
        sizes[name] = size
        axes.append(name)
    if not axes:
        raise ValueError("bad mesh spec %r: no axes" % (spec,))
    return MeshConfig(axes=tuple(axes), **sizes)


def make_mesh(n_devices=None, dp=None, mp=1, sp=1, pp=1, ep=1,
              axes=None, devices=None, drop_unit_axes=False):
    """Build a Mesh over the five parallelism axes.

    dp defaults to n_devices // (mp*sp*pp*ep).  With mp=1 this is pure
    data parallelism (the MultiGradientMachine/parallel_do capability);
    mp>1 shards weights (tensor parallelism), sp shards sequences
    (ring/Ulysses attention), pp pipelines stages, ep shards experts.
    By default the mesh keeps the ("dp", "mp") axes even at size 1
    (back-compat with ParallelTrainer); extended axes appear when
    requested, and drop_unit_axes=True trims every size-1 axis
    (at least "dp" always remains).
    """
    sizes = {"dp": dp, "mp": mp, "sp": sp, "pp": pp, "ep": ep}
    if axes is None:
        axes = ("dp", "mp") if (sp == pp == ep == 1) else tuple(
            a for a in ("dp", "mp", "sp", "pp", "ep")
            if a == "dp" or sizes[a] > 1)
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # asked for more chips than the default platform has (e.g.
            # a dry run on a host with one real TPU): fall back to the
            # virtual CPU devices ONLY when the caller deliberately
            # provisioned enough of them via
            # xla_force_host_platform_device_count; otherwise this is a
            # genuine under-provisioning error — say so.
            try:
                cpu_devices = jax.devices("cpu")
            except RuntimeError:  # cpu backend excluded by JAX_PLATFORMS
                cpu_devices = []
            if len(cpu_devices) >= n_devices:
                devices = cpu_devices
            else:
                raise ValueError(
                    "requested a %d-device mesh but only %d %s device(s)"
                    " are available (and %d virtual CPU devices); set "
                    "xla_force_host_platform_device_count for a CPU dry "
                    "run or pass devices= explicitly"
                    % (n_devices, len(devices), devices[0].platform,
                       len(cpu_devices)))
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if any(a not in sizes for a in axes):
        # custom axis NAMES with (dp, mp) semantics, e.g.
        # axes=("data", "model"): sizes map positionally
        if len(axes) != 2:
            raise ValueError("custom axis names are only supported for "
                             "two-axis (dp, mp)-shaped meshes; got %r"
                             % (axes,))
        if sp != 1 or pp != 1 or ep != 1:
            raise ValueError("sp/pp/ep cannot combine with custom axis "
                             "names %r" % (axes,))
        sizes = {axes[0]: dp, axes[1]: mp}
        dp_name = axes[0]
    else:
        dp_name = "dp"
        dropped = [a for a, s in sizes.items()
                   if a not in axes and s not in (None, 1)]
        if dropped:
            raise ValueError(
                "axis size(s) %s requested but axes=%r omits them — an "
                "explicit axes tuple must name every non-unit axis"
                % ({a: sizes[a] for a in dropped}, tuple(axes)))
    denom = int(np.prod([sizes[a] for a in axes if a != dp_name]))
    if dp is None:
        if n_devices % denom != 0:
            raise ValueError(
                "%d device(s) not divisible by the non-%s axis product "
                "%s = %d; resize an axis or pass %s explicitly"
                % (n_devices, dp_name,
                   _axis_product_str({a: sizes[a] for a in axes
                                      if a != dp_name}), denom, dp_name))
        dp = n_devices // denom
    if dp * denom != n_devices:
        raise ValueError(
            "mesh axis product %s = %d != %d device(s); resize an axis "
            "or the device set"
            % (_axis_product_str(
                {a: (dp if a == dp_name else sizes[a]) for a in axes}),
               dp * denom, n_devices))
    sizes[dp_name] = dp
    if drop_unit_axes:
        # "dp" always survives: batch_spec / trainer / moe default to a
        # dp axis existing, and a dp=1 axis costs nothing
        axes = tuple(a for a in axes if sizes[a] > 1 or a == dp_name)
    dev_array = np.array(devices).reshape([sizes[a] for a in axes])
    return Mesh(dev_array, axis_names=tuple(axes))
