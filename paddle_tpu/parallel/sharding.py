"""Sharding specs for program state and feeds.

The reference decides placement imperatively (scatter params to device
threads, MultiGradientMachine.h:100-140; split LoDTensor across places,
parallel_do_op.cc:37-47).  Here placement is declarative: every buffer
gets a NamedSharding over the mesh and XLA GSPMD partitions the program.
"""

import re as _re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["param_spec", "param_spec_reason", "batch_spec", "replicated",
           "shard_state", "shard_feeds", "zero1_spec",
           "zero1_spec_reason"]


def replicated(mesh):
    return NamedSharding(mesh, P())


def param_spec_reason(name, shape, mesh, mp_axis="mp", min_shard_dim=512):
    """(spec, reason) for a parameter under the default tensor-parallel
    layout.  `reason` is None when the spec shards (or replication is
    deliberate policy: no mp axis, or a non-2-D tensor the conv policy
    replicates on purpose); otherwise it is a sentence explaining what
    FORCED replication (min_shard_dim or divisibility) — the sharding
    analyzer's S001 cites it instead of letting the fallback stay
    silent."""
    if mp_axis not in mesh.shape:
        return P(), None
    mp = mesh.shape[mp_axis]
    if mp == 1:
        return P(), None
    if len(shape) != 2:
        return P(), None  # conv filters / biases / stats: policy
    rows, cols = int(shape[0]), int(shape[1])
    # embedding / big row-major tables: shard rows
    if rows >= min_shard_dim * mp and rows % mp == 0 and rows >= cols:
        return P(mp_axis, None), None
    if cols % mp == 0 and cols >= min_shard_dim:
        return P(None, mp_axis), None
    if rows % mp == 0 and rows >= min_shard_dim:
        return P(mp_axis, None), None
    if max(rows, cols) < min_shard_dim:
        reason = ("both dims of (%d, %d) are below min_shard_dim %d"
                  % (rows, cols, min_shard_dim))
    elif cols >= min_shard_dim and cols % mp:
        reason = ("cols %d not divisible by %s=%d (rows %d %s)"
                  % (cols, mp_axis, mp,
                     rows, "not divisible either" if rows % mp
                     else "below min_shard_dim %d" % min_shard_dim))
    else:
        reason = ("rows %d not divisible by %s=%d and cols %d below "
                  "min_shard_dim %d" % (rows, mp_axis, mp, cols,
                                        min_shard_dim))
    return P(), reason


def param_spec(name, shape, mesh, mp_axis="mp", min_shard_dim=512):
    """Default tensor-parallel layout for a parameter.

    Large 2-D weights (fc/projection) shard their output dim over mp;
    large embedding tables shard the vocab dim over mp (row-sharded like
    the reference's blockwise pserver partitioning,
    reference: pserver/ParameterServer2.h:73, distribute_transpiler.py:39);
    everything else (conv filters, biases, BN stats) is replicated — conv
    weights are small relative to activations, and replication keeps the
    conv spatially partitionable by dp.  See `param_spec_reason` for the
    variant that also says WHY a tensor fell back to replication.
    """
    spec, _reason = param_spec_reason(name, shape, mesh, mp_axis=mp_axis,
                                      min_shard_dim=min_shard_dim)
    return spec


def batch_spec(shape, mesh, dp_axis="dp"):
    """Feeds shard their leading (batch) dim over dp."""
    if dp_axis not in mesh.shape or len(shape) == 0:
        return P()
    return P(dp_axis)


def shard_state(state, mesh, var_shapes=None, mp_axis="mp"):
    """Return {name: NamedSharding} for a state dict (arrays or abstract)."""
    specs = {}
    for name, v in state.items():
        shape = v.shape if hasattr(v, "shape") else var_shapes[name]
        specs[name] = NamedSharding(mesh, param_spec(name, shape, mesh,
                                                     mp_axis=mp_axis))
    return specs


def shard_feeds(feeds, mesh, dp_axis="dp"):
    specs = {}
    for name, v in feeds.items():
        specs[name] = NamedSharding(mesh, batch_spec(v.shape, mesh,
                                                     dp_axis=dp_axis))
    return specs


# optimizer accumulator vars are named {param}_{acc}_{N} by
# fluid/optimizer.py _add_accumulator; these are the acc strings of the
# 11 optimizers
_ACC_NAME = _re.compile(
    r"_(velocity|moment[12]?|inf_norm|avg_squared_grad|"
    r"avg_squared_update|mean_square|squared|linear)_\d+$")

_OPTIMIZER_OPS = frozenset([
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
    # stacked same-recipe updates (fluid/fusion.py) — same slot layout,
    # so the Param/Grad/LearningRate exclusion below applies unchanged
    "fused_update"])

# optimizer-op input slots that are NOT accumulator state
_NON_STATE_SLOTS = frozenset(["Param", "Grad", "LearningRate"])


def optimizer_state_names(program):
    """The exact accumulator var names of a built program: every input
    to an optimizer op except Param/Grad/LearningRate.  Exact where the
    name-suffix regex is a guess (a user var named '*_squared_3' would
    fool the regex but can never appear in an optimizer slot)."""
    names = set()
    for block in program.blocks:
        for op in block.ops:
            if op.type not in _OPTIMIZER_OPS:
                continue
            for slot, vars_ in op.desc.inputs.items():
                if slot not in _NON_STATE_SLOTS:
                    names.update(vars_)
    return names


def is_optimizer_state(name, known=None):
    """`known` (from optimizer_state_names) is authoritative; the name
    regex is the fallback for detached state dicts with no program."""
    if known is not None:
        return name in known
    return bool(_ACC_NAME.search(name))


def zero1_spec_reason(base_spec, shape, mesh, dp_axis="dp"):
    """(spec, reason) for the ZeRO-1 layout of an optimizer-state
    tensor.  `reason` is None when a dim sharded (or there is no dp
    axis to shard over); otherwise it says why every dim stayed whole —
    the S001 citation for optimizer state that silently keeps dp full
    copies."""
    if dp_axis not in mesh.shape or mesh.shape[dp_axis] == 1:
        return base_spec, None
    dp = mesh.shape[dp_axis]
    dims = list(base_spec) + [None] * (len(shape) - len(base_spec))
    for i, (d, s) in enumerate(zip(dims, shape)):
        if d is None and int(s) % dp == 0 and int(s) >= dp:
            dims[i] = dp_axis
            return P(*dims), None
    if not shape:
        reason = "scalar state cannot shard over %s=%d" % (dp_axis, dp)
    else:
        reason = ("no free dim of %s divides %s=%d (zero-1 keeps %d "
                  "full copies)" % (tuple(int(s) for s in shape),
                                    dp_axis, dp, dp))
    return base_spec, reason


def zero1_spec(base_spec, shape, mesh, dp_axis="dp"):
    """ZeRO-1: shard an optimizer-state tensor over the dp axis on its
    first free, divisible dim (on top of any mp sharding the matching
    parameter has).  GSPMD then reduce-scatters the gradient into the
    shard-wise accumulator update and all-gathers the updated params —
    all-reduce bandwidth, 1/dp optimizer-state memory.  See
    `zero1_spec_reason` for the variant that reports why a tensor could
    not shard."""
    spec, _reason = zero1_spec_reason(base_spec, shape, mesh,
                                      dp_axis=dp_axis)
    return spec


def shard_map_norep(fn, **kwargs):
    """shard_map with replication checking off, across jax versions
    (`check_vma` replaced `check_rep`).  One shim shared by the ring /
    pipeline / moe modules so the compat logic can't drift."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, check_vma=False, **kwargs)
    except TypeError:
        return shard_map(fn, check_rep=False, **kwargs)
