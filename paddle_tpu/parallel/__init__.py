"""Multi-chip parallel training over a jax.sharding.Mesh.

TPU-native replacement for the reference's data/model parallel machinery:
  * MultiGradientMachine's per-GPU threads + ring gather/scatter
    (reference: paddle/gserver/gradientmachines/MultiGradientMachine.h:44-83)
  * parallel_do's LoDTensor split + per-place sub-scopes + NCCL allreduce
    (reference: paddle/operators/parallel_do_op.cc:112, nccl_op.cc:22-95)
  * ParallelNeuralNetwork's per-layer device placement
    (reference: paddle/gserver/gradientmachines/ParallelNeuralNetwork.h)

On TPU none of that is hand-built: we lay the *same program* out over a
device Mesh with named axes — "dp" (batch/data parallel) and "mp"
(model/tensor parallel) — annotate parameter and batch shardings, and let
XLA GSPMD partition the computation and insert the ICI collectives
(all-reduce/all-gather/reduce-scatter) that replace NCCL and the ring.
"""

from .mesh import make_mesh, MeshConfig, parse_mesh_spec
from .sharding import (param_spec, param_spec_reason, batch_spec,
                       shard_state, shard_feeds, replicated, zero1_spec,
                       zero1_spec_reason)
from .trainer import ParallelTrainer, make_parallel_step, verify_sharding
from .ring import (ring_attention, ulysses_attention, sp_shard_map,
                   sp_axis_info)
from .pipeline import (gpipe_spmd, pipeline_apply, split_microbatches,
                       stack_stage_params, pipeline_schedule_info)
from .moe import (switch_moe, moe_shard_map, init_moe_params,
                  expert_capacity, moe_axis_info)
from .program_api import (lower_program_fn, PipelineProgramTrainer,
                          MoEProgramLayer)
from .optim import PytreeOptimizer

__all__ = [
    "make_mesh", "MeshConfig", "parse_mesh_spec", "param_spec",
    "param_spec_reason", "batch_spec", "shard_state", "shard_feeds",
    "replicated", "zero1_spec", "zero1_spec_reason", "ParallelTrainer",
    "make_parallel_step", "verify_sharding",
    "ring_attention", "ulysses_attention", "sp_shard_map",
    "sp_axis_info", "gpipe_spmd", "pipeline_apply",
    "split_microbatches", "stack_stage_params",
    "pipeline_schedule_info", "switch_moe", "moe_shard_map",
    "init_moe_params", "expert_capacity", "moe_axis_info",
    "lower_program_fn", "PipelineProgramTrainer", "MoEProgramLayer",
    "PytreeOptimizer",
]
