"""Program-stack entries for pipeline and expert parallelism.

`ParallelTrainer` already drives dp x mp from a built Program; these
classes close the loop for the remaining axes: pipeline stages and MoE
experts are *built with fluid layers as Programs*, lowered through
FunctionalProgram (the same executor lowering every other program
takes), and their parameters initialized by running the startup program
— then the pp/ep schedules (pipeline.py / moe.py) stream them over the
mesh.  The reference's closest notions are per-layer device placement
(ParallelNeuralNetwork.h:25) and server-sharded parameters; here the
framework surface is the Program and the distribution is GSPMD +
shard_map underneath.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .pipeline import pipeline_apply, stack_stage_params
from .moe import moe_shard_map

__all__ = ["lower_program_fn", "PipelineProgramTrainer",
           "MoEProgramLayer"]

# Stage/expert builders construct fresh Programs (under program_guard),
# and name counters are per Program (fluid.framework.unique_name), so
# every replica build yields identical parameter names (fc_0.w_0 ...)
# by construction — no counter-resetting ceremony needed here.  The
# sorted-keys check in PipelineProgramTrainer still guards builders
# that emit divergent topologies.


def lower_program_fn(program, startup, feed_name, fetch_name, seed=None):
    """Lower a single-input single-output Program to a pure
    fn(params, x) -> y plus its startup-initialized parameters.

    The Program must not mutate state (no optimizer ops): stages and
    experts are pure transforms whose gradients flow through the
    surrounding schedule.
    """
    from ..fluid.executor import Executor, CPUPlace
    from ..core.scope import Scope
    from ..jit import FunctionalProgram, state_from_scope

    if seed is not None:
        startup.random_seed = int(seed)
    scope = Scope()
    Executor(CPUPlace()).run(startup, scope=scope)
    fp = FunctionalProgram(program, [feed_name], [fetch_name])
    if fp.state_out_names:
        raise ValueError(
            "stage/expert programs must be pure (no optimizer or "
            "state-mutating ops); %r writes %s"
            % (program, sorted(fp.state_out_names)))
    params = {n: np.asarray(v)
              for n, v in state_from_scope(fp, scope).items()}

    def fn(params, x):
        (y,), _ = fp(params, {feed_name: x})
        return y

    return fn, params


class PipelineProgramTrainer:
    """GPipe over fluid-built stages.

    build_stage(stage_idx) -> (program, startup, feed_name, fetch_name)
    must append identical layer topology for every stage (stage weights
    differ; names must match across stages so the per-stage states
    stack into the [S, ...] pp-sharded pytree).

    step(x, target) runs forward through the microbatch schedule,
    backprops through it (the ppermute transpose IS the backward
    pipeline), and applies `optimizer`'s declared update rule — a
    fluid.optimizer instance, its registered op kernel driven over the
    stacked stage weights by PytreeOptimizer — so pipeline training has
    the same accumulator state (velocity/moments) as executor training.
    """

    def __init__(self, build_stage, mesh, n_microbatches, pp_axis="pp",
                 optimizer=None, lr=0.1):
        from .optim import PytreeOptimizer
        from ..fluid.optimizer import MomentumOptimizer

        self.mesh = mesh
        self.n_microbatches = n_microbatches
        self.pp_axis = pp_axis
        if optimizer is None:
            optimizer = MomentumOptimizer(learning_rate=lr, momentum=0.9)
        self.optimizer = PytreeOptimizer(optimizer)
        from ..utils import flags as _flags

        if _flags.get_flag("verify_sharding"):
            from ..analysis import shard as _shard

            _shard.check_pipeline(
                mesh, n_stages=mesh.shape.get(pp_axis, 0),
                n_microbatches=n_microbatches,
                axis_name=pp_axis).raise_on_error()
        n_stages = mesh.shape[pp_axis]
        fns, states = [], []
        for i in range(n_stages):
            program, startup, feed, fetch = build_stage(i)
            fn, params = lower_program_fn(program, startup, feed, fetch,
                                          seed=i)
            fns.append(fn)
            states.append({n: jnp.asarray(v) for n, v in params.items()})
        keys = sorted(states[0])
        for s in states[1:]:
            if sorted(s) != keys:
                raise ValueError(
                    "stage programs disagree on parameter names: "
                    "%s vs %s" % (keys, sorted(s)))
        self.stage_fn = fns[0]
        self.stacked = stack_stage_params(states)
        # optimizer state stacks [S, ...] exactly like the params it
        # tracks, so it shards over pp with them
        self.opt_state = self.optimizer.init(self.stacked)
        self._step = None

    def _loss(self, stacked, x, tgt):
        out = pipeline_apply(self.mesh, self.stage_fn, stacked, x,
                             self.n_microbatches, axis_name=self.pp_axis)
        return jnp.mean(jnp.square(out - tgt))

    def step(self, x, tgt):
        if self._step is None:
            def _step(stacked, opt_state, x, tgt):
                loss, grads = jax.value_and_grad(self._loss)(stacked,
                                                             x, tgt)
                stacked, opt_state = self.optimizer.apply(
                    stacked, grads, opt_state)
                return loss, stacked, opt_state

            self._step = jax.jit(_step)
        loss, self.stacked, self.opt_state = self._step(
            self.stacked, self.opt_state, jnp.asarray(x),
            jnp.asarray(tgt))
        return float(loss)


class MoEProgramLayer:
    """Switch-MoE whose expert network is a fluid-built Program.

    build_expert() -> (program, startup, feed_name, fetch_name): the
    expert transform ([tokens, d] -> [tokens, d]).  One Program is
    built per expert (startup seeded per expert for distinct inits);
    their states stack into the [E, ...] ep-sharded pytree and apply
    vmapped over the local expert axis inside the dispatch/combine
    schedule.
    """

    def __init__(self, build_expert, n_experts, d_model, mesh,
                 ep_axis="ep", batch_axis="dp", capacity_factor=1.25,
                 seed=0):
        from ..utils import flags as _flags

        if _flags.get_flag("verify_sharding"):
            from ..analysis import shard as _shard

            _shard.check_moe(
                mesh, n_experts, capacity_factor=capacity_factor,
                axis_name=ep_axis,
                batch_axis=batch_axis).raise_on_error()
        expert_states, fns = [], []
        for e in range(n_experts):
            program, startup, feed, fetch = build_expert()
            fn, params = lower_program_fn(program, startup, feed, fetch,
                                          seed=seed + e)
            fns.append(fn)
            expert_states.append(
                {n: jnp.asarray(v) for n, v in params.items()})
        experts = stack_stage_params(expert_states)
        rs = np.random.RandomState(seed)
        self.params = {
            "gate_w": jnp.asarray(
                rs.randn(d_model, n_experts).astype(np.float32)
                * (2.0 / d_model) ** 0.5),
            "experts": experts,
        }
        expert_fn = jax.vmap(fns[0])   # over the local expert axis
        self.fn = moe_shard_map(
            mesh, axis_name=ep_axis, batch_axis=batch_axis,
            capacity_factor=capacity_factor, expert_fn=expert_fn,
            expert_param_template=experts)

    def __call__(self, params, x):
        return self.fn(params, x)
