"""Expert parallelism: Switch-style top-1 mixture-of-experts over an
"ep" mesh axis.

No reference counterpart (the 2018 snapshot predates MoE); included
because expert parallelism is a first-class distributed axis on TPU
pods.  Design is the standard TPU dispatch/combine einsum pattern:
tokens pick an expert by router argmax, are packed into per-expert
capacity slots, shipped to the expert's owner device with
`lax.all_to_all` over the ICI, transformed by the expert FFN, shipped
back, and combined weighted by the router probability.  Routing is
non-differentiable (argmax); gradients flow through the combine
weights and the expert FFN — exactly the Switch Transformer recipe,
with its load-balancing auxiliary loss.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .sharding import shard_map_norep

__all__ = ["switch_moe", "moe_shard_map", "init_moe_params",
           "expert_capacity", "moe_axis_info"]


def expert_capacity(tokens, n_experts, capacity_factor):
    """Per-expert capacity slots for `tokens` local tokens — THE
    capacity formula (switch_moe and the sharding analyzer's S004
    overflow check both use it, so they can never disagree)."""
    return max(1, int(capacity_factor * tokens / max(n_experts, 1)))


def moe_axis_info(mesh, n_experts, axis_name="ep", batch_axis="dp",
                  capacity_factor=1.25, tokens=None):
    """Static introspection of an MoE layout over `mesh` (or any
    axis->size mapping): expert ownership, token sharding, and
    capacity — what the analyzer's `check_moe` consumes."""
    shape = dict(getattr(mesh, "shape", mesh))
    ep = int(shape.get(axis_name, 1))
    dp = int(shape.get(batch_axis, 1))
    info = {"axis": axis_name, "ep": ep, "batch_axis": batch_axis,
            "n_experts": n_experts, "experts_per_device":
            (n_experts // ep if ep and n_experts % ep == 0 else None),
            "token_shards": ep * dp}
    if tokens is not None and info["token_shards"] \
            and tokens % info["token_shards"] == 0:
        local = tokens // info["token_shards"]
        info["local_tokens"] = local
        info["capacity"] = expert_capacity(local, n_experts,
                                           capacity_factor)
    return info


def init_moe_params(key, d_model, d_hidden, n_experts, dtype=jnp.float32):
    """Router + stacked expert FFN weights.  The leading n_experts axis
    of w1/b1/w2/b2 is the one to shard over "ep"."""
    ks = jax.random.split(jax.random.PRNGKey(key) if isinstance(key, int)
                          else key, 3)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate_w": jax.random.normal(ks[0], (d_model, n_experts),
                                    dtype) * s1,
        "w1": jax.random.normal(ks[1], (n_experts, d_model, d_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": jax.random.normal(ks[2], (n_experts, d_hidden, d_model),
                                dtype) * s2,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def switch_moe(params, x, axis_name="ep", capacity_factor=1.25,
               batch_axes=(), expert_fn=None):
    """Per-device MoE layer; call inside shard_map.

    params: gate_w [d, E] replicated; expert weights with the expert
    axis "ep"-sharded (local leading dim E/ep) — either the built-in
    FFN's w1/b1/w2/b2, or, with `expert_fn`, an "experts" pytree of
    arbitrary structure.  expert_fn(local_expert_params, xin) must map
    [e_loc, tokens, d] -> [e_loc, tokens, d] (e.g. a vmapped
    Program-lowered FFN).  x: [b, d] local tokens.  batch_axes: extra
    mesh axes the tokens shard over (e.g. ("dp",)) so the aux
    statistics average over ALL token shards.  Returns (y [b, d], aux)
    — aux is the Switch load-balancing loss
    (E * sum(fraction_routed * mean_router_prob); ~1 when balanced).
    """
    ep = lax.psum(1, axis_name)
    if expert_fn is None:
        e_loc = params["w1"].shape[0]
    else:
        e_loc = jax.tree_util.tree_leaves(params["experts"])[0].shape[0]
    n_expert = e_loc * ep
    b, d = x.shape

    # --- route (f32 softmax; tokens keep their activation dtype) ---
    logits = (x.astype(jnp.float32) @
              params["gate_w"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)            # [b, E]
    gate = jnp.max(probs, axis=-1)                     # [b]
    expert = jnp.argmax(probs, axis=-1)                # [b]
    onehot = jax.nn.one_hot(expert, n_expert,
                            dtype=jnp.float32)         # [b, E]

    # --- pack into capacity slots (per source device, per expert) ---
    capacity = expert_capacity(b, n_expert, capacity_factor)
    pos = jnp.cumsum(onehot, axis=0) - 1.0             # queue position
    in_cap = (pos < capacity) * onehot                 # dropped past C
    # dispatch is the single place capacity masking happens: one_hot of
    # a dropped token's slot is zeroed here and nowhere else
    dispatch = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32) * in_cap[..., None]
    combine = dispatch * gate[:, None, None]

    # --- dispatch: [b,d] -> [E, C, d] -> experts' owners over ICI ---
    # split_axis == concat_axis keeps the exchange self-transposed, so
    # jax.grad's transpose rule maps it onto the exact reverse exchange
    xin = jnp.einsum("bd,bec->ecd", x.astype(jnp.float32), dispatch)
    xin = xin.reshape(ep, e_loc, capacity, d)
    xin = lax.all_to_all(xin, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                  # [ep_src, e_loc, C, d]
    xin = jnp.transpose(xin, (1, 0, 2, 3)).reshape(e_loc,
                                                   ep * capacity, d)

    # --- expert FFN (vmapped over local experts; MXU batched) ---
    if expert_fn is None:
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin, params["w1"]) +
                        params["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) + \
            params["b2"][:, None, :]                   # [e_loc, ep*C, d]
    else:
        out = expert_fn(params["experts"], xin)        # [e_loc, ep*C, d]

    # --- ship results back and combine ---
    out = out.reshape(e_loc, ep, capacity, d)
    out = jnp.transpose(out, (1, 0, 2, 3))             # [ep_src, e_loc, C, d]
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                  # [ep_owner, e_loc, C, d]
    out = out.reshape(n_expert, capacity, d)
    y = jnp.einsum("ecd,bec->bd", out, combine).astype(x.dtype)

    # --- Switch aux loss: balance fraction-routed vs router mass ---
    frac = jnp.mean(onehot, axis=0)                    # [E]
    mass = jnp.mean(probs, axis=0)                     # [E]
    # average over EVERY axis the tokens shard across (ep + dp), so the
    # aux value is identical on all devices — out_specs declares it
    # replicated and the router gradient must match the reported loss
    stat_axes = (axis_name,) + tuple(batch_axes)
    frac = lax.pmean(frac, stat_axes)
    mass = lax.pmean(mass, stat_axes)
    aux = n_expert * jnp.sum(frac * mass)
    return y, aux


def moe_shard_map(mesh, axis_name="ep", batch_axis="dp",
                  capacity_factor=1.25, expert_fn=None,
                  expert_param_template=None):
    """Wrap switch_moe for `mesh`: tokens shard over (dp, ep) jointly,
    expert weights shard over ep, the router replicates.

    With `expert_fn`, params must be {"gate_w": ..., "experts": pytree}
    where every experts leaf has a leading [E] axis (sharded over ep);
    pass that pytree (or one with the same structure) as
    expert_param_template so the shard_map specs can be derived.

    Returns fn(params, x[B, d]) -> (y[B, d], aux)."""
    axes = tuple(a for a in (batch_axis, axis_name) if a in mesh.shape)
    x_spec = P(axes if len(axes) > 1 else (axes[0] if axes else None))
    if expert_fn is None:
        param_specs = {
            "gate_w": P(), "w1": P(axis_name), "b1": P(axis_name),
            "w2": P(axis_name), "b2": P(axis_name),
        }
    else:
        if expert_param_template is None:
            raise ValueError(
                "expert_fn needs expert_param_template to derive specs")
        param_specs = {
            "gate_w": P(),
            "experts": jax.tree_util.tree_map(
                lambda _: P(axis_name), expert_param_template),
        }
    fn = functools.partial(
        switch_moe, axis_name=axis_name, capacity_factor=capacity_factor,
        batch_axes=tuple(a for a in axes if a != axis_name),
        expert_fn=expert_fn)
    return shard_map_norep(fn, mesh=mesh, in_specs=(param_specs, x_spec),
                           out_specs=(x_spec, P()))
