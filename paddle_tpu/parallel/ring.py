"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

No reference counterpart (the 2018 snapshot has no sequence
parallelism — SURVEY §5 'long-context' gap); this is new TPU-first
design: the sequence axis shards over a mesh axis ("sp"), K/V shards
rotate around the ring with `lax.ppermute` (ICI neighbor exchange — the
TPU analog of the reference's ring gather in
MultiGradientMachine.h:61-76, but over sequence blocks instead of
gradients), and each step folds into a running online-softmax
accumulator so the full sequence never materializes on one chip.

Ulysses-style all-to-all trades the sequence axis for the head axis
instead: attention runs locally over full sequences for 1/sp of the
heads (one all-to-all before, one after).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharding import shard_map_norep
from ..kernels.flash_attention import flash_attention, NEG_INF

__all__ = ["ring_attention", "ulysses_attention", "sp_shard_map",
           "sp_axis_info", "ring_allreduce", "grad_buckets",
           "bucketed_allreduce"]


def sp_axis_info(mesh, seq_len=None, n_heads=None, axis_name="sp",
                 mode="ring"):
    """Static introspection of a sequence-parallel layout over `mesh`
    (or any axis->size mapping): shard extent and the divisibility
    requirements the schedule imposes — what the sharding analyzer's
    `check_ring` consumes."""
    shape = dict(getattr(mesh, "shape", mesh))
    sp = int(shape.get(axis_name, 0))
    info = {"axis": axis_name, "sp": sp, "mode": mode,
            "requires": ["seq_len %% %d == 0" % sp] if sp else []}
    if mode == "ulysses" and sp:
        info["requires"].append("n_heads %% %d == 0" % sp)
    if seq_len is not None and sp:
        info["local_seq"] = (seq_len // sp if seq_len % sp == 0
                             else None)
    if n_heads is not None and sp and mode == "ulysses":
        info["local_heads"] = (n_heads // sp if n_heads % sp == 0
                               else None)
    return info


def _block_attend(q, k, v, sm_scale, causal, q_start, k_start):
    """Unnormalized blockwise attention: returns (acc, m, l) where
    out = acc / l after all blocks merge.  q: [B,H,Tq,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        q_pos = q_start + jnp.arange(Tq)
        k_pos = k_start + jnp.arange(Tk)
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # [B,H,Tq]
    # fully-masked rows: exp(NEG_INF - NEG_INF)=1 would pollute l
    p = jnp.where((s > NEG_INF / 2),
                  jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (acc1 * a1[..., None] + acc2 * a2[..., None],
            m, l1 * a1 + l2 * a2)


def ring_attention(q, k, v, axis_name="sp", sm_scale=None, causal=False):
    """Attention with q/k/v sharded [B,H,T/sp,D] along `axis_name`.

    Call inside shard_map (or use sp_shard_map).  sp steps: local
    q attends the rotating k/v shard; partials merge via online
    softmax; k/v hop to the next neighbor with ppermute (ICI ring).
    Differentiable (ppermute/scan transpose gives the reverse ring).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_start = my * t_local

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # step 0: attend the locally-held shard (no communication), then
    # sp-1 hop+attend steps — sp-1 ppermutes total, none wasted
    acc, m, l = _block_attend(q, k, v, sm_scale, causal, q_start,
                              my * t_local)

    def step(carry, i):
        k_cur, v_cur, acc, m, l = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        # after i hops we hold shard (my - i) mod sp
        src = (my - i) % sp
        a, bm, bl = _block_attend(q, k_cur, v_cur, sm_scale, causal,
                                  q_start, src * t_local)
        acc, m, l = _merge(acc, m, l, a, bm, bl)
        return (k_cur, v_cur, acc, m, l), None

    (k, v, acc, m, l), _ = jax.lax.scan(
        step, (k, v, acc, m, l), jnp.arange(1, sp))
    safe_l = jnp.where(l > 0, l, 1.0)
    return (acc / safe_l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", sm_scale=None,
                      causal=False, use_flash=True):
    """All-to-all sequence parallelism: swap the sharded axis from
    sequence to heads, attend full sequences locally (flash kernel),
    swap back.  q/k/v local: [B, H, T/sp, D]; H must divide by sp."""
    sp = jax.lax.psum(1, axis_name)
    if q.shape[1] % sp:
        raise ValueError(
            "ulysses attention needs the head count (%d) divisible by "
            "the sp axis size (%d)" % (q.shape[1], sp))

    # tiled all_to_all does the split/concat in one collective with no
    # inserted axes: head-group g ships to device g while each device
    # gathers its group's sequence shards (and the inverse on the way
    # back).  The untiled reshape choreography used before produced a
    # mis-transposed cotangent under multi-axis meshes (dp x sp) in
    # jax's transpose rule; tiled is also simply clearer.
    def seq2head(x):
        # [B, H, t, D] -> [B, H/sp, T, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def head2seq(x):
        # [B, H/sp, T, D] -> [B, H, t, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    if use_flash:
        oh = flash_attention(qh, kh, vh, sm_scale, causal)
    else:
        from ..kernels.flash_attention import reference_attention

        oh = reference_attention(qh, kh, vh, sm_scale, causal)
    return head2seq(oh)


def ring_allreduce(x, axis_name="dp", mean=False):
    """All-reduce `x` over `axis_name` as an explicit ring: a
    reduce-scatter pass followed by an all-gather pass, each p-1
    neighbor hops of 1/p of the payload over `lax.ppermute` (the ICI
    neighbor exchange; reference: the gradient ring in
    MultiGradientMachine.h:61-76).  Call inside shard_map.

    Unlike `lax.psum` — which XLA lowers to one monolithic fused
    all-reduce per use site — each call here is its own collective
    chain, so bucketed callers (spmd/overlap.py) hand the scheduler
    p-1 independent hops per bucket to overlap with remaining
    backward compute.  Bandwidth-optimal: 2*(p-1)/p of the payload
    crosses each link.
    """
    p = jax.lax.psum(1, axis_name)
    if p == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.size
    m = -(-n // p)  # chunk size, padded up to a multiple of p
    if m * p != n:
        flat = jnp.pad(flat, (0, m * p - n))
    chunks = flat.reshape(p, m)

    # reduce-scatter: after p-1 hops device i owns the fully-reduced
    # chunk (i+1) mod p
    buf = jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)
    for s in range(1, p):
        buf = jax.lax.ppermute(buf, axis_name, perm)
        j = (idx - s) % p
        buf = buf + jax.lax.dynamic_index_in_dim(chunks, j, 0,
                                                 keepdims=False)
        chunks = jax.lax.dynamic_update_index_in_dim(chunks, buf, j, 0)
    # all-gather: circulate the reduced chunks the rest of the way
    for s in range(1, p):
        buf = jax.lax.ppermute(buf, axis_name, perm)
        chunks = jax.lax.dynamic_update_index_in_dim(
            chunks, buf, (idx - s + 1) % p, 0)
    out = chunks.reshape(-1)[:n]
    if mean:
        out = out / p
    return out.reshape(shape).astype(dtype)


def grad_buckets(sized_names, bucket_bytes):
    """Group (name, nbytes) pairs into reduction buckets of at most
    `bucket_bytes` each (always at least one name per bucket).  The
    input order is preserved — callers pass grads in reverse
    production order so the bucket holding the LAST-produced grads
    reduces first, overlapping with the backward still computing the
    earlier layers' grads (the DDP bucketing discipline)."""
    buckets, cur, cur_bytes = [], [], 0
    for name, nbytes in sized_names:
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += int(nbytes)
    if cur:
        buckets.append(cur)
    return buckets


def bucketed_allreduce(grads, bucket_bytes, axis_name="dp",
                       mean=True, order=None):
    """Ring-allreduce a dict of per-device gradient shards in buckets.

    Each bucket flattens and concatenates its members into one f32
    vector, runs ONE `ring_allreduce` over it, and splits the result
    back — one collective chain per bucket instead of one per tensor
    (tiny grads amortize) or one for everything (no overlap).  `order`
    (default: reversed dict order) fixes which grads reduce first.
    """
    # runs at TRACE time under jit: the obs.comm hook records the
    # bucket schedule + nested spans once per trace, and the compiled
    # program replays the schedule invisibly (runtime per-bucket truth
    # comes from obs.comm.measure_bucket_times)
    from ..obs import comm as obs_comm

    if not grads:
        return grads
    names = list(order) if order is not None \
        else list(reversed(list(grads)))
    sized = [(n, grads[n].size * grads[n].dtype.itemsize)
             for n in names]
    size_of = dict(sized)
    buckets = grad_buckets(sized, bucket_bytes)
    sched = obs_comm.record_schedule(
        "allreduce", axis_name,
        [{"bucket": i, "names": list(b),
          "bytes": int(sum(size_of[n] for n in b))}
         for i, b in enumerate(buckets)], mean=mean)
    out = dict(grads)
    with obs_comm.schedule_span(sched):
        for i, bucket in enumerate(buckets):
            with obs_comm.bucket_span(sched, i):
                parts = [grads[n].astype(jnp.float32).reshape(-1)
                         for n in bucket]
                flat = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)
                flat = ring_allreduce(flat, axis_name, mean=mean)
                off = 0
                for n in bucket:
                    size = grads[n].size
                    out[n] = flat[off:off + size].reshape(
                        grads[n].shape).astype(grads[n].dtype)
                    off += size
    return out


def sp_shard_map(fn, mesh, axis_name="sp", dp_axis="dp", mp_axis="mp"):
    """Wrap `fn(q,k,v,...)` in a shard_map over [B,H,T,D] tensors: T
    shards along `axis_name`, and batch/heads stay sharded along
    dp/mp when those axes exist — otherwise attention would all-gather
    the full batch and all heads onto every device."""
    batch = dp_axis if dp_axis in mesh.shape else None
    heads = mp_axis if mp_axis in mesh.shape else None
    spec = P(batch, heads, axis_name, None)
    return shard_map_norep(fn, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec)
