"""Pipeline parallelism: GPipe-style microbatch streaming over a "pp"
mesh axis.

The reference snapshot has no pipeline engine — its closest notion is
per-layer device placement in ParallelNeuralNetwork
(reference: paddle/gserver/gradientmachines/ParallelNeuralNetwork.h:25,
which round-robins layers across GPUs and synchronizes on layer
boundaries).  The TPU-first redesign is SPMD: every device runs the
SAME program under shard_map; stage parameters are stacked on a leading
axis sharded over "pp" (device i holds stage i), and microbatch
activations hop stage-to-stage around the ICI ring with `lax.ppermute`.
The whole schedule is a `lax.scan` over M + S - 1 ticks, so
`jax.grad` differentiates straight through it — the transpose of
ppermute is the reverse ring, which IS the backward pipeline; no
hand-written 1F1B schedule needed.

Composes with the other axes: batch ("dp") sharding applies to the
microbatch dimension, tensor ("mp") sharding inside stage_fn, sequence
("sp") via ring attention inside stage_fn.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs import trace as obs_trace
from .sharding import shard_map_norep

__all__ = ["gpipe_spmd", "pipeline_apply", "split_microbatches",
           "stack_stage_params", "pipeline_schedule_info"]


def pipeline_schedule_info(mesh, n_microbatches, axis_name="pp",
                           batch_size=None):
    """Static introspection of a GPipe schedule over `mesh` (or any
    axis->size mapping): stage count, tick count, bubble fraction —
    what the sharding analyzer's `check_pipeline` consumes."""
    shape = dict(getattr(mesh, "shape", mesh))
    s = int(shape.get(axis_name, 0))
    m = int(n_microbatches)
    info = {"axis": axis_name, "stages": s, "microbatches": m,
            "ticks": (m + s - 1) if s else None,
            "bubble_fraction": (float(s - 1) / (m + s - 1)
                                if s and (m + s - 1) else None)}
    if batch_size is not None:
        info["microbatch_size"] = (batch_size // m
                                   if m and batch_size % m == 0
                                   else None)
    return info


def split_microbatches(x, n_microbatches):
    """[B, ...] -> [M, B/M, ...] microbatch stream."""
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_microbatches))
    return x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])


def stack_stage_params(per_stage_params):
    """List of S identical-pytree stage params -> one pytree whose
    leaves have a leading stage axis [S, ...] (shard it over "pp")."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def gpipe_spmd(stage_fn, stacked_params, x_mb, axis_name="pp"):
    """The per-device pipeline schedule; call inside shard_map.

    stage_fn(params, x) -> y must preserve the activation shape
    (classic stacked-stage pipelining, e.g. transformer blocks).

    stacked_params: leaves [1, ...] locally (the "pp"-sharded stage
    axis); x_mb: [M, mb, ...] microbatches (replicated across pp).
    Returns [M, mb, ...] last-stage outputs, replicated across pp.
    """
    s = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    local = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    m = x_mb.shape[0]
    fwd = [(i, i + 1) for i in range(s - 1)]

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (clamped; invalid ticks are
        # masked out of `outs` below), later stages eat what the
        # predecessor ppermuted in last tick
        inj = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, m - 1), 0,
                                       keepdims=False)
        cur = jnp.where(idx == 0, inj, state)
        # named_scope threads the stage region through to HLO metadata,
        # so a device profile (jax.profiler / Perfetto) attributes time
        # to the pipeline stage instead of an anonymous fusion
        with jax.named_scope("pp_stage"):
            y = stage_fn(local, cur)
        # the last stage finishes microbatch t-(s-1) at tick t
        o_idx = jnp.clip(t - (s - 1), 0, m - 1)
        valid = jnp.logical_and(idx == s - 1, t >= s - 1)
        outs = jnp.where(valid,
                         lax.dynamic_update_index_in_dim(outs, y, o_idx, 0),
                         outs)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, outs), None

    state0 = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)
    (_, outs), _ = lax.scan(tick, (state0, outs0),
                            jnp.arange(m + s - 1))
    # only the last device holds real outputs; broadcast so the loss
    # (and dp-sharded label math) runs replicated across pp
    return lax.psum(jnp.where(idx == s - 1, outs, 0.0), axis_name)


def pipeline_apply(mesh, stage_fn, stacked_params, x, n_microbatches,
                   axis_name="pp", batch_axis="dp", remat=False):
    """Run `x` through the pipelined stack of stages over `mesh`.

    stacked_params: pytree with leading stage axis [S, ...]; S must
    equal mesh.shape[axis_name].  x: [B, ...] global batch; with a
    "dp" axis in the mesh the microbatch dimension is dp-sharded too.
    Returns [B, ...] outputs of the final stage.
    """
    from ..utils import flags as _flags

    if _flags.get_flag("verify_sharding"):
        from ..analysis import shard as _shard

        _shard.check_pipeline(
            mesh, n_stages=jax.tree_util.tree_leaves(
                stacked_params)[0].shape[0],
            n_microbatches=n_microbatches, axis_name=axis_name,
            batch_size=int(x.shape[0])).raise_on_error()
    s = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != s:
        raise ValueError("stacked_params has %d stages but mesh axis "
                         "%r has size %d" % (n_stages, axis_name, s))
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    db = batch_axis if batch_axis in mesh.shape else None

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name), stacked_params)
    x_spec = P(None, db)  # [M, mb, ...]: microbatch dim dp-sharded

    mapped = shard_map_norep(
        functools.partial(gpipe_spmd, fn, axis_name=axis_name),
        mesh=mesh, in_specs=(param_specs, x_spec), out_specs=x_spec)

    # host-side span over the whole pipelined dispatch; per-stage
    # attribution inside the scan comes from the pp_stage named_scope
    # (device timeline), since the schedule itself is one traced scan
    with obs_trace.span("parallel/pipeline_apply", cat="parallel",
                        stages=int(s), microbatches=int(n_microbatches)):
        x_mb = split_microbatches(x, n_microbatches)
        out_mb = mapped(stacked_params, x_mb)
        if obs_trace.is_enabled():
            jax.block_until_ready(out_mb)
    return out_mb.reshape((-1,) + out_mb.shape[2:])
