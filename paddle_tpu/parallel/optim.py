"""Registered update-op kernels applied to parameter pytrees.

The pipeline and MoE schedules hold their parameters as stacked pytrees
([stage, ...] / [expert, ...]) streamed by shard_map — there is no
Program block to append update ops to.  Rather than hand-rolling SGD
there (or duplicating optimizer math), `PytreeOptimizer` drives the
SAME declarative update rule a `fluid.optimizer` instance carries —
its op type, state slots, shared scalars, and hyperparameter attrs
(fluid/optimizer.py) — through the registered op kernel
(ops/optimizer_ops.py), leaf by leaf.  One rule, two surfaces: program
ops for executor-driven training, pytree application for schedule-
driven training.  Fully jittable; state lives alongside the params so
the schedules shard it the same way.
"""

import jax
import jax.numpy as jnp

from ..ops.registry import get_op_info

__all__ = ["PytreeOptimizer"]


class PytreeOptimizer:
    """Apply a fluid optimizer's update rule over a params pytree.

        opt = PytreeOptimizer(fluid.optimizer.Momentum(0.1, momentum=0.9))
        state = opt.init(params)
        params, state = opt.apply(params, grads, state)   # pure/jittable
    """

    def __init__(self, fluid_optimizer):
        self._rule = fluid_optimizer
        if fluid_optimizer.op_type is None:
            raise ValueError("optimizer declares no update op")
        self._kernel = get_op_info(fluid_optimizer.op_type).kernel
        lr = fluid_optimizer._learning_rate
        if not isinstance(lr, float):
            raise ValueError(
                "PytreeOptimizer needs a float learning rate (schedule "
                "variables live in programs)")
        self._lr = lr

    @property
    def slot_names(self):
        """Names of the per-parameter accumulator slots this rule
        carries (velocity/moment/...), for spec introspection."""
        return [spec.name for spec in self._rule.state_slots]

    def state_specs(self, param_specs):
        """PartitionSpecs for the state pytree `init` builds: each
        accumulator slot shards exactly like the parameter it tracks
        (the schedules stream state alongside params), shared scalars
        replicate.  `param_specs` is the params-pytree of specs."""
        import jax

        return {
            "slots": {name: jax.tree_util.tree_map(lambda s: s,
                                                   param_specs)
                      for name in self.slot_names},
            "shared": {spec.name: None
                       for spec in self._rule.shared_scalars},
        }

    def init(self, params):
        """State pytree: one zeros-like per (state slot, param leaf),
        plus the shared scalars at their initial values."""
        slots = {
            spec.name: jax.tree_util.tree_map(
                lambda p: jnp.full(p.shape, spec.fill, p.dtype), params)
            for spec in self._rule.state_slots
        }
        shared = {spec.name: jnp.asarray(spec.init, jnp.float32)
                  for spec in self._rule.shared_scalars}
        return {"slots": slots, "shared": shared}

    def apply(self, params, grads, state):
        """Returns (new_params, new_state)."""
        rule = self._rule
        attrs = rule._hyper_attrs()
        lr = jnp.asarray(self._lr, jnp.float32)

        leaves_p, treedef = jax.tree_util.tree_flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        slot_leaves = {
            spec.name: treedef.flatten_up_to(state["slots"][spec.name])
            for spec in rule.state_slots
        }

        new_p, new_slots = [], {spec.name: [] for spec in rule.state_slots}
        for i, (p, g) in enumerate(zip(leaves_p, leaves_g)):
            ins = {"Param": [p], "Grad": [g]}
            if rule.uses_lr:
                ins["LearningRate"] = [lr]
            for spec in rule.state_slots:
                ins[spec.in_key] = [slot_leaves[spec.name][i]]
            for spec in rule.shared_scalars:
                ins[spec.in_key] = [state["shared"][spec.name]]
            outs = self._kernel(None, ins, attrs)
            new_p.append(outs["ParamOut"][0])
            for spec in rule.state_slots:
                new_slots[spec.name].append(outs[spec.out_key][0])

        new_state = {
            "slots": {name: jax.tree_util.tree_unflatten(treedef, leaves)
                      for name, leaves in new_slots.items()},
            "shared": {spec.name:
                       state["shared"][spec.name] * spec.step_factor
                       if spec.step_factor is not None
                       else state["shared"][spec.name]
                       for spec in rule.shared_scalars},
        }
        return jax.tree_util.tree_unflatten(treedef, new_p), new_state
