"""Mesh-parallel training driver.

The reference's single-process multi-device trainer splits the batch,
runs per-device threads, and ring-reduces gradients
(reference: MultiGradientMachine.h:44-83, parallel_do_op.cc:112).  Here
the whole train step (forward + backward + optimizer, one Program block)
is ONE jitted function laid out over the mesh: batch sharded on dp,
weights sharded on mp, gradients all-reduced by XLA over ICI.
"""

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..jit import FunctionalProgram, state_from_scope
from ..obs import flight as obs_flight
from ..obs import health as obs_health
from ..obs import telemetry as obs_tele
from ..utils import flags as _flags
from .sharding import (param_spec, batch_spec, is_optimizer_state,
                       optimizer_state_names, zero1_spec)

__all__ = ["make_parallel_step", "ParallelTrainer", "verify_sharding"]


def verify_sharding(program, mesh, feed_names, fetch_names,
                    feed_specs=None, zero_stage=0, dp_axis="dp",
                    mp_axis="mp", origin="parallel_trainer",
                    hbm_gb=None):
    """Run the static SPMD analyzer over `program` against `mesh` and
    raise ProgramVerificationError on any error-severity S0xx finding
    (non-divisible shard, schedule hazard, budget overrun) — BEFORE
    anything lowers or compiles.  The trust-boundary gate behind
    FLAGS_verify_sharding; callers can also invoke it directly.
    Returns the ShardingPlan for introspection."""
    from ..analysis import shard as shard_analysis

    plan = shard_analysis.analyze_sharding(
        program, mesh, feed_names=list(feed_names),
        feed_specs=feed_specs, fetches=list(fetch_names),
        zero_stage=zero_stage, dp_axis=dp_axis, mp_axis=mp_axis,
        hbm_gb=hbm_gb, publish=True, origin=origin,
        # trainer feeds carry their real runtime shapes: a
        # non-divisible static batch is a hard S002 here
        concrete_feeds=True)
    plan.report.raise_on_error()
    return plan


def make_parallel_step(program, feed_names, fetch_names, mesh,
                       state_template, dp_axis="dp", mp_axis="mp",
                       donate_state=None, fp=None, zero_stage=0,
                       feed_specs=None, spec_overrides=None):
    """Compile a Program block into a sharded step function.

    donate_state: None (default) routes through the donation plan —
    FLAGS_donation=off disables state donation, any other mode keeps
    it (analysis.state_donation); pass an explicit bool to override
    (the AOT "-nodonate" twin and obs.comm's compute-only twin do).

    Returns (step, state_shardings) where
      step(state, feeds, rng) -> (fetches, new_state)
    is jitted with: state sharded per param_spec, feeds sharded on dp,
    fetches replicated (losses/metrics are scalars after mean).

    zero_stage=1 additionally shards the optimizer accumulators
    (velocity/moment/... vars) over dp — ZeRO-1: GSPMD turns the
    gradient all-reduce into reduce-scatter + all-gather and each chip
    keeps 1/dp of the optimizer state.

    feed_specs overrides the default dp batch sharding per feed name
    (e.g. {"tokens": P("dp", "sp")} lays the sequence dim over the sp
    axis for sequence-parallel programs).

    spec_overrides overrides the heuristic `param_spec` per STATE var
    name — the spmd partition-plan hook (spmd/plan.py): a plan entry
    carries the final layout (zero1 already applied by the analyzer),
    so an overridden name bypasses both the heuristic and the zero1
    rewrite here.

    With FLAGS_verify_sharding on, the static SPMD analyzer runs over
    the program/mesh pair first (unless the caller already did —
    ParallelTrainer.init verifies before running startup) and rejects
    S0xx errors before any lowering.
    """
    if donate_state is None:
        from ..analysis.alias import state_donation

        donate_state = state_donation()
    if fp is None:
        if program is not None and _flags.get_flag("verify_sharding"):
            verify_sharding(program, mesh, feed_names, fetch_names,
                            feed_specs=feed_specs,
                            zero_stage=zero_stage, dp_axis=dp_axis,
                            mp_axis=mp_axis, origin="parallel_step")
        fp = FunctionalProgram(program, feed_names, fetch_names)

    # exact accumulator names from the program's optimizer ops (the
    # name-suffix regex stays only for detached state dicts)
    acc_names = optimizer_state_names(program) if program is not None \
        else None

    spec_overrides = spec_overrides or {}

    def spec_for(name, shape):
        if name in spec_overrides:
            return spec_overrides[name]
        spec = param_spec(name, shape, mesh, mp_axis=mp_axis)
        if zero_stage >= 1 and is_optimizer_state(name, known=acc_names):
            spec = zero1_spec(spec, shape, mesh, dp_axis=dp_axis)
        return spec

    state_shardings = {
        name: NamedSharding(mesh, spec_for(name, v.shape))
        for name, v in state_template.items()
    }

    feed_specs = feed_specs or {}

    def step(state, feeds, rng):
        feeds = {
            n: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, feed_specs.get(
                    n, batch_spec(v.shape, mesh, dp_axis))))
            if hasattr(v, "shape") else v
            for n, v in feeds.items()
        }
        fetches, new_state = fp(state, feeds, rng)
        return fetches, new_state

    jitted = jax.jit(
        step,
        in_shardings=(state_shardings, None, None),
        out_shardings=(None, state_shardings),
        donate_argnums=(0,) if donate_state else (),
    )
    return jitted, state_shardings


class ParallelTrainer:
    """End-to-end sharded trainer for a built Program.

    Usage:
        trainer = ParallelTrainer(main_prog, startup_prog,
                                  feed_names=["image", "label"],
                                  fetch_names=[loss.name], mesh=mesh)
        trainer.init()                       # run startup, shard params
        (loss,) = trainer.step({"image": x, "label": y})
    """

    def __init__(self, main_program, startup_program, feed_names,
                 fetch_names, mesh, dp_axis="dp", mp_axis="mp", seed=0,
                 zero_stage=0, feed_specs=None):
        self.main_program = main_program
        self.startup_program = startup_program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.mp_axis = mp_axis
        self.zero_stage = zero_stage
        self.feed_specs = feed_specs
        self._base_rng = jax.random.PRNGKey(seed)
        self._step_count = 0
        self._step_fn = None
        self.state = None

    def init(self, scope=None, executor=None):
        """Run the startup program (single device), then lay the state out
        over the mesh per the sharding specs.

        With FLAGS_verify_sharding on, the static SPMD analyzer runs
        FIRST — before the startup program executes, before any jit
        trace — so a non-divisible shard or schedule hazard rejects
        with op/var/spec identity instead of burning an XLA compile."""
        from ..fluid.executor import Executor, CPUPlace
        from ..core.scope import Scope

        self._verify()

        scope = scope or Scope()
        exe = executor or Executor(CPUPlace())
        exe.run(self.startup_program, scope=scope)

        # numerics health: when enabled, the monitor's on-device
        # reductions (nonfinite counts over fetches + grads, global
        # grad norm) join the jitted step as extra replicated fetches —
        # XLA folds the cross-chip reduce into the step executable
        fetch_all = list(self.fetch_names)
        self._monitor = None
        if obs_health.enabled():
            self._monitor = obs_health.NumericsMonitor(
                self.main_program,
                tensors=list(self.fetch_names)).install()
            fetch_all += self._monitor.fetch_names

        fp = FunctionalProgram(self.main_program, self.feed_names,
                               fetch_all)
        state = state_from_scope(fp, scope)
        self._step_fn, self._shardings = self._make_step(fp, state,
                                                         fetch_all)
        # place state on the mesh
        self.state = {
            n: jax.device_put(np.asarray(v), self._shardings[n])
            for n, v in state.items()
        }
        return self

    def _verify(self):
        """The pre-startup trust-boundary gate; `SpmdTrainer` replaces
        it with the partition-plan build (which raises on the same
        S0xx errors, rules included)."""
        if _flags.get_flag("verify_sharding"):
            verify_sharding(self.main_program, self.mesh,
                            self.feed_names, self.fetch_names,
                            feed_specs=self.feed_specs,
                            zero_stage=self.zero_stage,
                            dp_axis=self.dp_axis, mp_axis=self.mp_axis,
                            origin="parallel_trainer")

    def _make_step(self, fp, state, fetch_all):
        """Build (step_fn, state_shardings) — the lowering hook
        subclasses override (SpmdTrainer routes plan specs and the
        overlapped-dp schedule through here)."""
        return make_parallel_step(
            self.main_program, self.feed_names, fetch_all,
            self.mesh, state, dp_axis=self.dp_axis, mp_axis=self.mp_axis,
            fp=fp, zero_stage=self.zero_stage, feed_specs=self.feed_specs)

    def step(self, feeds):
        rng = jax.random.fold_in(self._base_rng, self._step_count)
        step_id = self._step_count
        self._step_count += 1
        feeds = {n: jnp_asarray(v) for n, v in feeds.items()}
        examples = next((int(v.shape[0]) for v in feeds.values()
                         if getattr(v, "ndim", 0)), None)
        # step telemetry into the unified registry + a parallel/step
        # span; block on the fetches so trainer_step_seconds is device
        # time, never just the async dispatch (~µs).  Fetches are the
        # replicated loss/metric scalars every caller reads right
        # after, and new_state materializes in the same executable, so
        # this costs the host-side feed-prep overlap only.
        try:
            with obs_tele.step("parallel", examples=examples,
                               step=step_id):
                # trace under the mesh context so mesh-aware op kernels
                # (ring flash_attention) see the sp topology
                with self.mesh:
                    fetches, self.state = self._step_fn(self.state,
                                                        feeds, rng)
                jax.block_until_ready(fetches)
        except Exception as exc:
            obs_flight.on_crash(exc, origin="parallel/step",
                                step=step_id,
                                feeds=obs_flight.describe_feeds(feeds))
            raise
        monitor = getattr(self, "_monitor", None)
        if monitor is not None:
            n_user = len(self.fetch_names)
            monitor.record(dict(zip(monitor.fetch_names,
                                    fetches[n_user:])))
            fetches = fetches[:n_user]
        if obs_flight.active():
            loss = None
            first = fetches[0] if fetches else None
            if first is not None and getattr(first, "size", 0) == 1:
                loss = float(np.asarray(first).reshape(-1)[0])
            obs_flight.record_step("parallel", step_id, feeds=feeds,
                                   loss=loss)
        return fetches

    def fetch_state(self, name):
        return np.asarray(self.state[name])

    def sharding_plan(self, hbm_gb=None):
        """Introspection: the static SPMD analysis of this trainer's
        program/mesh pair (specs, replication reasons, comm cost,
        per-device peak-HBM estimate) WITHOUT raising — see
        docs/ANALYSIS.md 'lint before you burn a pod slice'."""
        from ..analysis import shard as shard_analysis

        return shard_analysis.analyze_sharding(
            self.main_program, self.mesh, feed_names=self.feed_names,
            feed_specs=self.feed_specs, fetches=self.fetch_names,
            zero_stage=self.zero_stage, dp_axis=self.dp_axis,
            mp_axis=self.mp_axis, hbm_gb=hbm_gb, publish=False)

    # -- supervisor integration ---------------------------------------------
    def dump_state_to(self, scope):
        """Host copies of the sharded state into `scope` (called by
        the resilience supervisor right before a checkpoint save)."""
        for name, val in self.state.items():
            scope.set(name, np.asarray(val))

    def load_state_from(self, scope):
        """Re-place checkpointed host values onto the mesh with the
        step function's shardings (after a supervisor restore)."""
        restored = {}
        for name in self.state:
            val = scope.get(name)
            if val is None:
                raise KeyError("checkpoint is missing state var %r"
                               % name)
            restored[name] = jax.device_put(np.asarray(val),
                                            self._shardings[name])
        self.state = restored


def jnp_asarray(v):
    import jax.numpy as jnp

    if isinstance(v, jax.Array):
        return v
    return jnp.asarray(np.asarray(v))
