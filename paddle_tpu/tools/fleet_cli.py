"""Fleet metric aggregation CLI ("pfleet") — thin entry point over
`paddle_tpu.obs.fleet` (the module itself is imported by the obs
package, so `-m` must target this wrapper to avoid the runpy
double-import).

    # worker: publish this process's registry snapshot
    python -m paddle_tpu.tools.fleet_cli --push --master 127.0.0.1:7164

    # operator: the merged host-labeled view + straggler report
    python -m paddle_tpu.tools.fleet_cli --aggregate \
        --master 127.0.0.1:7164

See docs/OBSERVABILITY.md "Fleet aggregation & stragglers".
"""

import sys

from ..obs.fleet import main

if __name__ == "__main__":
    sys.exit(main())
