"""proglint: the static-analysis CLI over Program IR.

    # lint a save_inference_model export (the __model__ JSON):
    python -m paddle_tpu.tools.lint_cli path/to/model_dir

    # lint the checked-in golden program fixtures (the pre-push hook):
    python -m paddle_tpu.tools.lint_cli --golden

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.lint_cli --selftest

Exit status: 0 when no error-severity finding survives suppression,
1 otherwise (`--strict` also fails on warnings).  `--json` emits the
structured report instead of text.  Codes, severities and the
suppression syntax are documented in docs/ANALYSIS.md.

`--selftest` builds a REAL training program, asserts it verifies with
zero error-severity diagnostics, then seeds seven deliberate
corruptions — unknown op, use-before-def, dtype mismatch, dangling
BlockRef, write-write race, in-place alias read hazard, dead op — and
asserts each is reported under its stable diagnostic code.  It also
drives the executor's FLAGS_verify_program gate end to end: the
corrupted program must fail BEFORE any XLA compile with an error
naming the op index and variable.
"""

import argparse
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="proglint")
    p.add_argument("model_dir", nargs="?", default=None,
                   help="a save_inference_model directory to lint")
    p.add_argument("--model-filename", default="__model__")
    p.add_argument("--golden", nargs="?", const="", default=None,
                   metavar="DIR",
                   help="lint golden ProgramDesc fixtures (default "
                        "dir: tests/fixtures/golden)")
    p.add_argument("--level", choices=("structural", "full"),
                   default="full",
                   help="structural: desc walking only; full: also "
                        "re-derive output metas via the registry")
    p.add_argument("--fetch", default=None,
                   help="comma-separated runtime fetch names (enables "
                        "dead-op detection)")
    p.add_argument("--suppress", default=None,
                   help="comma-separated suppressions, e.g. "
                        "H002,L003@dropout,D002@var:tmp_0")
    p.add_argument("--strict", action="store_true",
                   help="fail (exit 1) on warnings too")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="don't print info-severity findings (they "
                        "still count in the summary)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    p.add_argument("--selftest", action="store_true")
    return p.parse_args(argv)


def _split(csv):
    return [s for s in (csv or "").split(",") if s]


def _report_exit(name, report, args):
    if args.json:
        doc = report.to_dict()
        doc["target"] = name
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        shown = report.sorted()
        if args.quiet:
            shown = [d for d in shown if d.severity != "info"]
        for d in shown:
            print(d.format())
        print("[lint] %s: %d error(s), %d warning(s), %d info, "
              "%d suppressed"
              % (name, len(report.errors), len(report.warnings),
                 len(report.by_severity("info")),
                 len(report.suppressed)))
    failed = bool(report.errors) or (args.strict
                                     and bool(report.warnings))
    return 1 if failed else 0


def lint_model_dir(args):
    from paddle_tpu import analysis
    from paddle_tpu.core.desc import ProgramDesc

    path = os.path.join(args.model_dir, args.model_filename)
    with open(path) as f:
        meta = json.load(f)
    desc = ProgramDesc.from_dict(meta["program"])
    fetches = _split(args.fetch) or meta.get("fetch_names")
    report = analysis.check_program(
        desc, level=args.level, fetches=fetches,
        bucket_hints=meta.get("bucket_hints"),
        suppress=_split(args.suppress), origin="lint_cli")
    return _report_exit(args.model_dir, report, args)


def lint_golden(args):
    """Lint every checked-in golden ProgramDesc fixture (the pre-push
    hook's gate: a red fixture means the pinned IR itself is broken,
    not just changed)."""
    from paddle_tpu import analysis
    from paddle_tpu.core.desc import ProgramDesc

    golden_dir = args.golden or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tests", "fixtures", "golden")
    results = []  # (fixture name, report)
    for fname in sorted(os.listdir(golden_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(golden_dir, fname)) as f:
            doc = json.load(f)
        if "blocks" in doc:
            descs = [(fname, doc)]
        elif "trainer" in doc:  # transpiled_pair: trainer program + table
            descs = [(fname + ":trainer", doc["trainer"])]
        else:
            continue
        for name, d in descs:
            results.append((name, analysis.check_program(
                ProgramDesc.from_dict(d), level=args.level,
                suppress=_split(args.suppress), origin="lint_golden")))
    if not results:
        print("[lint] no golden ProgramDesc fixtures under %s"
              % golden_dir)
        return 1
    if args.json:
        # ONE parseable document for the whole fixture set, not one
        # json.dumps per fixture
        docs = []
        rc = 0
        for name, report in results:
            d = report.to_dict()
            d["target"] = name
            docs.append(d)
            if report.errors or (args.strict and report.warnings):
                rc = 1
        print(json.dumps(docs, indent=1, sort_keys=True))
        return rc
    rc = 0
    for name, report in results:
        rc |= _report_exit(name, report, args)
    return rc


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _build_train_program():
    """A fresh fit-a-line-style training program (fc -> mse -> SGD) in
    its own Program pair; returns (main, startup, loss_name,
    param_name)."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    param = [v.name for v in main.global_block().vars.values()
             if getattr(v.desc, "is_parameter", False)][0]
    return main, startup, loss.name, param


def _corruptions(main, loss_name, param_name):
    """[(corruption label, expected code, mutator(program))] — each
    mutator receives a FRESH clone of the clean program."""
    from paddle_tpu.core.desc import BlockRef, OpDesc, VarDesc

    def unknown_op(p):
        p.desc.block(0).ops[1].type = "definitely_not_an_op"

    def use_before_def(p):
        ops = p.desc.block(0).ops
        # hoist the loss-producing op above its producers
        idx = next(i for i, od in enumerate(ops)
                   if loss_name in od.output_names())
        ops.insert(0, ops.pop(idx))

    def dtype_mismatch(p):
        bd = p.desc.block(0)
        # the fc matmul output: recorded int32 vs re-derived float32
        out = next(od.output_names()[0] for od in bd.ops
                   if od.type == "mul")
        bd.vars[out].dtype = "int32"

    def dangling_block_ref(p):
        p.desc.block(0).ops[0].attrs["sub_block"] = BlockRef(7)

    def write_write(p):
        bd = p.desc.block(0)
        i = next(i for i, od in enumerate(bd.ops) if od.type == "mul")
        od = bd.ops[i]
        bd.ops.insert(i + 1, OpDesc(od.type, dict(od.inputs),
                                    dict(od.outputs), dict(od.attrs)))

    def alias_race(p):
        bd = p.desc.block(0)
        bd.vars["__shadow__"] = VarDesc("__shadow__", dtype="float32",
                                        shape=(13, 1))
        # an unordered reader of the in-place-updated parameter
        bd.ops.insert(0, OpDesc("scale", {"X": [param_name]},
                                {"Out": ["__shadow__"]}, {"scale": 2.0}))

    def dead_op(p):
        bd = p.desc.block(0)
        bd.vars["__unused__"] = VarDesc("__unused__", dtype="float32",
                                        shape=(1,))
        bd.ops.append(OpDesc("scale", {"X": [loss_name]},
                             {"Out": ["__unused__"]}, {"scale": 1.0}))

    return [
        ("unknown op", "V001", unknown_op),
        ("use-before-def", "V003", use_before_def),
        ("dtype mismatch", "V005", dtype_mismatch),
        ("dangling BlockRef", "V004", dangling_block_ref),
        ("write-write race", "H001", write_write),
        ("in-place alias read hazard", "H002", alias_race),
        ("dead op", "D001", dead_op),
    ]


def selftest(args):
    # never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import analysis
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.utils import flags

    main, startup, loss_name, param_name = _build_train_program()

    # 1. the clean program: zero error-severity diagnostics
    clean = analysis.check_program(main, level="full",
                                   fetches=[loss_name],
                                   origin="lint_selftest")
    assert clean.ok(), \
        "clean program reported errors:\n%s" % clean.format()

    # 2. every seeded corruption reports its stable code
    for label, code, mutate in _corruptions(main, loss_name,
                                            param_name):
        prog = main.clone()
        mutate(prog)
        report = analysis.check_program(prog, level="full",
                                        fetches=[loss_name],
                                        publish=False)
        assert report.has(code), \
            "%s: expected %s, got codes %s\n%s" \
            % (label, code, report.codes(), report.format())

    # 3. suppression: the same corruption vanishes when suppressed
    prog = main.clone()
    _corruptions(main, loss_name, param_name)[0][2](prog)
    sup = analysis.check_program(prog, level="full", suppress=("V001",),
                                 publish=False)
    assert not sup.has("V001") and sup.suppressed, "suppression broken"

    # 4. the executor gate: corruption fails BEFORE any XLA compile,
    #    naming op index + var
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        prev = flags.get_flag("verify_program")
        flags.set_flag("verify_program", True)
        try:
            feed = {"x": np.zeros((2, 13), np.float32),
                    "y": np.zeros((2, 1), np.float32)}
            out, = exe.run(main, feed=feed, fetch_list=[loss_name])
            assert np.isfinite(out).all()
            bad = main.clone()
            bad.desc.block(0).ops[2].type = "definitely_not_an_op"
            try:
                exe.run(bad, feed=feed, fetch_list=[loss_name])
                raise AssertionError(
                    "corrupted program ran under FLAGS_verify_program")
            except analysis.ProgramVerificationError as err:
                first = err.report.errors[0]
                assert first.op_index is not None, first
                assert "op 2" in str(err), err
        finally:
            flags.set_flag("verify_program", prev)

    # 5. finding counters landed in the obs registry
    snap = {s["name"]: s for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "analysis_diagnostics_total" in snap or any(
        k.startswith("analysis_") for k in snap), \
        "no analysis_* metrics in the registry"

    print("[lint] selftest green: clean program verified (0 errors), "
          "%d seeded corruptions each reported their code, "
          "suppression filters, executor FLAGS_verify_program gate "
          "rejects pre-compile with op identity, finding counters in "
          "the registry" % len(_corruptions(main, loss_name,
                                            param_name)), flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.golden is not None:
        return lint_golden(args)
    if args.model_dir:
        return lint_model_dir(args)
    raise SystemExit("nothing to do: pass a model dir, --golden, or "
                     "--selftest")


if __name__ == "__main__":
    sys.exit(main())
