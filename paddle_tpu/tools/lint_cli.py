"""proglint: the static-analysis CLI over Program IR.

    # lint a save_inference_model export (the __model__ JSON):
    python -m paddle_tpu.tools.lint_cli path/to/model_dir

    # additionally run the static SPMD/sharding analyzer against a
    # mesh description (no devices needed; docs/ANALYSIS.md S0xx):
    python -m paddle_tpu.tools.lint_cli path/to/model_dir \
        --mesh dp=4,mp=2 --hbm-gb 16

    # additionally run the A0xx donation-safety analysis
    # (analysis/alias.py): which buffers each jit segment can donate,
    # and why the rest are refused:
    python -m paddle_tpu.tools.lint_cli path/to/model_dir --donation

    # lint the checked-in golden program fixtures (the pre-push hook
    # passes --mesh dp=4,mp=2 --donation so the pinned IR must also
    # shard AND donation-plan clean):
    python -m paddle_tpu.tools.lint_cli --golden

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.lint_cli --selftest --mesh dp=4,mp=2

Exit status: 0 when no error-severity finding survives suppression,
1 otherwise (`--strict` also fails on warnings).  `--json` emits the
structured report instead of text.  Codes, severities and the
suppression syntax are documented in docs/ANALYSIS.md.

`--selftest` builds a REAL training program, asserts it verifies with
zero error-severity diagnostics, then seeds seven deliberate
corruptions — unknown op, use-before-def, dtype mismatch, dangling
BlockRef, write-write race, in-place alias read hazard, dead op — and
asserts each is reported under its stable diagnostic code.  It also
drives the executor's FLAGS_verify_program gate end to end: the
corrupted program must fail BEFORE any XLA compile with an error
naming the op index and variable.  The sharding leg then analyzes a
clean lenet5 training program AND every golden fixture over the four
dryrun mesh shapes (dp/mp, dp/mp/sp, pp/dp, dp/ep) asserting zero
errors, and seeds one corruption per S0xx code (unmatched rule,
non-divisible batch, conflicting layouts, schedule mismatch, HBM
budget) asserting each exact code.  The donation leg does the same
for the A0xx family: lenet5 + golden fixtures plan clean, then one
seeded corruption per code — forked Adam slot (A001), plan replayed
over a program with a late reader (A002), fetched donatable
intermediate (A003), in-place update in a non-jit segment (A004),
donation-unsafe backend (A005) — each asserting its exact code.
"""

import argparse
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="proglint")
    p.add_argument("model_dir", nargs="?", default=None,
                   help="a save_inference_model directory to lint")
    p.add_argument("--model-filename", default="__model__")
    p.add_argument("--golden", nargs="?", const="", default=None,
                   metavar="DIR",
                   help="lint golden ProgramDesc fixtures (default "
                        "dir: tests/fixtures/golden)")
    p.add_argument("--level", choices=("structural", "full"),
                   default="full",
                   help="structural: desc walking only; full: also "
                        "re-derive output metas via the registry")
    p.add_argument("--fetch", default=None,
                   help="comma-separated runtime fetch names (enables "
                        "dead-op detection)")
    p.add_argument("--mesh", default=None, metavar="SPEC",
                   help="axis=size mesh description, e.g. dp=4,mp=2 — "
                        "also run the static SPMD/sharding analyzer "
                        "(S0xx codes) against it; no devices needed")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM budget in GiB for the S005 "
                        "peak-memory check (needs --mesh)")
    p.add_argument("--zero", type=int, default=0, metavar="STAGE",
                   help="ZeRO stage for the sharding analysis "
                        "(1 = dp-shard optimizer state)")
    p.add_argument("--passes", default=None, metavar="SPEC",
                   help="optimize each target through this rewrite "
                        "pipeline (compile/passes.py spec, e.g. "
                        "default+layout+fuse+auto_remat) BEFORE "
                        "linting — proves a pass can never emit a "
                        "program the linter would reject")
    p.add_argument("--donation", action="store_true",
                   help="also run the A0xx donation-safety analysis "
                        "(analysis/alias.py): per jit segment, which "
                        "buffers are provably donatable and why the "
                        "rest are refused; no devices needed")
    p.add_argument("--suppress", default=None,
                   help="comma-separated suppressions, e.g. "
                        "H002,L003@dropout,D002@var:tmp_0")
    p.add_argument("--strict", action="store_true",
                   help="fail (exit 1) on warnings too")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="don't print info-severity findings (they "
                        "still count in the summary)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    p.add_argument("--selftest", action="store_true")
    return p.parse_args(argv)


def _split(csv):
    return [s for s in (csv or "").split(",") if s]


def _shard_analyze(desc, args, report, fetches=None):
    """Run the SPMD analyzer against --mesh, merging S0xx findings
    into `report`; returns the ShardingPlan (None without --mesh)."""
    if not args.mesh:
        return None
    from paddle_tpu import analysis
    from paddle_tpu.parallel.mesh import parse_mesh_spec

    before = len(report.diagnostics)
    plan = analysis.analyze_sharding(
        desc, parse_mesh_spec(args.mesh), fetches=fetches,
        zero_stage=args.zero, hbm_gb=args.hbm_gb, report=report,
        publish=False)
    # `report` was already published by check_program: count ONLY the
    # findings this analysis added (re-publishing the merged report
    # would double-count every V/D/H/L finding), plus the comm/HBM
    # side the plan carries
    analysis.Report(report.diagnostics[before:]).publish(
        origin="lint_cli_mesh")
    plan.publish(diagnostics=False)
    return plan


def _donation_analyze(desc, args, report, fetches=None):
    """Run the donation-safety analysis under --donation, merging A0xx
    findings into `report`; returns the DonationPlan (None without
    --donation)."""
    if not args.donation:
        return None
    from paddle_tpu import analysis

    before = len(report.diagnostics)
    plan = analysis.analyze_donation(desc, fetches=fetches or (),
                                     report=report, publish=False)
    # same contract as _shard_analyze: count only the findings this
    # analysis added, never re-publish the merged report
    analysis.Report(report.diagnostics[before:]).publish(
        origin="lint_cli_donation")
    return plan


def _report_exit(name, report, args, plan=None, donation=None):
    if args.json:
        doc = report.to_dict()
        doc["target"] = name
        if plan is not None:
            doc["sharding"] = plan.to_dict()
        if donation is not None:
            doc["donation"] = donation.to_dict()
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        shown = report.sorted()
        if args.quiet:
            shown = [d for d in shown if d.severity != "info"]
        for d in shown:
            print(d.format())
        if plan is not None:
            comm = plan.comm.totals()
            print("[lint] %s: mesh=%s comm=%s peak_hbm=%.3fGiB"
                  % (name, dict(plan.mesh_axes),
                     {k: int(v) for k, v in comm.items()} or "none",
                     (plan.peak_hbm_bytes or 0) / 2**30))
        if donation is not None:
            donate = sum(len(donation.donate(i))
                         for i in range(len(donation.segments)))
            refused = sum(1 for e in donation.entries
                          if e["status"] == "reclaimable") \
                + sum(len(s["declined"]) for s in donation.segments)
            print("[lint] %s: donation mode=%s(effective %s) "
                  "donates %d buffer(s)/step, %d refused, plan %s"
                  % (name, donation.mode, donation.effective_mode,
                     donate, refused, donation.fingerprint()))
        print("[lint] %s: %d error(s), %d warning(s), %d info, "
              "%d suppressed"
              % (name, len(report.errors), len(report.warnings),
                 len(report.by_severity("info")),
                 len(report.suppressed)))
    failed = bool(report.errors) or (args.strict
                                     and bool(report.warnings))
    return 1 if failed else 0


def lint_model_dir(args):
    from paddle_tpu import analysis
    from paddle_tpu.core.desc import ProgramDesc

    path = os.path.join(args.model_dir, args.model_filename)
    with open(path) as f:
        meta = json.load(f)
    desc = ProgramDesc.from_dict(meta["program"])
    fetches = _split(args.fetch) or meta.get("fetch_names")
    report = analysis.check_program(
        desc, level=args.level, fetches=fetches,
        bucket_hints=meta.get("bucket_hints"),
        suppress=_split(args.suppress), origin="lint_cli")
    plan = _shard_analyze(desc, args, report, fetches=fetches)
    dplan = _donation_analyze(desc, args, report, fetches=fetches)
    return _report_exit(args.model_dir, report, args, plan=plan,
                        donation=dplan)


def lint_golden(args):
    """Lint every checked-in golden ProgramDesc fixture (the pre-push
    hook's gate: a red fixture means the pinned IR itself is broken,
    not just changed).  With --mesh the pinned IR must also shard
    clean against that mesh description."""
    from paddle_tpu import analysis

    results = []  # (name, report, sharding plan, donation plan)
    for name, desc in _golden_descs(args.golden):
        if args.passes:
            # lint the POST-PASS program: the optimized IR is what
            # compiles, so it must satisfy the same linter contract as
            # the pinned fixture (no fetch set here — passes needing
            # one, dce/fuse, decline by contract)
            from paddle_tpu.compile.passes import optimize_program

            desc, _pm = optimize_program(desc, args.passes,
                                         fetches=_split(args.fetch))
            name = "%s [%s]" % (name, _pm.pipeline_id)
        report = analysis.check_program(
            desc, level=args.level, suppress=_split(args.suppress),
            origin="lint_golden")
        plan = _shard_analyze(desc, args, report)
        dplan = _donation_analyze(desc, args, report)
        results.append((name, report, plan, dplan))
    if not results:
        print("[lint] no golden ProgramDesc fixtures found")
        return 1
    if args.json:
        # ONE parseable document for the whole fixture set, not one
        # json.dumps per fixture
        docs = []
        rc = 0
        for name, report, plan, dplan in results:
            d = report.to_dict()
            d["target"] = name
            if plan is not None:
                d["sharding"] = plan.to_dict()
            if dplan is not None:
                d["donation"] = dplan.to_dict()
            docs.append(d)
            if report.errors or (args.strict and report.warnings):
                rc = 1
        print(json.dumps(docs, indent=1, sort_keys=True))
        return rc
    rc = 0
    for name, report, plan, dplan in results:
        rc |= _report_exit(name, report, args, plan=plan,
                           donation=dplan)
    return rc


def _golden_descs(golden_dir=None):
    """[(name, ProgramDesc)] for every checked-in golden fixture."""
    from paddle_tpu.core.desc import ProgramDesc

    golden_dir = golden_dir or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tests", "fixtures", "golden")
    out = []
    for fname in sorted(os.listdir(golden_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(golden_dir, fname)) as f:
            doc = json.load(f)
        if "blocks" in doc:
            out.append((fname, ProgramDesc.from_dict(doc)))
        elif "trainer" in doc:  # transpiled_pair: trainer program + table
            out.append((fname + ":trainer",
                        ProgramDesc.from_dict(doc["trainer"])))
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _build_train_program():
    """A fresh fit-a-line-style training program (fc -> mse -> SGD) in
    its own Program pair; returns (main, startup, loss_name,
    param_name)."""
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    param = [v.name for v in main.global_block().vars.values()
             if getattr(v.desc, "is_parameter", False)][0]
    return main, startup, loss.name, param


def _corruptions(main, loss_name, param_name):
    """[(corruption label, expected code, mutator(program))] — each
    mutator receives a FRESH clone of the clean program."""
    from paddle_tpu.core.desc import BlockRef, OpDesc, VarDesc

    def unknown_op(p):
        p.desc.block(0).ops[1].type = "definitely_not_an_op"

    def use_before_def(p):
        ops = p.desc.block(0).ops
        # hoist the loss-producing op above its producers
        idx = next(i for i, od in enumerate(ops)
                   if loss_name in od.output_names())
        ops.insert(0, ops.pop(idx))

    def dtype_mismatch(p):
        bd = p.desc.block(0)
        # the fc matmul output: recorded int32 vs re-derived float32
        out = next(od.output_names()[0] for od in bd.ops
                   if od.type == "mul")
        bd.vars[out].dtype = "int32"

    def dangling_block_ref(p):
        p.desc.block(0).ops[0].attrs["sub_block"] = BlockRef(7)

    def write_write(p):
        bd = p.desc.block(0)
        i = next(i for i, od in enumerate(bd.ops) if od.type == "mul")
        od = bd.ops[i]
        bd.ops.insert(i + 1, OpDesc(od.type, dict(od.inputs),
                                    dict(od.outputs), dict(od.attrs)))

    def alias_race(p):
        bd = p.desc.block(0)
        bd.vars["__shadow__"] = VarDesc("__shadow__", dtype="float32",
                                        shape=(13, 1))
        # an unordered reader of the in-place-updated parameter
        bd.ops.insert(0, OpDesc("scale", {"X": [param_name]},
                                {"Out": ["__shadow__"]}, {"scale": 2.0}))

    def dead_op(p):
        bd = p.desc.block(0)
        bd.vars["__unused__"] = VarDesc("__unused__", dtype="float32",
                                        shape=(1,))
        bd.ops.append(OpDesc("scale", {"X": [loss_name]},
                             {"Out": ["__unused__"]}, {"scale": 1.0}))

    return [
        ("unknown op", "V001", unknown_op),
        ("use-before-def", "V003", use_before_def),
        ("dtype mismatch", "V005", dtype_mismatch),
        ("dangling BlockRef", "V004", dangling_block_ref),
        ("write-write race", "H001", write_write),
        ("in-place alias read hazard", "H002", alias_race),
        ("dead op", "D001", dead_op),
    ]


# the four multichip dryrun mesh shapes (__graft_entry__.dryrun paths);
# the sharding selftest proves every clean program analyzes green on
# ALL of them before CI lets a change land
DRYRUN_MESHES = [
    ("dp/mp", "dp=4,mp=2"),
    ("dp/mp/sp", "dp=2,mp=2,sp=2"),
    ("pp/dp", "pp=4,dp=2"),
    ("dp/ep", "dp=2,ep=4"),
]


def _build_lenet5_train():
    """lenet5 -> cross-entropy -> Momentum in a fresh Program pair (the
    flagship small-model topology: conv/pool/fc/softmax, real backward
    + update ops)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.image import lenet5

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        probs = lenet5(img, class_dim=10)
        loss = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=probs, label=label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    return main, loss.name


def _shard_corruptions():
    """[(label, expected S-code, run(analysis, mesh_spec) -> Report)]
    — one seeded sharding corruption per stable S0xx code, each run
    against a mesh parsed from a dryrun shape."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.mesh import parse_mesh_spec

    def _mlp(batch=None):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            kw = {} if batch is None else \
                {"append_batch_size": False}
            shp = [1024] if batch is None else [batch, 1024]
            x = fluid.layers.data(name="x", shape=shp,
                                  dtype="float32", **kw)
            h = fluid.layers.fc(input=x, size=1024, act="relu")
            loss = fluid.layers.mean(x=h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, loss.name

    def s001_unmatched_rule(analysis, mesh):
        main, loss = _mlp()
        return analysis.analyze_sharding(
            main, mesh, fetches=[loss], publish=False,
            rules=[("^matches_nothing$", ())]).report

    def s002_non_divisible_batch(analysis, mesh):
        main, loss = _mlp(batch=6)  # 6 % dp=4 != 0
        # concrete_feeds: the trainer boundary, where the static
        # batch IS the runtime batch
        return analysis.analyze_sharding(
            main, mesh, fetches=[loss], publish=False,
            concrete_feeds=True).report

    def s003_conflicting_layouts(analysis, mesh):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = fluid.layers.data(name="a", shape=[8, 16],
                                  dtype="float32",
                                  append_batch_size=False)
            b = fluid.layers.data(name="b", shape=[8, 16],
                                  dtype="float32",
                                  append_batch_size=False)
            fluid.layers.elementwise_add(x=a, y=b)
        # a shards dim0 over dp (the default), b demands mp there
        return analysis.analyze_sharding(
            main, mesh, feed_specs={"b": ("mp",)},
            publish=False).report

    def s004_schedule_mismatch(analysis, mesh):
        # 3 stacked stages on a pp=4 ring: the ppermute misroutes
        return analysis.check_pipeline(
            parse_mesh_spec("pp=4,dp=2"), n_stages=3,
            n_microbatches=8)

    def s005_hbm_budget(analysis, mesh):
        main, loss = _mlp()
        return analysis.analyze_sharding(
            main, mesh, fetches=[loss], hbm_gb=1e-6,
            publish=False).report

    return [
        ("param matched no partition rule", "S001",
         s001_unmatched_rule),
        ("batch not divisible by dp", "S002",
         s002_non_divisible_batch),
        ("conflicting input layouts", "S003",
         s003_conflicting_layouts),
        ("pipeline stage/mesh mismatch", "S004",
         s004_schedule_mismatch),
        ("peak HBM over budget", "S005", s005_hbm_budget),
    ]


def _build_two_segment():
    """fc -> print -> mean: the host print op splits block 0 into two
    jit segments, so the fc output crosses a segment boundary and the
    tail segment can (provably) donate it.  Returns (main, startup,
    intermediate name, loss name)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.desc import OpDesc

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        h = fluid.layers.fc(input=x, size=8)
        loss = fluid.layers.mean(x=h)
    bd = main.desc.block(0)
    i = next(i for i, od in enumerate(bd.ops) if od.type == "mean")
    bd.ops.insert(i, OpDesc("print", {"X": [h.name]},
                            {"Out": [h.name]},
                            {"message": "seg-split", "summarize": 1}))
    return main, startup, h.name, loss.name


def _donation_corruptions():
    """[(label, expected A-code, run(analysis) -> Report)] — one
    seeded donation-safety corruption per stable A0xx code."""
    from paddle_tpu.core.desc import OpDesc
    from paddle_tpu.tools.mem_cli import (_build_adam_toy,
                                          _fork_adam_slot)

    def a001_forked_slot(analysis):
        main, _startup, cost = _build_adam_toy()
        _fork_adam_slot(main)
        return analysis.analyze_donation(
            main, fetches=[cost.name], publish=False).report

    def a002_late_reader(analysis):
        # plan first, then the program grows a reader of the donated
        # intermediate: replaying the stale plan must be an ERROR
        main, _startup, hname, lname = _build_two_segment()
        plan = analysis.analyze_donation(main, fetches=[lname],
                                         feeds=["x"], publish=False)
        assert any(hname in s["widened"] for s in plan.segments), \
            "two-segment seed did not widen %r: %r" \
            % (hname, [s["widened"] for s in plan.segments])
        main.desc.block(0).ops.append(
            OpDesc("scale", {"X": [hname]}, {"Out": ["__late__"]},
                   {"scale": 2.0}))
        return plan.verify(main, fetches=[lname, "__late__"])

    def a003_fetched_candidate(analysis):
        main, _startup, hname, lname = _build_two_segment()
        return analysis.analyze_donation(
            main, fetches=[hname, lname], feeds=["x"],
            publish=False).report

    def a004_non_jit_update(analysis):
        # dist_send declares ParamOut in-place but is not jittable:
        # the declared reuse strands in the eager segment
        main, _startup, cost = _build_adam_toy()
        bd = main.desc.block(0)
        pname = next(n for n, vd in bd.vars.items()
                     if vd.is_parameter)
        bd.ops.append(OpDesc("dist_send",
                             {"Param": [pname], "Grad": [pname]},
                             {"ParamOut": [pname]},
                             {"param_name": pname, "blocks": []}))
        return analysis.analyze_donation(
            main, fetches=[cost.name], publish=False).report

    def a005_unsafe_backend(analysis):
        main, _startup, cost = _build_adam_toy()
        return analysis.analyze_donation(
            main, fetches=[cost.name], mode="auto",
            backend_safe=False, publish=False).report

    return [
        ("forked in-place slot", "A001", a001_forked_slot),
        ("read-after-donation hazard", "A002", a002_late_reader),
        ("fetch aliases donatable buffer", "A003",
         a003_fetched_candidate),
        ("in-place update stranded non-jit", "A004",
         a004_non_jit_update),
        ("donation-unsafe backend", "A005", a005_unsafe_backend),
    ]


def _selftest_donation(args):
    """The donation-safety analyzer leg of --selftest."""
    from paddle_tpu import analysis
    from paddle_tpu.tools.mem_cli import _build_adam_toy

    # 1. clean targets plan with zero A-code findings: the adam toy
    #    (donates its conservative set), lenet5, every golden fixture
    main, _startup, cost = _build_adam_toy()
    plan = analysis.analyze_donation(main, fetches=[cost.name],
                                     publish=False)
    assert plan.report.ok() and not plan.report.codes(), \
        "clean adam toy reported:\n%s" % plan.report.format()
    assert any(plan.donate(i) for i in range(len(plan.segments))), \
        "clean adam toy donates nothing"
    lenet_main, lenet_loss = _build_lenet5_train()
    targets = [("lenet5", lenet_main, [lenet_loss])]
    targets += [(name, desc, None) for name, desc in _golden_descs()]
    for name, prog, fetches in targets:
        p = analysis.analyze_donation(prog, fetches=fetches,
                                      publish=False)
        assert p.report.ok(), "%s donation plan has errors:\n%s" \
            % (name, p.report.format())

    # 2. every seeded corruption reports its exact A-code
    for label, code, run in _donation_corruptions():
        report = run(analysis)
        assert report.has(code), \
            "%s: expected %s, got codes %s\n%s" \
            % (label, code, report.codes(), report.format())

    # 3. the mode ladder is ordered: off donates nothing,
    #    conservative a subset of auto, and the fingerprints differ
    plans = {m: analysis.analyze_donation(main, fetches=[cost.name],
                                          mode=m, publish=False)
             for m in ("off", "conservative", "auto")}
    for i in range(len(plans["auto"].segments)):
        assert plans["off"].donate(i) == ()
        assert set(plans["conservative"].donate(i)) <= \
            set(plans["auto"].donate(i))
    assert plans["off"].fingerprint() != plans["auto"].fingerprint()
    return len(_donation_corruptions())


def _selftest_sharding(args):
    """The sharding analyzer leg of --selftest."""
    import paddle_tpu.fluid as fluid  # noqa: F401  (program builders)
    from paddle_tpu import analysis
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.parallel.mesh import parse_mesh_spec

    # 1. the clean lenet5 training program and every golden fixture
    #    analyze with ZERO errors on all four dryrun mesh shapes
    lenet_main, lenet_loss = _build_lenet5_train()
    targets = [("lenet5", lenet_main, [lenet_loss])]
    targets += [(name, desc, None) for name, desc in _golden_descs()]
    for mesh_label, mesh_spec in DRYRUN_MESHES:
        mesh = parse_mesh_spec(mesh_spec)
        for name, prog, fetches in targets:
            plan = analysis.analyze_sharding(prog, mesh,
                                             fetches=fetches,
                                             publish=False)
            assert plan.report.ok(), \
                "%s on %s mesh reported errors:\n%s" \
                % (name, mesh_label, plan.report.format())

    # 2. every seeded sharding corruption reports its exact S-code.
    # The seeds are tuned to this mesh (batch 6 % dp=4, an mp axis to
    # conflict with) — pinned, NOT args.mesh, so any legal --mesh
    # value leaves the selftest self-contained
    default_mesh = parse_mesh_spec("dp=4,mp=2")
    for label, code, run in _shard_corruptions():
        report = run(analysis, default_mesh)
        assert report.has(code), \
            "%s: expected %s, got codes %s\n%s" \
            % (label, code, report.codes(), report.format())
        assert any(d.code == code and d.severity in
                   ("error", "warning") for d in report.diagnostics), \
            "%s: %s only reported as info" % (label, code)

    # 3. the comm cost model prices the dp gradient sync and lands in
    #    the registry as shard_comm_bytes_total{collective}
    plan = analysis.analyze_sharding(lenet_main, default_mesh,
                                     fetches=[lenet_loss],
                                     publish=True,
                                     origin="lint_selftest")
    totals = plan.comm.totals()
    assert totals.get("allreduce", 0) > 0, \
        "no gradient all-reduce priced: %s" % totals
    assert plan.peak_hbm_bytes and plan.peak_hbm_bytes > 0
    snap = {s["name"] for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "shard_comm_bytes_total" in snap, \
        "shard_comm_bytes_total missing from the registry"
    return len(_shard_corruptions())


def selftest(args):
    # never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import analysis
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.utils import flags

    main, startup, loss_name, param_name = _build_train_program()

    # 1. the clean program: zero error-severity diagnostics
    clean = analysis.check_program(main, level="full",
                                   fetches=[loss_name],
                                   origin="lint_selftest")
    assert clean.ok(), \
        "clean program reported errors:\n%s" % clean.format()

    # 2. every seeded corruption reports its stable code
    for label, code, mutate in _corruptions(main, loss_name,
                                            param_name):
        prog = main.clone()
        mutate(prog)
        report = analysis.check_program(prog, level="full",
                                        fetches=[loss_name],
                                        publish=False)
        assert report.has(code), \
            "%s: expected %s, got codes %s\n%s" \
            % (label, code, report.codes(), report.format())

    # 3. suppression: the same corruption vanishes when suppressed
    prog = main.clone()
    _corruptions(main, loss_name, param_name)[0][2](prog)
    sup = analysis.check_program(prog, level="full", suppress=("V001",),
                                 publish=False)
    assert not sup.has("V001") and sup.suppressed, "suppression broken"

    # 4. the executor gate: corruption fails BEFORE any XLA compile,
    #    naming op index + var
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        prev = flags.get_flag("verify_program")
        flags.set_flag("verify_program", True)
        try:
            feed = {"x": np.zeros((2, 13), np.float32),
                    "y": np.zeros((2, 1), np.float32)}
            out, = exe.run(main, feed=feed, fetch_list=[loss_name])
            assert np.isfinite(out).all()
            bad = main.clone()
            bad.desc.block(0).ops[2].type = "definitely_not_an_op"
            try:
                exe.run(bad, feed=feed, fetch_list=[loss_name])
                raise AssertionError(
                    "corrupted program ran under FLAGS_verify_program")
            except analysis.ProgramVerificationError as err:
                first = err.report.errors[0]
                assert first.op_index is not None, first
                assert "op 2" in str(err), err
        finally:
            flags.set_flag("verify_program", prev)

    # 5. finding counters landed in the obs registry
    snap = {s["name"]: s for s in
            obs_registry.get_registry().to_dict()["metrics"]}
    assert "analysis_diagnostics_total" in snap or any(
        k.startswith("analysis_") for k in snap), \
        "no analysis_* metrics in the registry"

    # 6. the SPMD/sharding analyzer: clean programs green on all four
    #    dryrun mesh shapes, seeded S0xx corruptions each caught,
    #    comm cost model in the registry
    n_shard = _selftest_sharding(args)

    # 7. the donation-safety analyzer: clean programs plan green,
    #    seeded A0xx corruptions each caught, mode ladder ordered
    n_donation = _selftest_donation(args)

    print("[lint] selftest green: clean program verified (0 errors), "
          "%d seeded corruptions each reported their code, "
          "suppression filters, executor FLAGS_verify_program gate "
          "rejects pre-compile with op identity, finding counters in "
          "the registry; sharding: lenet5 + golden fixtures clean on "
          "%d dryrun mesh shapes, %d seeded S-code corruptions each "
          "caught, comm bytes published; donation: clean targets "
          "plan green, %d seeded A-code corruptions each caught, "
          "off/conservative/auto ladder ordered"
          % (len(_corruptions(main, loss_name, param_name)),
             len(DRYRUN_MESHES), n_shard, n_donation), flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.golden is not None:
        return lint_golden(args)
    if args.model_dir:
        return lint_model_dir(args)
    raise SystemExit("nothing to do: pass a model dir, --golden, or "
                     "--selftest")


if __name__ == "__main__":
    sys.exit(main())
