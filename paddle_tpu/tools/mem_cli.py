"""pmem — HBM memory observability CLI (paddle_tpu.obs.mem).

    # the static memory timeline of a model's training program:
    # per-op live bytes, peak op, top resident buffers blamed to
    # their defining ops (+ a Chrome-trace counter track)
    pmem timeline --model lenet5 --batch 128 [--trace-out mem.json]

    # static-vs-XLA drift: run one step under attribution (or join a
    # saved --store dump), report actual/static per segment, and
    # emit the calibration blob `ptune plan --hbm-calibration` eats
    pmem drift --model lenet5 [--calibration-out mem_cal.json]
    pmem drift --store mem_store.json

    # buffer-donation audit: param/optimizer-state buffers that are
    # dead-after-use but NOT donated, with bytes reclaimable
    pmem audit --model lenet5

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh)
    pmem --selftest

`--selftest` proves the whole loop on CPU: timeline render + counter
track (validated as Chrome trace JSON), a REAL lenet5 step whose
static peak joins XLA's `memory_analysis()` actuals into a drift
report with a usable calibration blob, a donation audit that finds a
deliberately-forked Adam moment slot (and nothing on the clean
program), and a forced-tiny-budget OOM whose flight bundle carries
the same top blamed buffer the static timeline names.
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pmem")
    p.add_argument("cmd", nargs="?",
                   choices=["timeline", "drift", "audit"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="timeline + drift join + donation audit + "
                        "OOM flight-bundle certification (CPU)")
    p.add_argument("--model", default="lenet5",
                   help="model name (paddle_tpu.tune.models)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--class-dim", type=int, default=None)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--f32", dest="bf16", action="store_false")
    p.add_argument("--top", type=int, default=8,
                   help="timeline: blamed buffers to list")
    p.add_argument("--trace-out", default=None,
                   help="timeline: write the Chrome-trace counter "
                        "track here (co-loadable with obs exports)")
    p.add_argument("--store", default=None,
                   help="drift: join a saved obs.mem store dump "
                        "instead of running a step in-process")
    p.add_argument("--store-out", default=None,
                   help="drift: also dump this process's capture "
                        "store for later offline joins")
    p.add_argument("--calibration-out", default=None,
                   help="drift: write the hbm_ratio calibration blob "
                        "`ptune plan --hbm-calibration` consumes")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    return p.parse_args(argv)


def _build_train(model, batch, image_size=None, class_dim=None):
    """(main, startup, loss_var): the tune.models training recipe,
    with the startup program the drift run needs (tune's builder
    discards it — ranking never executes)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.tune.models import MODELS, _model_fn

    if model not in MODELS:
        raise SystemExit("unknown model %r; pmem knows %s"
                         % (model, ", ".join(sorted(MODELS))))
    spec = MODELS[model]
    size = int(image_size or spec["image_size"])
    classes = int(class_dim or spec["class_dim"])
    fn = _model_fn(model)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        image = fluid.layers.data(
            name="image", shape=[batch, spec["channels"], size, size],
            dtype="float32", append_batch_size=False)
        logits = fn(image, class_dim=classes)
        label = fluid.layers.data(
            name="label", shape=[batch, 1], dtype="int64",
            append_batch_size=False)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    return main, startup, loss


def _feeds(model, batch, image_size=None, class_dim=None):
    import numpy as np

    from paddle_tpu.tune.models import MODELS

    spec = MODELS[model]
    size = int(image_size or spec["image_size"])
    classes = int(class_dim or spec["class_dim"])
    rs = np.random.RandomState(0)
    return {
        "image": rs.rand(batch, spec["channels"], size,
                         size).astype("float32"),
        "label": rs.randint(0, classes, (batch, 1)).astype("int64"),
    }


def _amp(bf16):
    import paddle_tpu.fluid as fluid

    if bf16:
        fluid.amp.enable_bf16()
    else:
        fluid.amp.disable_bf16()


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------

def cmd_timeline(args):
    from paddle_tpu.obs import mem as obs_mem

    _amp(args.bf16)
    main, _startup, loss = _build_train(args.model, args.batch,
                                        args.image_size,
                                        args.class_dim)
    tl = obs_mem.program_timeline(main, fetches=[loss.name],
                                  top_n=args.top)
    if args.trace_out:
        obs_mem.timeline_chrome_trace(tl, path=args.trace_out)
    if args.json:
        print(json.dumps(tl, sort_keys=True))
    else:
        print("[pmem] %s batch %d (%s):"
              % (args.model, args.batch,
                 "bf16-act" if args.bf16 else "f32"))
        print(obs_mem.render_timeline(tl))
        if args.trace_out:
            print("[pmem] counter track written: %s (load next to an "
                  "obs_dump trace in Perfetto)" % args.trace_out)
    return 0


def cmd_audit(args):
    from paddle_tpu.obs import mem as obs_mem

    _amp(args.bf16)
    main, _startup, loss = _build_train(args.model, args.batch,
                                        args.image_size,
                                        args.class_dim)
    audit = obs_mem.audit_donation(main, fetches=[loss.name])
    if args.json:
        print(json.dumps(audit, sort_keys=True))
    else:
        print("[pmem] %s batch %d:" % (args.model, args.batch))
        print(obs_mem.render_audit(audit))
    return audit["reclaimable_bytes"] > 0 and 1 or 0


def _capture_one_step(args):
    """Run one real training step with attribution forced so the
    executor registers the static side and publish_compile_stats
    supplies the XLA side of every segment's drift join."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.obs import health as obs_health

    _amp(args.bf16)
    main, startup, loss = _build_train(args.model, args.batch,
                                       args.image_size,
                                       args.class_dim)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        with obs_health.force_attribution():
            exe.run(main, feed=_feeds(args.model, args.batch,
                                      args.image_size,
                                      args.class_dim),
                    fetch_list=[loss], scope=scope)
    return main, loss


def cmd_drift(args):
    from paddle_tpu.obs import mem as obs_mem

    if args.store:
        store = obs_mem.load_store(args.store)
    else:
        _capture_one_step(args)
        store = None  # this process's live capture
    rep = obs_mem.drift_report(store)
    if args.store_out and not args.store:
        obs_mem.dump_store(args.store_out)
    if args.json:
        print(json.dumps(rep, sort_keys=True))
    else:
        print("[pmem] " + ("store %s" % args.store if args.store
                           else "%s batch %d, one captured step"
                           % (args.model, args.batch)))
        print(obs_mem.render_drift(rep))
    if args.calibration_out:
        blob = obs_mem.calibration_blob(rep, model=None if args.store
                                        else args.model)
        if blob is None:
            print("[pmem] no joined segments — no calibration "
                  "written", file=sys.stderr)
            return 2
        obs_mem.save_calibration(blob, args.calibration_out)
        if not args.json:
            print("[pmem] calibration written: %s (hbm_ratio %.3f "
                  "over %d segment(s)) — feed it to `ptune plan "
                  "--hbm-calibration`"
                  % (args.calibration_out, blob["hbm_ratio"],
                     blob["n"]))
    return 0 if rep["n"] else 2


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _fork_adam_slot(program):
    """Deliberately break one Adam update's Moment1Out alias (the
    H003 fork class): the audit must name the stranded moment buffer
    as reclaimable."""
    from paddle_tpu.core.desc import VarDesc

    bd = program.desc.block(0)
    for od in bd.ops:
        if od.type == "adam":
            m1 = od.input("Moment1")[0]
            fork = m1 + "__fork"
            src = bd.vars[m1]
            bd.vars[fork] = VarDesc(fork, src.type, src.dtype,
                                    src.shape, persistable=True)
            od.outputs["Moment1Out"] = [fork]
            return m1
    raise AssertionError("no adam op to fork")


def _build_adam_toy():
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        h = fluid.layers.fc(input=x, size=32)
        cost = fluid.layers.mean(x=h)
        fluid.optimizer.AdamOptimizer(
            learning_rate=0.01).minimize(cost)
    return main, startup, cost


def selftest(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.obs import flight as obs_flight
    from paddle_tpu.obs import mem as obs_mem
    from paddle_tpu.tools import obs_dump
    from paddle_tpu.tune.fit import load_hbm_calibration
    from paddle_tpu.utils import flags as pt_flags

    workdir = tempfile.mkdtemp(prefix="paddle_pmem_")

    # --- leg 1: static timeline + counter-track export -----------------
    main, startup, loss = _build_train("lenet5", 8)
    tl = obs_mem.program_timeline(main, fetches=[loss.name], top_n=5)
    assert len(tl["series"]) == tl["ops"] and tl["ops"] > 0, tl
    assert tl["peak_bytes"] > 0 and tl["peak_op"] is not None, tl
    assert tl["top_buffers"], "no blamed buffers at the peak"
    assert tl["top_buffers"][0]["def_op_type"], tl["top_buffers"][0]
    rendered = obs_mem.render_timeline(tl)
    assert "<- peak" in rendered and "top buffers" in rendered
    trace_path = os.path.join(workdir, "mem_trace.json")
    obs_mem.timeline_chrome_trace(tl, path=trace_path)
    events = obs_dump.validate_chrome_trace(trace_path)
    assert any(ev["ph"] == "C" for ev in events), \
        "no counter events in the mem trace"

    # --- leg 2: drift join on a real captured step + calibration -------
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    from paddle_tpu.obs import health as obs_health

    feeds = _feeds("lenet5", 8)
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
        with obs_health.force_attribution():
            exe.run(main, feed=feeds, fetch_list=[loss], scope=scope)
    rep = obs_mem.drift_report()
    joined = [r for r in rep["segments"] if r["ratio"]]
    assert joined, "no static-vs-XLA joined segments:\n%s" \
        % obs_mem.render_drift(rep)
    assert rep["median_ratio"] and rep["median_ratio"] > 0
    cal_path = os.path.join(workdir, "mem_cal.json")
    obs_mem.save_calibration(
        obs_mem.calibration_blob(rep, model="lenet5"), cal_path)
    ratio = load_hbm_calibration(cal_path)
    assert ratio == rep["median_ratio"], (ratio, rep["median_ratio"])
    store_path = os.path.join(workdir, "mem_store.json")
    obs_mem.dump_store(store_path)
    offline = obs_mem.drift_report(obs_mem.load_store(store_path))
    assert offline["n"] == rep["n"], "offline store join drifted"

    # --- leg 3: donation audit — clean program, then a forked slot -----
    adam_main, _adam_startup, adam_cost = _build_adam_toy()
    clean = obs_mem.audit_donation(adam_main,
                                   fetches=[adam_cost.name])
    assert clean["donated"] and not clean["reclaimable"], \
        obs_mem.render_audit(clean)
    forked_name = _fork_adam_slot(adam_main)
    broken = obs_mem.audit_donation(adam_main,
                                    fetches=[adam_cost.name])
    hits = [r for r in broken["reclaimable"]
            if r["name"] == forked_name]
    assert hits and hits[0]["bytes"] > 0 \
        and hits[0]["kind"] == "optimizer_state", \
        obs_mem.render_audit(broken)
    # the refusal is explained, not just priced: the forked slot
    # carries its A-code (analysis/alias.py) in entry and rendering
    assert hits[0].get("code") == "A001", hits[0]
    assert "A001" in obs_mem.render_audit(broken)
    # the plan closes what the audit prices: flag off, every donated
    # buffer moves to reclaimable — the off/auto delta IS the win
    off = obs_mem.audit_donation(adam_main, fetches=[adam_cost.name],
                                 mode="off")
    assert not off["donated"], obs_mem.render_audit(off)
    assert off["reclaimable_bytes"] == (broken["reclaimable_bytes"]
                                        + broken["donated_bytes"]), \
        (off["reclaimable_bytes"], broken["reclaimable_bytes"],
         broken["donated_bytes"])

    # --- leg 4: forced-tiny-budget OOM -> flight bundle with blame -----
    recorder = obs_flight.install(out_dir=workdir, capacity=8)
    oom_scope = fluid.Scope()
    oom_exe = fluid.Executor(fluid.CPUPlace())
    budget_prev = pt_flags.get_flag("mem_budget_gb")
    try:
        with fluid.scope_guard(oom_scope):
            oom_exe.run(startup, scope=oom_scope)
            pt_flags.set_flag("mem_budget_gb", 1e-6)
            try:
                oom_exe.run(main, feed=feeds, fetch_list=[loss],
                            scope=oom_scope, use_program_cache=False)
                raise AssertionError("tiny mem budget did not trip "
                                     "the pre-flight")
            except obs_mem.MemoryBudgetError as exc:
                assert "RESOURCE_EXHAUSTED" in str(exc), exc
    finally:
        pt_flags.set_flag("mem_budget_gb", budget_prev)
        obs_flight.uninstall()
    bundle = recorder.last_bundle_path
    assert bundle and os.path.exists(bundle), "no OOM flight bundle"
    with open(bundle) as f:
        doc = json.load(f)
    oom_notes = [n["oom"] for n in doc.get("notes", [])
                 if n.get("oom")]
    assert oom_notes, "flight bundle carries no oom note"
    top = oom_notes[0]["top_buffers"]
    assert top and top[0]["name"] == tl["top_buffers"][0]["name"], \
        "bundle's top blamed buffer %r != static timeline's %r" \
        % (top and top[0]["name"], tl["top_buffers"][0]["name"])
    rendered_bundle = obs_dump.render_flight(bundle)
    assert "OOM post-mortem" in rendered_bundle

    print("[pmem] selftest green: timeline %d op(s) peak %.2f MiB at "
          "op %s (%s), counter track %d event(s); drift joined %d "
          "segment(s) median ratio %.3f -> calibration %s; donation "
          "audit: clean program donates %d buffer(s), forked Adam "
          "slot %r flagged A001 with %.1f KiB reclaimable and "
          "FLAGS_donation=off surrenders the full delta; OOM bundle "
          "%s blames %r"
          % (tl["ops"], tl["peak_bytes"] / 2**20, tl["peak_op"],
             tl["peak_op_type"], len(events), rep["n"],
             rep["median_ratio"], cal_path, len(clean["donated"]),
             forked_name, hits[0]["bytes"] / 1024.0, bundle,
             top[0]["name"]),
          flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "timeline":
        return cmd_timeline(args)
    if args.cmd == "drift":
        return cmd_drift(args)
    if args.cmd == "audit":
        return cmd_audit(args)
    raise SystemExit("nothing to do: pass timeline|drift|audit or "
                     "--selftest")


if __name__ == "__main__":
    sys.exit(main())
