"""paddle_trainer-style CLI: train a config-file topology.

reference: paddle/trainer/TrainerMain.cpp:32 (`paddle_trainer
--config=conf.py --num_passes=.. --save_dir=..`) — the C++ trainer
embeds Python to parse the config and drives GradientMachine passes.
Here the config executes directly (its DSL calls build the fluid
Program), and the v2 SGD trainer drives the compiled program:

    python -m paddle_tpu.tools.trainer_cli --config=conf.py \
        --num_passes=3 --save_dir=./output [--use_gpu is accepted and
        ignored: placement follows the available accelerator]

The config calls settings(...), define_py_data_sources2(...), builds
layers, and declares outputs(cost) — see
trainer_config_helpers/config.py for the provider convention.
"""

import argparse
import os
import runpy
import sys

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_trainer")
    p.add_argument("--config", required=True,
                   help="python config file (trainer_config_helpers DSL)")
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--save_dir", default=None,
                   help="save parameters tar per pass (ParamUtil "
                        "behavior: pass-00000/, pass-00001/, ...)")
    p.add_argument("--init_model_path", default=None,
                   help="warm-start parameters tar")
    p.add_argument("--start_pass", type=int, default=0)
    p.add_argument("--log_period", type=int, default=10)
    p.add_argument("--use_gpu", default=None,
                   help="accepted for reference-CLI compat; ignored "
                        "(placement follows the available accelerator)")
    p.add_argument("--trainer_count", type=int, default=1,
                   help="accepted for compat; single-process runs use "
                        "the mesh instead")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    import paddle_tpu as paddle
    import paddle_tpu.v2 as v2
    from paddle_tpu.trainer_config_helpers import config as tc_config

    cfg = tc_config.reset_config()
    # execute the config: its DSL calls build into the default Program
    # and record settings/outputs/data sources
    sys.path.insert(0, os.path.dirname(os.path.abspath(args.config)))
    runpy.run_path(args.config, run_name="__paddle_config__")

    if not cfg.outputs:
        raise SystemExit("config declared no outputs(); nothing to train")
    cost = cfg.outputs[0]
    train_reader = tc_config.build_reader(cfg.train_source)
    if train_reader is None:
        raise SystemExit("config declared no train data source")
    test_reader = tc_config.build_reader(cfg.test_source)

    optimizer = cfg.learning_method or v2.optimizer.Adam(
        learning_rate=cfg.learning_rate)
    if cfg.lr_explicit:
        # reference DSL semantics: settings() owns the learning rate,
        # the learning_method object only picks the update rule
        optimizer.learning_rate = cfg.learning_rate
    schedule = cfg.extra.get("learning_rate_schedule")
    if schedule and schedule != "constant":
        # reference LearningRateScheduler spellings (samples-based)
        import paddle_tpu.fluid as fluid

        optimizer.learning_rate = fluid.lr_schedules.v2_schedule(
            schedule, optimizer.learning_rate,
            decay_a=float(cfg.extra.get("learning_rate_decay_a", 0.0)),
            decay_b=float(cfg.extra.get("learning_rate_decay_b", 0.0)),
            batch_size=cfg.batch_size)

    parameters = v2.parameters.create(cost)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            parameters.init_from_tar(f)
    trainer = v2.trainer.SGD(cost=cost, parameters=parameters,
                             update_equation=optimizer)

    batched = paddle.batch(train_reader, batch_size=cfg.batch_size)
    state = {"pass": args.start_pass, "batch": 0, "costs": []}

    def handler(ev):
        if isinstance(ev, v2.event.EndIteration):
            state["batch"] += 1
            state["costs"].append(float(np.asarray(ev.cost).reshape(-1)[0]))
            if state["batch"] % args.log_period == 0:
                print("Pass %d, Batch %d, Cost %.6f" %
                      (state["pass"], state["batch"], state["costs"][-1]),
                      flush=True)
        elif isinstance(ev, v2.event.EndPass):
            mean_cost = (float(np.mean(state["costs"]))
                         if state["costs"] else float("nan"))
            line = "Pass %d done, AvgCost %.6f" % (state["pass"],
                                                   mean_cost)
            if test_reader is not None:
                result = trainer.test(reader=paddle.batch(
                    test_reader, batch_size=cfg.batch_size))
                line += ", TestCost %.6f" % result.cost
            print(line, flush=True)
            if args.save_dir:
                pass_dir = os.path.join(args.save_dir,
                                        "pass-%05d" % state["pass"])
                os.makedirs(pass_dir, exist_ok=True)
                with open(os.path.join(pass_dir, "params.tar"),
                          "wb") as f:
                    parameters.to_tar(f)
            state["pass"] += 1
            state["batch"] = 0
            state["costs"] = []

    trainer.train(reader=batched, num_passes=args.num_passes,
                  event_handler=handler)
    return 0


if __name__ == "__main__":
    sys.exit(main())
