"""Autotuner CLI ("ptune"): offline launch-config search over
`paddle_tpu.tune` — rank the whole space with zero devices, measure
only the top-K, learn from what was measured.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.tune_cli --selftest

    # "what config do I launch lenet5 with on 8 chips of 16 GiB":
    # a ranked, priced table + a reproducible launch plan JSON —
    # runs anywhere, JAX_PLATFORMS=cpu, no devices touched
    python -m paddle_tpu.tools.tune_cli plan --model lenet5 \
        --chips 8 --hbm-gb 16 --out plan.json

    # burn hardware on only the top-3 survivors (records land in
    # perf_history.jsonl with leg ptune:<tag> + a "config" blob):
    python -m paddle_tpu.tools.tune_cli measure --plan plan.json --topk 3

    # fit the per-term correction from everything measured so far and
    # save it; the next `plan --calibration` ranks with it:
    python -m paddle_tpu.tools.tune_cli fit --plan plan.json \
        --calibration ptune_cal.json
    python -m paddle_tpu.tools.tune_cli plan --model lenet5 --chips 8 \
        --hbm-gb 16 --calibration ptune_cal.json

`--selftest` certifies the loop end to end on lenet5 against a fake
8-device mesh (no accelerator touched):

  1. **deterministic ranking** — two fresh `ptune plan --json`
     processes must emit byte-identical plans (the reproducibility
     contract launch plans rest on);
  2. **static rejection** — an injected S002-invalid mesh (batch not
     divisible by dp) and an S005 over-HBM budget are rejected at
     rank time with their exact codes, and the S002 candidate
     provably never reaches measurement;
  3. **measured top-K** — bench.py runs the top-2 candidates through
     the AOT + pcache path; their records land in the history file
     with `"config"` blobs and `ptune:` legs;
  4. **calibration** — `fit` over those records reports a model error
     that DECREASES after ingesting the measurements, and a re-rank
     with the fitted calibration changes the predictions.
"""

import argparse
import json
import os
import sys
import tempfile


def _csv(text):
    return [t.strip() for t in str(text).split(",") if t.strip()]


def _csv_int(text):
    return [int(t) for t in _csv(text)]


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ptune")
    p.add_argument("cmd", nargs="?",
                   choices=["plan", "measure", "fit", "report"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="full plan->rank->measure->fit loop on lenet5 "
                        "with a fake 8-device mesh")
    # plan: the model + target
    p.add_argument("--model", default="lenet5",
                   help="model to tune (tune/models.py zoo)")
    p.add_argument("--chips", type=int, default=8,
                   help="device count the plan targets")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM budget in GiB (enables the "
                        "S005 rejection)")
    # plan: the space
    p.add_argument("--meshes", default=None,
                   help="comma list of mesh specs (dp=4,mp=2 style "
                        "uses '=' and axis names, so separate CANDIDATE "
                        "meshes with ';'), default: every factorization "
                        "of --chips over --axes")
    p.add_argument("--axes", default="dp,mp",
                   help="axes to enumerate meshes over (default dp,mp)")
    p.add_argument("--batches", default="64,128,256",
                   help="global batch sizes (comma list)")
    p.add_argument("--micro-batches", default="1,2,4",
                   help="micro-batch splits (comma list)")
    p.add_argument("--pipelines", default="none,default",
                   help="pass pipelines (comma list of 'none', "
                        "'default', or +-joined pass names like "
                        "dce+fold or default+layout+fuse+auto_remat; "
                        "pass knobs attach with ':' — fuse:cap=8)")
    p.add_argument("--fusion-caps", default="0",
                   help="fuse:cap= settings crossed with pipelines "
                        "containing a bare fuse pass (comma ints; 0 = "
                        "pipeline default)")
    p.add_argument("--remat-strides", default="0",
                   help="auto_remat:stride= settings crossed with "
                        "pipelines containing a bare auto_remat pass "
                        "(comma ints; 0 = pipeline default)")
    # plan: the cost model
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--class-dim", type=int, default=None)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--f32", dest="bf16", action="store_false")
    p.add_argument("--peak-tflops", type=float, default=None)
    p.add_argument("--hbm-gbps", type=float, default=None)
    p.add_argument("--calibration", default=None,
                   help="plan: rank with this fitted calibration; "
                        "fit: save the fitted calibration here")
    p.add_argument("--hbm-calibration", default=None,
                   help="plan: a `pmem drift --calibration-out` blob; "
                        "its measured actual/static ratio scales the "
                        "static HBM peak before the S005 budget check "
                        "(tune.fit.load_hbm_calibration)")
    p.add_argument("--comm-calibration", default=None,
                   help="fit: a `pcomm report --calibration-out` "
                        "blob; its measured/predicted ring pairs "
                        "price the comm coefficient alongside any "
                        "multichip history records "
                        "(tune.fit.load_comm_calibration)")
    p.add_argument("--out", default=None,
                   help="plan: write the launch plan JSON here")
    p.add_argument("--topk", type=int, default=None,
                   help="plan: table rows; measure: candidates to run "
                        "(default 3)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    # measure / fit / report
    p.add_argument("--plan", dest="plan_path", default=None,
                   help="launch plan JSON from `ptune plan --out`")
    p.add_argument("--history", default="perf_history.jsonl",
                   help="perf history path (bench.py appends here)")
    p.add_argument("--iters", type=int, default=2)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--cache-dir", default=None,
                   help="measure: FLAGS_compile_cache_dir for the "
                        "bench runs (the pcache path)")
    p.add_argument("--timeout", type=float, default=900,
                   help="measure: per-candidate wall-clock bound")
    return p.parse_args(argv)


def _pipelines(arg):
    # '+' joins pass names on the command line because ',' separates
    # pipeline candidates: "none,default,dce+fold"
    return [p.replace("+", ",") for p in _csv(arg)]


def _build_space(args):
    from paddle_tpu.tune.space import SearchSpace

    meshes = None
    if args.meshes:
        meshes = [m.strip() for m in args.meshes.split(";")
                  if m.strip()]
    return SearchSpace(
        args.chips, meshes=meshes,
        pipelines=_pipelines(args.pipelines),
        batches=_csv_int(args.batches),
        micro_batches=_csv_int(args.micro_batches),
        axes=tuple(_csv(args.axes)),
        fusion_caps=_csv_int(args.fusion_caps),
        remat_strides=_csv_int(args.remat_strides))


def _rank_plan(args, extra_candidates=(), hbm_gb="arg"):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.tune import models as tune_models
    from paddle_tpu.tune import rank as tune_rank

    # explicit disable on --f32: amp state is process-global, and a
    # prior in-process plan (or library caller) may have enabled it
    # (the mega_bench run_one convention)
    if args.bf16:
        fluid.amp.enable_bf16()
    else:
        fluid.amp.disable_bf16()
    space = _build_space(args)
    candidates = space.points() + list(extra_candidates)
    calibration = None
    if args.calibration and os.path.exists(args.calibration):
        calibration = tune_rank.Calibration.load(args.calibration)
    hbm_ratio = None
    if getattr(args, "hbm_calibration", None):
        from paddle_tpu.tune.fit import load_hbm_calibration

        hbm_ratio = load_hbm_calibration(args.hbm_calibration)
    builder = tune_models.builder(args.model, image_size=args.image_size,
                                  class_dim=args.class_dim)
    # the EFFECTIVE builder knobs (CLI override or model default) ride
    # in the plan context so `ptune measure` replays the same program
    # the ranking priced
    spec = tune_models.MODELS[args.model]
    extra_context = {
        "image_size": int(args.image_size or spec["image_size"]),
        "class_dim": int(args.class_dim or spec["class_dim"]),
    }
    return tune_rank.rank(
        builder, candidates, args.chips, model=args.model,
        hbm_gb=args.hbm_gb if hbm_gb == "arg" else hbm_gb,
        calibration=calibration, bf16_act=args.bf16,
        peak_tflops=args.peak_tflops, hbm_gbps=args.hbm_gbps,
        space_dict=space.to_dict(), skipped=space.skipped,
        extra_context=extra_context, hbm_ratio=hbm_ratio)


def cmd_plan(args):
    plan = _rank_plan(args)
    if args.out:
        plan.save(args.out)
    if args.json:
        print(plan.to_json())
    else:
        print(plan.format_table(topk=args.topk))
        if args.out:
            print("[ptune] launch plan written to %s" % args.out)
    if not plan.ranked:
        print("[ptune] every candidate was rejected — see the plan's "
              "rejected list", file=sys.stderr)
        return 1
    return 0


def _load_plan(args):
    if not args.plan_path:
        raise SystemExit("--plan <plan.json> is required (make one "
                         "with `ptune plan --out plan.json`)")
    with open(args.plan_path) as f:
        return json.load(f)


def cmd_measure(args):
    from paddle_tpu.tune import measure as tune_measure

    plan = _load_plan(args)
    results = tune_measure.measure_plan(
        plan, topk=args.topk or 3, history=args.history,
        iters=args.iters, warmup=args.warmup,
        image_size=args.image_size, cache_dir=args.cache_dir,
        timeout=args.timeout,
        echo=lambda msg: print(msg, flush=True))
    ok = 0
    for r in results:
        if r["ok"]:
            ok += 1
            rec = r["record"]
            print("[ptune] %-44s %10.4g %-9s step %.2f ms (%s)"
                  % (r["tag"], rec.get("value") or 0.0,
                     rec.get("unit") or "", rec.get("step_ms") or 0.0,
                     rec.get("platform")))
        else:
            print("[ptune] %-44s FAILED: %s" % (r["tag"], r["error"]),
                  file=sys.stderr)
    print("[ptune] measured %d/%d candidate(s); history: %s"
          % (ok, len(results), args.history))
    return 0 if ok == len(results) and results else 1


def _join(args, plan):
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tune import fit as tune_fit

    records = obs_perf.load_history(args.history)
    return tune_fit.join_history(plan, records)


def cmd_fit(args):
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tune import fit as tune_fit

    plan = _load_plan(args)
    pairs = _join(args, plan)
    if not pairs:
        print("[ptune] no ptune-tagged measurements in %s for this "
              "plan — run `ptune measure` first" % args.history)
        return 2
    # multichip comm measurements (spmd/bench.py legs) price the comm
    # coefficient when the history has any from the training class
    comm_pairs = tune_fit.join_comm_history(
        obs_perf.load_history(args.history))
    if getattr(args, "comm_calibration", None):
        comm_pairs = comm_pairs + tune_fit.load_comm_calibration(
            args.comm_calibration)
    cal = tune_fit.fit_calibration(pairs, model=plan.get("model"),
                                   comm_pairs=comm_pairs)
    if args.json:
        print(json.dumps({"calibration": cal.to_dict(),
                          "pairs": len(pairs),
                          "comm_pairs": len(comm_pairs)},
                         sort_keys=True))
    else:
        print(tune_fit.format_fit_report(cal, pairs))
    if args.calibration:
        cal.save(args.calibration)
        if not args.json:
            print("[ptune] calibration saved to %s (rank with "
                  "`ptune plan --calibration %s`)"
                  % (args.calibration, args.calibration))
    return 0


def cmd_report(args):
    """Like fit, but read-only: show the current calibration's error
    against the measured history without refitting or saving."""
    from paddle_tpu.tune import fit as tune_fit
    from paddle_tpu.tune.rank import Calibration

    plan = _load_plan(args)
    pairs = _join(args, plan)
    if not pairs:
        print("[ptune] no ptune-tagged measurements in %s for this "
              "plan" % args.history)
        return 2
    cal = Calibration.identity()
    if args.calibration and os.path.exists(args.calibration):
        cal = Calibration.load(args.calibration)
    err = tune_fit._rel_error(pairs, cal.coef["compute"],
                              cal.coef["overhead"], cal.bias_s)
    if args.json:
        print(json.dumps({"calibration": cal.to_dict(),
                          "pairs": len(pairs),
                          "median_rel_error": round(err, 6)},
                         sort_keys=True))
    else:
        print(tune_fit.format_fit_report(cal, pairs))
        print("[ptune] current median relative error: %.1f%% over %d "
              "measurement(s)" % (err * 100, len(pairs)))
    return 0


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_SELFTEST_PLAN_ARGS = [
    "plan", "--model", "lenet5", "--chips", "8", "--hbm-gb", "16",
    "--batches", "32,64", "--micro-batches", "1,2",
    "--pipelines", "none,default", "--json",
]


def _selftest_determinism():
    """Two FRESH processes must emit byte-identical plan JSON."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.tune_cli"]
            + _SELFTEST_PLAN_ARGS,
            env=env, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, \
            "plan subprocess failed:\n%s" % proc.stderr[-2000:]
        outs.append(proc.stdout)
    assert outs[0] == outs[1], \
        "two fresh `ptune plan` processes disagreed — ranking is " \
        "nondeterministic"
    plan = json.loads(outs[0])
    assert plan["ranked"], "selftest space ranked nothing"
    for e in plan["ranked"]:
        assert e["predicted_step_ms"] > 0, e
        assert "comm_wire_bytes" in e and "peak_hbm_bytes" in e, e
    assert not plan["rejected"], \
        "clean lenet5 space rejected candidates: %r" % plan["rejected"]
    return plan


def _selftest_rejections(args):
    """Injected invalid candidates must be rejected with their exact
    codes and stay out of the ranked (measurable) list."""
    from paddle_tpu.tune.space import Candidate

    # batch 36 % dp=8 != 0: the sharding analyzer's S002 at the
    # concrete trainer boundary
    bad = Candidate("dp=8,mp=1", "", batch=36, micro_batches=1)
    plan = _rank_plan(args, extra_candidates=[bad])
    tags = [e.candidate.tag() for e in plan.ranked]
    assert bad.tag() not in tags, "S002-invalid mesh was ranked"
    rej = {r.candidate.tag(): r for r in plan.rejected}
    assert bad.tag() in rej, "S002-invalid mesh was not rejected"
    assert rej[bad.tag()].code == "S002", rej[bad.tag()]

    # an absurd budget: everything must reject S005 citing bytes
    tiny = _rank_plan(args, hbm_gb=1e-6)
    assert not tiny.ranked and tiny.rejected, \
        "1e-6 GiB budget ranked candidates"
    for r in tiny.rejected:
        assert r.code == "S005" and r.peak_hbm_bytes > 0, r
        assert "GiB" in r.message and "budget" in r.message, r
    return plan, bad


def _selftest_measure_fit(args, plan, bad, workdir):
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tune import fit as tune_fit
    from paddle_tpu.tune import measure as tune_measure

    history = os.path.join(workdir, "ptune_history.jsonl")
    results = tune_measure.measure_plan(
        plan, topk=2, history=history, iters=1, warmup=1,
        cache_dir=os.path.join(workdir, "pcache"),
        extra_env={"JAX_PLATFORMS": "cpu"}, timeout=600)
    assert len(results) == 2, results
    for r in results:
        assert r["ok"], "measurement failed: %r" % (r,)
        assert r["record"]["config"]["mesh"], r["record"]

    # the history file carries the join keys: ptune legs + config
    records = obs_perf.load_history(history)
    assert len(records) == 2, records
    for rec in records:
        assert rec.get("leg", "").startswith(tune_fit.LEG_PREFIX), rec
        assert rec.get("config", {}).get("mesh"), \
            "history line has no config blob: %r" % rec
    # the rejected candidate never reached measurement
    assert not any(r.get("leg") == tune_fit.LEG_PREFIX + bad.tag()
                   for r in records), \
        "S002-rejected candidate was measured"

    # calibration: error must decrease after ingesting measurements
    pairs = tune_fit.join_history(plan, records)
    assert len(pairs) == 2, pairs
    cal = tune_fit.fit_calibration(pairs, model="lenet5")
    assert cal.n == 2, cal.to_dict()
    assert cal.error_before is not None \
        and cal.error_after <= cal.error_before, \
        "calibration did not improve: %r" % cal.to_dict()
    # roundtrip + a calibrated re-rank changes the prediction
    cal_path = os.path.join(workdir, "cal.json")
    cal.save(cal_path)
    from paddle_tpu.tune.rank import Calibration

    loaded = Calibration.load(cal_path)
    assert loaded.to_dict() == cal.to_dict()
    args.calibration = cal_path
    plan2 = _rank_plan(args)
    tag = plan.ranked[0].candidate.tag()
    before = plan.entry(tag).predicted_step_s
    after = plan2.entry(tag).predicted_step_s
    assert after != before, \
        "fitted calibration left predictions unchanged"
    return len(records), cal


def selftest(args):
    import shutil

    # never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the selftest space is pinned (not the user's --batches etc.) so
    # it stays self-contained under any CLI invocation
    args = parse_args(_SELFTEST_PLAN_ARGS)
    workdir = tempfile.mkdtemp(prefix="paddle_ptune_")
    try:
        _selftest_determinism()
        plan, bad = _selftest_rejections(args)
        measured, cal = _selftest_measure_fit(args, plan, bad, workdir)
    finally:
        # ci.sh/smoke.sh run this every time: don't stack /tmp dirs
        shutil.rmtree(workdir, ignore_errors=True)

    print("[ptune] selftest green: deterministic plan (%d candidates "
          "ranked), S002 + S005 rejected before measurement, %d "
          "top-K records measured into history with config blobs, "
          "calibration error %.1f%% -> %.1f%%"
          % (len(plan.ranked), measured, cal.error_before * 100,
             cal.error_after * 100), flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "plan":
        return cmd_plan(args)
    if args.cmd == "measure":
        return cmd_measure(args)
    if args.cmd == "fit":
        return cmd_fit(args)
    if args.cmd == "report":
        return cmd_report(args)
    raise SystemExit("nothing to do: pass a command (plan | measure "
                     "| fit | report) or --selftest")


if __name__ == "__main__":
    sys.exit(main())
