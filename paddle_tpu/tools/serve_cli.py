"""Online inference server CLI.

    python -m paddle_tpu.tools.serve_cli --model_dir=./inference_model \
        --port=8500 --max_batch=32 --max_wait_ms=5 --queue_size=64 \
        --batch_buckets=1,2,4,8,16

Serves a `fluid.io.save_inference_model` export over HTTP (see
docs/SERVING.md for the request format, knobs and /metrics).  SIGINT /
SIGTERM drain gracefully: admission stops, queued requests are
answered, then the listener closes.

`--selftest` builds a tiny classifier in-process, starts the server on
an ephemeral port, round-trips one request, scrapes /metrics and
drains — the smoke-test entry point (scripts/smoke.sh, scripts/ci.sh).
"""

import argparse
import json
import signal
import sys
import threading


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_serve")
    p.add_argument("--model_dir", default=None,
                   help="save_inference_model export directory")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500,
                   help="0 picks an ephemeral port")
    p.add_argument("--max_batch", type=int, default=32,
                   help="sample-row budget per device launch")
    p.add_argument("--max_wait_ms", type=float, default=5.0,
                   help="micro-batch assembly window")
    p.add_argument("--queue_size", type=int, default=64,
                   help="admission-queue bound (full => 429)")
    p.add_argument("--timeout_ms", type=float, default=None,
                   help="default per-request deadline")
    p.add_argument("--batch_buckets", default=None,
                   help="comma list of batch buckets to pad/compile "
                        "(default: export hints, else 1,2,4,...,64)")
    p.add_argument("--token_bucket", type=int, default=None,
                   help="flat token-length multiple for ragged feeds")
    p.add_argument("--no_warmup", action="store_true",
                   help="skip pre-compiling the buckets at startup")
    p.add_argument("--slo_ms", type=float, default=None,
                   help="latency objective: publish slo_burn_rate in "
                        "/metrics and /healthz (docs/SERVING.md)")
    p.add_argument("--slo_target", type=float, default=0.99,
                   help="fraction of requests that must answer "
                        "within --slo_ms")
    p.add_argument("--model_name", default="default",
                   help="model label on the slo_burn_rate gauge")
    p.add_argument("--tail_slow_ms", type=float, default=None,
                   help="keep the full span tree of requests slower "
                        "than this (default: --slo_ms) or answered "
                        ">=500 — GET /debug/tail, obs_dump --tail")
    p.add_argument("--tail_capacity", type=int, default=64,
                   help="tail-capture ring bound")
    p.add_argument("--access_log", default=None,
                   help="opt-in JSONL access log path (request_id, "
                        "trace_id, status, latency_ms, batch, bucket)")
    p.add_argument("--selftest", action="store_true",
                   help="serve a built-in tiny model, fire one "
                        "request, scrape /metrics, drain, exit")
    return p.parse_args(argv)


def _engine_config(args):
    from paddle_tpu.serving import EngineConfig

    if args.batch_buckets is None and args.token_bucket is None:
        return None  # defer to export hints / defaults
    kw = {}
    if args.batch_buckets is not None:
        kw["batch_buckets"] = [int(b) for b in
                               args.batch_buckets.split(",")]
    if args.token_bucket is not None:
        kw["token_bucket"] = args.token_bucket
    return EngineConfig(**kw)


def _serve(engine, args, ready=None):
    from paddle_tpu.serving import InferenceServer, ServerConfig

    server = InferenceServer(engine, ServerConfig(
        host=args.host, port=args.port, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, queue_size=args.queue_size,
        default_timeout_ms=args.timeout_ms,
        warmup=not args.no_warmup, slo_ms=args.slo_ms,
        slo_target=args.slo_target, model_name=args.model_name,
        tail_slow_ms=args.tail_slow_ms,
        tail_capacity=args.tail_capacity,
        access_log=args.access_log))
    server.start()
    host, port = server.address
    print("[serve] listening on http://%s:%d (feeds=%s fetches=%s "
          "buckets=%s)" % (host, port, engine.feed_names,
                           engine.fetch_names,
                           engine.config.batch_buckets), flush=True)
    if ready is not None:
        ready(server)
    return server


def _install_drain_handlers(server, done):
    def drain(signum, frame):
        print("[serve] signal %d: draining ..." % signum, flush=True)
        threading.Thread(target=lambda: (server.shutdown(),
                                         done.set()),
                         daemon=True).start()

    signal.signal(signal.SIGINT, drain)
    signal.signal(signal.SIGTERM, drain)


def _selftest_model(tmpdir):
    """Export a tiny startup-initialized classifier: deterministic
    enough for a round-trip check, cheap enough for CI."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[16], dtype="float32")
        hidden = fluid.layers.fc(input=img, size=8, act="tanh")
        probs = fluid.layers.fc(input=hidden, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(Scope()):
        exe.run(startup)
        fluid_io.save_inference_model(
            tmpdir, ["img"], [probs], exe, main_program=main,
            bucket_hints={"batch_buckets": [1, 2, 4]})
    return tmpdir


def _selftest(args):
    import http.client
    import tempfile

    from paddle_tpu.serving import InferenceEngine

    tmpdir = tempfile.mkdtemp(prefix="paddle_serve_selftest_")
    _selftest_model(tmpdir)
    engine = InferenceEngine.from_saved_model(tmpdir)
    args.port = 0
    server = _serve(engine, args)
    host, port = server.address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        body = json.dumps({"inputs": {"img": [[0.1] * 16, [0.9] * 16]}})
        conn.request("POST", "/v1/infer", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        assert resp.status == 200, (resp.status, payload)
        probs = payload["outputs"][engine.fetch_names[0]]
        assert len(probs) == 2 and len(probs[0]) == 4, probs
        assert all(abs(sum(row) - 1.0) < 1e-3 for row in probs), probs
        conn.request("GET", "/metrics", headers={})
        metrics_text = conn.getresponse().read().decode()
        assert "serving_responses_total 1" in metrics_text, metrics_text
        assert "serving_compile_cache_hit_total" in metrics_text
        conn.close()
    finally:
        server.shutdown()
    print("[serve] selftest green: 1 request served, metrics scraped, "
          "drained cleanly", flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return _selftest(args)
    if not args.model_dir:
        raise SystemExit("--model_dir is required (or --selftest)")

    from paddle_tpu.serving import InferenceEngine

    engine = InferenceEngine.from_saved_model(
        args.model_dir, config=_engine_config(args))
    server = _serve(engine, args)
    done = threading.Event()
    _install_drain_handlers(server, done)
    done.wait()
    print("[serve] drained, bye", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
