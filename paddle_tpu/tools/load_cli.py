"""Load CLI ("pload"): load generation + traffic replay over
`paddle_tpu.obs.load`, with coordinated-omission-safe latency truth
and the serving tail-latency gate hookup.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.load_cli --selftest

    # open-loop Poisson load against a live server (the honest tail):
    pload run --url http://127.0.0.1:8500 --rate 200 --n 2000 \
        --mix 1:6,4:3,8:1 --slo-ms 50

    # closed-loop capacity probe (N workers, think time):
    pload run --url ... --mode closed --workers 16 --think-ms 5 --n 2000

    # replay a recorded access log at 4x speed, original gaps:
    pload replay --url ... --log access.jsonl --speed 4

    # land the run in perf history for `pperf gate --latency-tolerance`:
    pload run --url ... --rate 100 --n 1000 --slo-ms 50 \
        --history perf_history.jsonl

`--selftest` certifies the harness end to end on a loopback server
(docs/SERVING.md has the runbook):

  1. **coordinated omission, demonstrated** — an injected engine stall
     must inflate the OPEN-loop p99 (requests measured from their
     scheduled send time keep accruing latency through the stall) ...
  2. ... while the same stall stays HIDDEN from the closed-loop p99
     (the single worker is itself blocked, so only one request
     observes it): the open/closed gap IS the omission error;
  3. **tail join** — the slowest open-loop request's request_id must
     resolve to a span tree in the server's /debug/tail ring, and the
     /metrics exemplars must parse (the "p99 is bad -> why" loop);
  4. **replay fidelity** — replaying the run's own access-log JSONL
     must reproduce its request count and bucket mix exactly;
  5. **gate round-trip** — a `latency` blob must flow through
     perf_history.jsonl into `pperf gate --latency-tolerance`: an
     injected p99 regression fails the gate naming the percentile,
     and the same history passes with the flag omitted (opt-in).
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pload")
    p.add_argument("cmd", nargs="?", choices=["run", "replay"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="loopback open-vs-closed omission proof, tail "
                        "join, replay fidelity, latency gate")
    p.add_argument("--url", default="http://127.0.0.1:8500",
                   help="server base URL (POST <url>/v1/infer)")
    p.add_argument("--mode", choices=["open", "closed"], default="open",
                   help="arrival discipline: open = scheduled "
                        "arrivals, latency from the schedule "
                        "(omission-safe); closed = N looping workers")
    p.add_argument("--arrival", choices=["poisson", "uniform"],
                   default="poisson", help="open-loop gap law")
    p.add_argument("--rate", type=float, default=100.0,
                   help="open-loop offered req/s (base rate before "
                        "--phases/--ramp-s)")
    p.add_argument("--n", type=int, default=None,
                   help="total requests (or bound by --duration)")
    p.add_argument("--duration", type=float, default=None,
                   help="run length in seconds")
    p.add_argument("--workers", type=int, default=4,
                   help="closed-loop concurrent workers")
    p.add_argument("--think-ms", type=float, default=0.0,
                   help="closed-loop pause between a worker's requests")
    p.add_argument("--mix", default="1",
                   help="weighted batch-size mix, e.g. 1:6,4:3,8:1 "
                        "(bare sizes weigh equally)")
    p.add_argument("--phases", default=None,
                   help="burst phases t:rate,..., e.g. 5:400,6:100 — "
                        "from t=5s offer 400 req/s, from 6s 100")
    p.add_argument("--ramp-s", type=float, default=0.0,
                   help="linear rate ramp-in over the first N seconds")
    p.add_argument("--slo-ms", type=float, default=None,
                   help="latency objective; report carries attainment")
    p.add_argument("--speed", type=float, default=1.0,
                   help="replay: time-compression multiplier over the "
                        "log's original inter-arrival gaps")
    p.add_argument("--log", default=None,
                   help="replay: server access-log JSONL "
                        "(ServerConfig.access_log output)")
    p.add_argument("--timeout-ms", type=float, default=None,
                   help="per-request timeout_ms field (server-side "
                        "deadline -> 504)")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule/mix RNG seed (schedules are "
                        "deterministic under it)")
    p.add_argument("--max-inflight", type=int, default=32,
                   help="open-loop sender pool: above this many "
                        "unanswered requests, further arrivals queue "
                        "(and keep accruing scheduled-time latency)")
    p.add_argument("--feed", default="img",
                   help="feed tensor name for the generated payload")
    p.add_argument("--dim", type=int, default=16,
                   help="per-sample feature width of the feed")
    p.add_argument("--worst", type=int, default=5,
                   help="worst-K requests to report and tail-join")
    p.add_argument("--no-join", action="store_true",
                   help="skip the /debug/tail + /metrics joins")
    p.add_argument("--report", default=None,
                   help="write the full JSON report here")
    p.add_argument("--history", default=None,
                   help="append a latency-blob record to this perf "
                        "history (pperf gate --latency-tolerance)")
    p.add_argument("--json", action="store_true",
                   help="print the report as JSON instead of text")
    return p.parse_args(argv)


def _run_report(args, target, schedule):
    from paddle_tpu.obs import load as obs_load

    payload_fn = obs_load.vector_payload(args.feed, args.dim,
                                         timeout_ms=args.timeout_ms)
    if args.mode == "open":
        report = obs_load.run_open_loop(
            target, schedule, payload_fn, slo_ms=args.slo_ms,
            max_inflight=args.max_inflight)
    else:
        report = obs_load.run_closed_loop(
            target, payload_fn, workers=args.workers, n=args.n,
            duration_s=args.duration, think_ms=args.think_ms,
            mix=obs_load.TrafficMix.parse(args.mix), seed=args.seed,
            slo_ms=args.slo_ms)
    if not args.no_join:
        try:
            obs_load.join_tail(report, target.get("/debug/tail"))
            obs_load.join_exemplars(report, target.get("/metrics"))
        except (OSError, ValueError):
            pass  # a server without debug endpoints still measures
    return report


def _emit(args, report):
    from paddle_tpu.obs import load as obs_load
    from paddle_tpu.obs import perf as obs_perf

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
    if args.history:
        blob = obs_load.latency_blob(report)
        record = {
            "metric": "pload_%s_rps" % report["mode"],
            "value": report["achieved_rps"],
            "unit": "req/s",
            "platform": "cpu",
            "latency": blob,
        }
        obs_perf.append_history(record, args.history, leg="pload")
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(obs_load.format_report(report))
    slo = report.get("slo")
    if slo is not None and slo["violations"] and \
            slo["attainment"] < 0.99:
        return 1
    return 0


def cmd_run(args):
    from paddle_tpu.obs import load as obs_load

    target = obs_load.HttpTarget(args.url)
    schedule = None
    if args.mode == "open":
        schedule = obs_load.build_schedule(
            args.rate, n=args.n, duration_s=args.duration,
            arrival=args.arrival,
            mix=obs_load.TrafficMix.parse(args.mix), seed=args.seed,
            phases=obs_load.parse_phases(args.phases),
            ramp_s=args.ramp_s)
    return _emit(args, _run_report(args, target, schedule))


def cmd_replay(args):
    from paddle_tpu.obs import load as obs_load

    if not args.log:
        raise SystemExit("replay needs --log <access log JSONL>")
    entries = obs_load.load_access_log(args.log)
    if not entries:
        raise SystemExit("no replayable entries in %s" % args.log)
    schedule = obs_load.replay_schedule(entries, speed=args.speed)
    target = obs_load.HttpTarget(args.url)
    args.mode = "open"  # replay is open-loop by definition
    return _emit(args, _run_report(args, target, schedule))


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

class _StallEngine:
    """Delegating engine wrapper with a one-shot armable stall: the
    Nth `run()` call after `arm()` sleeps `stall_s` first.  One-shot
    on purpose — a periodic stall would hit enough closed-loop
    requests to surface in that p99 too, and the whole point of the
    selftest is the asymmetry."""

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()
        self._remaining = None
        self._stall_s = 0.0

    def arm(self, after_calls, stall_s):
        with self._lock:
            self._remaining = int(after_calls)
            self._stall_s = float(stall_s)

    def run(self, feeds, timings=None):
        stall = 0.0
        with self._lock:
            if self._remaining is not None:
                self._remaining -= 1
                if self._remaining <= 0:
                    stall = self._stall_s
                    self._remaining = None
        if stall:
            time.sleep(stall)
        return self._inner.run(feeds, timings=timings)

    # everything the batcher/server touches delegates
    def warmup(self):
        return self._inner.warmup()

    def batch_size(self, feeds):
        return self._inner.batch_size(feeds)

    @property
    def feed_names(self):
        return self._inner.feed_names

    @property
    def fetch_names(self):
        return self._inner.fetch_names

    @property
    def _feed_meta(self):
        return self._inner._feed_meta

    @property
    def config(self):
        return self._inner.config

    @property
    def metrics(self):
        return self._inner.metrics

    @metrics.setter
    def metrics(self, value):
        self._inner.metrics = value


def _selftest_omission(workdir):
    """Legs 1-3: the same injected stall must be LOUD in the open-loop
    p99 and QUIET in the closed-loop p99, and the slowest open-loop
    request must join to a /debug/tail span tree."""
    from paddle_tpu.obs import load as obs_load
    from paddle_tpu.serving import InferenceServer, ServerConfig

    access_log = os.path.join(workdir, "access.jsonl")
    engine = _StallEngine(obs_load.build_tiny_engine(
        dim=8, classes=3, buckets=(1, 2, 4, 8)))
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=8, max_wait_ms=1.0, queue_size=64,
        warmup=False, slo_ms=100.0, model_name="pload-selftest",
        tail_slow_ms=100.0, tail_capacity=128,
        access_log=access_log)).start()
    stall_s = 0.3
    try:
        host, port = server.address
        target = obs_load.HttpTarget("http://%s:%d" % (host, port))
        payload_fn = obs_load.vector_payload("img", 8)
        mix = obs_load.TrafficMix.parse("1:2,2:1,4:1")

        # leg 1: open loop, 200 req @ 100/s.  ~30 arrivals are
        # scheduled inside the 300ms stall; each is measured from its
        # schedule slot, so the stall floods the upper percentiles.
        engine.arm(after_calls=10, stall_s=stall_s)
        schedule = obs_load.build_schedule(
            100.0, n=200, arrival="poisson", mix=mix, seed=7)
        open_report = obs_load.run_open_loop(
            target, schedule, payload_fn, slo_ms=100.0,
            max_inflight=64)
        open_p99 = open_report["percentiles_ms"]["p99_ms"]
        assert open_p99 >= 100.0, \
            "open-loop p99 %.2fms did not surface a %dms stall" \
            % (open_p99, stall_s * 1e3)

        # leg 2: closed loop, 1 worker, same stall re-armed.  The
        # worker is blocked DURING the stall, so exactly one request
        # observes it; the p99 (2nd-worst of 200) stays clean — the
        # coordinated-omission trap, reproduced on demand.
        engine.arm(after_calls=10, stall_s=stall_s)
        closed_report = obs_load.run_closed_loop(
            target, payload_fn, workers=1, n=200, mix=mix, seed=7,
            slo_ms=100.0)
        closed_p99 = closed_report["percentiles_ms"]["p99_ms"]
        assert closed_report["max_ms"] >= stall_s * 1e3 * 0.8, \
            "closed-loop run never hit the armed stall (max %.2fms)" \
            % closed_report["max_ms"]
        assert closed_p99 < 100.0 and closed_p99 < open_p99 / 2.0, \
            "closed-loop p99 %.2fms did not hide the stall open-loop " \
            "p99 %.2fms exposed" % (closed_p99, open_p99)

        # leg 3: the debugging loop — worst request -> span tree
        joined = obs_load.join_tail(open_report,
                                    target.get("/debug/tail"))
        assert joined >= 1, "no worst request resolved in /debug/tail"
        worst = open_report["worst"][0]
        assert worst.get("tail") and worst["tail"].get("spans"), \
            "slowest request %s carried no span tree" \
            % worst["request_id"]
        metrics_text = target.get("/metrics")
        assert obs_load.parse_exemplars(metrics_text), \
            "/metrics exposed no parsable exemplars"
        obs_load.join_exemplars(open_report, metrics_text)
        # satellite check: the stall backlog must have left a nonzero
        # queue-depth high-watermark for the scrape to carry out
        peak = [l for l in metrics_text.splitlines()
                if l.startswith("serving_queue_depth_peak")]
        assert peak and float(peak[0].split()[-1]) > 0, \
            "queue_depth_peak watermark missing/zero: %r" % peak
    finally:
        server.shutdown()
    return open_report, closed_report, open_p99, closed_p99, access_log


def _selftest_replay(workdir, access_log):
    """Leg 4: replaying the recorded access log must reproduce its
    request count and bucket mix exactly (batch sizes come from the
    log lines, not from a sampled mix)."""
    from paddle_tpu.obs import load as obs_load
    from paddle_tpu.serving import InferenceServer, ServerConfig

    entries = obs_load.load_access_log(access_log)
    assert entries, "server wrote no access log"
    want_buckets = {}
    for e in entries:
        b = "b%d" % max(1, int(e.get("batch") or 1))
        want_buckets[b] = want_buckets.get(b, 0) + 1

    engine = obs_load.build_tiny_engine(dim=8, classes=3,
                                        buckets=(1, 2, 4, 8))
    server = InferenceServer(engine, ServerConfig(
        port=0, max_batch=8, max_wait_ms=1.0, queue_size=256,
        warmup=False, model_name="pload-replay")).start()
    try:
        host, port = server.address
        target = obs_load.HttpTarget("http://%s:%d" % (host, port))
        schedule = obs_load.replay_schedule(entries, speed=20.0)
        report = obs_load.run_open_loop(
            target, schedule, obs_load.vector_payload("img", 8),
            max_inflight=64)
    finally:
        server.shutdown()
    assert report["n"] == len(entries), \
        "replay answered %d of %d logged requests" \
        % (report["n"], len(entries))
    got_buckets = {b: st["n"] for b, st in report["by_bucket"].items()}
    assert got_buckets == want_buckets, \
        "replay bucket mix %r != recorded %r" % (got_buckets,
                                                 want_buckets)
    statuses = set(report["by_status"])
    assert statuses == {"200"}, \
        "replay saw non-200s: %r" % report["by_status"]
    return report


def _selftest_gate(workdir, open_report):
    """Leg 5: the latency blob's CI story — baseline history + a
    doubled-p99 candidate must FAIL `pperf gate --latency-tolerance`
    naming the percentile, and PASS with the flag omitted."""
    from paddle_tpu.obs import load as obs_load
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tools import perf_cli

    path = os.path.join(workdir, "perf_history.jsonl")
    blob = obs_load.latency_blob(open_report)

    def record(latency):
        return {"metric": "serving_slo_openloop_rps",
                "value": open_report["achieved_rps"],
                "unit": "req/s", "platform": "cpu",
                "latency": latency}

    ts = 1_700_000_000.0
    for i in range(5):
        norm = obs_perf.append_history(record(dict(blob)), path,
                                       leg="serving-slo", ts=ts + i)
        assert norm and norm["latency"].get("p99_ms") == \
            blob["p99_ms"], "latency blob did not survive " \
            "normalize_record: %r" % (norm,)
    regressed = dict(blob)
    for key in ("p50_ms", "p90_ms", "p99_ms", "p99_9_ms"):
        regressed[key] = round(blob[key] * 3.0, 3)
    obs_perf.append_history(record(regressed), path, leg="serving-slo",
                            ts=ts + 5)

    res = obs_perf.gate_history(obs_perf.load_history(path),
                                latency_tolerance=0.25)
    assert not res.ok and res.failures[0]["kind"] == "latency", \
        res.to_dict()
    assert "p99" in res.failures[0]["why"], res.to_dict()
    rc = perf_cli.main(["gate", "--history", path,
                        "--latency-tolerance", "0.25"])
    assert rc == 1, "pperf gate exit %r for a 3x tail regression" % rc
    # opt-in: the same history passes when latency is not gated
    rc = perf_cli.main(["gate", "--history", path])
    assert rc == 0, "latency gate fired without --latency-tolerance"
    return res.failures[0]["why"]


def selftest(args):
    import shutil

    # never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="paddle_pload_")
    try:
        (open_report, closed_report, open_p99, closed_p99,
         access_log) = _selftest_omission(workdir)
        replay_report = _selftest_replay(workdir, access_log)
        gate_why = _selftest_gate(workdir, open_report)
    finally:
        # ci.sh/smoke.sh run this every time: don't stack /tmp dirs
        shutil.rmtree(workdir, ignore_errors=True)

    print("[pload] selftest green: injected stall -> open-loop p99 "
          "%.1fms vs closed-loop p99 %.1fms (the coordinated-omission "
          "gap), worst request joined to its /debug/tail span tree, "
          "replay reproduced %d requests + bucket mix, latency gate: "
          "%s" % (open_p99, closed_p99, replay_report["n"], gate_why),
          flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "run":
        return cmd_run(args)
    if args.cmd == "replay":
        return cmd_replay(args)
    raise SystemExit("nothing to do: pass a command (run | replay) or "
                     "--selftest")


if __name__ == "__main__":
    sys.exit(main())
