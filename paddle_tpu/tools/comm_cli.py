"""pcomm — collective/communication observability CLI
(paddle_tpu.obs.comm).

    # per-bucket comm truth + the overlap-efficiency split on a
    # simulated dp=8 mesh (JAX_PLATFORMS=cpu; virtual devices are
    # provisioned automatically)
    pcomm report [--dp 8] [--bucket-kb 24] [--reps 3] \\
                 [--trace-out comm_trace.json] \\
                 [--calibration-out comm_cal.json] [--json]

    # cross-host merge: pull every live /obsspan/* window from the
    # master's lease store (workers push them via
    # FleetReporter(span_window=N)), estimate per-host clock offsets
    # over the same store, emit ONE Perfetto trace with a process
    # track per host on a common timebase
    pcomm merge --master host:port --out merged_trace.json
    pcomm merge --windows w1.json w2.json --out merged_trace.json

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh)
    pcomm --selftest

`--selftest` proves the loop on the 8-device simulated mesh: the
traced bucket schedule nests one `comm/bucket` span per bucket in
last-produced-first order with byte labels; `overlap_report` splits
step wall into exposed-vs-hidden comm against the reduction-elided
twin (and a gspmd-fallback trainer is refused WITHOUT an exposed_s);
a real master lease store carries span windows + the NTP-style clock
exchange (a ClockResponder with 0.5s injected skew is recovered and
the merged trace re-bases by it, validating as a Chrome trace); the
drift calibration blob round-trips through
`tune.fit.load_comm_calibration` into a fitted comm coefficient
(same-platform-class only); and `pperf gate --comm-tolerance` passes
±2% exposed-comm noise while failing an injected 20% regression.
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pcomm")
    p.add_argument("cmd", nargs="?", choices=["report", "merge"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="spans + overlap split + cross-host merge + "
                        "calibration round-trip + comm gate "
                        "certification (CPU, 8 virtual devices)")
    # report
    p.add_argument("--dp", type=int, default=8,
                   help="report: data-parallel mesh width")
    p.add_argument("--bucket-kb", type=int, default=24,
                   help="report: ring-allreduce bucket size in KiB "
                        "(small enough that the probe MLP fills "
                        "several buckets)")
    p.add_argument("--reps", type=int, default=3,
                   help="report: timed repetitions per measurement")
    p.add_argument("--trace-out", default=None,
                   help="report: write this process's span trace "
                        "here (Chrome trace JSON)")
    p.add_argument("--calibration-out", default=None,
                   help="report: write the measured/predicted ring "
                        "blob `ptune fit --comm-calibration` eats")
    # merge
    p.add_argument("--master", default=None,
                   help="merge: master host:port whose /obsspan/* "
                        "windows to pull")
    p.add_argument("--windows", nargs="*", default=None,
                   help="merge: span-window JSON files (offline "
                        "merge; skips the clock exchange)")
    p.add_argument("--out", default=None,
                   help="merge: merged trace path (default "
                        "comm_merged_trace.json)")
    p.add_argument("--no-clock-sync", action="store_true",
                   help="merge: skip the clock-offset exchange (rely "
                        "on host wall clocks)")
    p.add_argument("--clock-reps", type=int, default=3,
                   help="merge: ping/pong exchanges per host")
    p.add_argument("--clock-timeout", type=float, default=3.0,
                   help="merge: seconds to wait for each pong")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    return p.parse_args(argv)


def _ensure_virtual_devices(n=8):
    """Provision n virtual CPU devices BEFORE jax imports — the report
    and selftest paths need a real multi-device mesh with no
    accelerator attached."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d"
            % int(n)).strip()


# ---------------------------------------------------------------------------
# probe model (the test_spmd MLP recipe: big first layer, small head,
# so a KB-scale bucket cap yields several buckets in reduce order)
# ---------------------------------------------------------------------------

BATCH, DIM, HIDDEN, CLASSES = 16, 8, 1024, 4


def _build_mlp():
    import paddle_tpu.fluid as fluid

    fluid.framework.reset_unique_name()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[BATCH, DIM],
                              dtype="float32",
                              append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[BATCH, 1],
                                  dtype="int64",
                                  append_batch_size=False)
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(avg)
    return main, startup, avg


def _feeds(step=0):
    import numpy as np

    rs = np.random.RandomState(100 + step)
    return {
        "x": rs.rand(BATCH, DIM).astype(np.float32),
        "label": rs.randint(0, CLASSES,
                            size=(BATCH, 1)).astype(np.int64),
    }


def _make_trainer(mesh, bucket_bytes):
    from paddle_tpu.spmd import SpmdTrainer

    main, startup, avg = _build_mlp()
    return SpmdTrainer(main, startup, feed_names=["x", "label"],
                       fetch_names=[avg.name], mesh=mesh,
                       bucket_bytes=bucket_bytes,
                       use_pcache=False).init()


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _render_report(rep, bucket_report, drift):
    lines = []
    if not rep["supported"]:
        lines.append("overlap NOT measured: step_mode=%s (%s)"
                     % (rep["step_mode"],
                        rep["overlap_fallback_reason"]))
        return "\n".join(lines)
    lines.append("per-bucket ring truth (allreduce over %s, %d-way):"
                 % (bucket_report["axis"], bucket_report["n"]))
    lines.append("  %-7s %10s %10s %9s %9s %7s"
                 % ("bucket", "bytes", "wire", "pred ms",
                    "meas ms", "ratio"))
    for r in bucket_report["buckets"]:
        lines.append("  %-7d %10d %10d %9.3f %9.3f %7s"
                     % (r["bucket"], r["bytes"], r["wire_bytes"],
                        r["pred_s"] * 1e3, r["measured_s"] * 1e3,
                        "%.2f" % r["ratio"] if r["ratio"] else "-"))
    lines.append("overlap split over %d rep(s):" % rep["reps"])
    lines.append("  step %.3f ms = compute %.3f ms + exposed comm "
                 "%.3f ms" % (rep["step_s"] * 1e3,
                              rep["compute_s"] * 1e3,
                              rep["exposed_s"] * 1e3))
    eff = rep["overlap_efficiency"]
    lines.append("  standalone comm %.3f ms -> hidden %.3f ms "
                 "(overlap efficiency %s)"
                 % (rep["comm_s"] * 1e3, rep["hidden_s"] * 1e3,
                    "%.1f%%" % (eff * 100) if eff is not None
                    else "n/a"))
    if drift["median_ratio"]:
        lines.append("analytic-floor drift: median measured/pred "
                     "%.2f over %d bucket(s)"
                     % (drift["median_ratio"], drift["n"]))
    return "\n".join(lines)


def cmd_report(args):
    from paddle_tpu.obs import comm as obs_comm
    from paddle_tpu.obs import trace as obs_trace
    from paddle_tpu.parallel import make_mesh

    obs_trace.enable()
    mesh = make_mesh(n_devices=args.dp, dp=args.dp)
    trainer = _make_trainer(mesh, args.bucket_kb << 10)
    feeds = _feeds(0)
    trainer.step(feeds)                 # trace the bucket schedule
    bucket_report = obs_comm.measure_trainer_comm(trainer,
                                                  reps=args.reps)
    rep = obs_comm.overlap_report(trainer, feeds, reps=args.reps,
                                  bucket_report=bucket_report)
    drift = obs_comm.drift_report(bucket_report)
    if args.json:
        out = dict(rep)
        out.pop("spans", None)
        print(json.dumps({"overlap": out, "drift": drift},
                         sort_keys=True))
    else:
        print("[pcomm] mlp probe, dp=%d, bucket %d KiB:"
              % (args.dp, args.bucket_kb))
        print(_render_report(rep, bucket_report, drift))
    if args.calibration_out:
        blob = obs_comm.calibration_blob(bucket_report,
                                         model="pcomm-mlp")
        if blob is None:
            print("[pcomm] nothing measured — no calibration "
                  "written", file=sys.stderr)
            return 2
        obs_comm.save_calibration(blob, args.calibration_out)
        if not args.json:
            print("[pcomm] calibration written: %s (comm_ratio %.3f "
                  "over %d bucket(s)) — feed it to `ptune fit "
                  "--comm-calibration`"
                  % (args.calibration_out, blob["comm_ratio"],
                     blob["n"]))
    if args.trace_out:
        obs_trace.export_chrome_trace(args.trace_out)
        if not args.json:
            print("[pcomm] span trace written: %s" % args.trace_out)
    return 0 if rep["supported"] else 2


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------

def cmd_merge(args):
    from paddle_tpu.obs import comm as obs_comm

    offsets = {}
    if args.windows:
        windows = {}
        for path in args.windows:
            with open(path) as f:
                payload = json.load(f)
            windows[payload.get("host") or path] = payload
    elif args.master:
        windows = obs_comm.collect_span_windows(args.master)
        if windows and not args.no_clock_sync:
            offsets = obs_comm.estimate_clock_offsets(
                args.master, sorted(windows), reps=args.clock_reps,
                timeout_s=args.clock_timeout)
    else:
        raise SystemExit("merge needs --master or --windows")
    merged = obs_comm.merge_windows(windows, offsets)
    out = args.out or "comm_merged_trace.json"
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, sort_keys=True)
    os.replace(tmp, out)
    hosts = merged["otherData"]["hosts"]
    if args.json:
        print(json.dumps({"out": out, "hosts": hosts,
                          "events": len(merged["traceEvents"]),
                          "clock_offsets":
                              merged["otherData"]["clock_offsets"]},
                         sort_keys=True))
    else:
        print("[pcomm] merged %d host track(s) (%s) into %s (%d "
              "events); clock offsets: %s"
              % (len(hosts), ", ".join(hosts) or "none", out,
                 len(merged["traceEvents"]),
                 {h: ("%.3fs" % o if o is not None else "?")
                  for h, o in
                  merged["otherData"]["clock_offsets"].items()}
                 or "skipped"))
    return 0 if hosts else 2


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _selftest_spans_and_overlap(workdir):
    """Legs 1-2: traced schedule shape + the overlap-efficiency split
    (and the fallback trainer refused without an exposed_s)."""
    from paddle_tpu.obs import comm as obs_comm
    from paddle_tpu.obs import flight as obs_flight
    from paddle_tpu.obs import trace as obs_trace
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.spmd import overlap as spmd_overlap

    obs_trace.enable()
    mesh = make_mesh(n_devices=8, dp=8)
    trainer = _make_trainer(mesh, 24 << 10)
    feeds = _feeds(0)
    trainer.step(feeds)
    assert trainer.step_mode == "overlap-dp", trainer.step_mode

    # schedule shape: >= 2 buckets, flattened names in EXACTLY the
    # last-produced-first (DDP) order the program's seam defines
    sched = obs_comm.last_schedule()
    assert sched and sched["collective"] == "allreduce", sched
    assert sched["n_buckets"] >= 2, sched
    _split, grad_order = spmd_overlap._split_point(
        list(trainer.main_program.desc.block(0).ops))
    flat = [n for b in sched["buckets"] for n in b["names"]]
    want = [g for g in reversed(grad_order) if g in set(flat)]
    assert flat == want, (flat, want)

    # trace nesting: one parent, one comm/bucket span per bucket with
    # byte labels, bracketed by launch/complete instants, plus the
    # reduce seam marker from the overlap schedule
    evs = obs_trace.events()
    parents = [e for e in evs
               if e.get("name") == "comm/bucketed_allreduce"]
    assert parents and parents[0]["args"]["n_buckets"] \
        == sched["n_buckets"], parents
    bspans = [e for e in evs if e.get("name") == "comm/bucket"]
    assert len(bspans) == sched["n_buckets"], evs
    for e in bspans:
        assert e["args"]["bytes"] > 0 and e["args"]["names"] >= 1, e
    assert [e["args"]["first"] for e in bspans] \
        == [b["names"][0] for b in sched["buckets"]]
    launches = [e for e in evs if e.get("name") == "comm/bucket_launch"]
    completes = [e for e in evs
                 if e.get("name") == "comm/bucket_complete"]
    assert len(launches) == len(completes) == sched["n_buckets"]
    assert any(e.get("name") == "comm/reduce_seam" for e in evs)

    # overlap truth: the split is internally consistent and published
    bucket_report = obs_comm.measure_trainer_comm(trainer, reps=2)
    assert bucket_report and len(bucket_report["buckets"]) >= 2
    for r in bucket_report["buckets"]:
        assert r["measured_s"] > 0 and r["pred_s"] > 0, r
    rep = obs_comm.overlap_report(trainer, feeds, reps=2,
                                  bucket_report=bucket_report)
    assert rep["supported"] and rep["step_s"] > 0 \
        and rep["compute_s"] > 0 and rep["comm_s"] > 0, rep
    assert rep["exposed_s"] >= 0 \
        and 0.0 <= rep["overlap_efficiency"] <= 1.0, rep
    assert abs(rep["exposed_s"] + rep["hidden_s"] - rep["comm_s"]) \
        < 1e-9 or rep["exposed_s"] >= rep["comm_s"], rep

    # satellite: the trainer stamped this worker's identity for any
    # future flight bundle; a dump carries it
    ctx = obs_flight.host_context()
    assert ctx.get("process_index") == 0 \
        and ctx.get("mesh_axes", {}).get("dp") == 8 \
        and ctx.get("plan_fingerprint") \
        == trainer.plan.fingerprint(), ctx
    recorder = obs_flight.install(out_dir=workdir, capacity=8)
    try:
        bundle = recorder.dump(reason="pcomm-selftest")
    finally:
        obs_flight.uninstall()
    with open(bundle) as f:
        doc = json.load(f)
    assert doc["host_context"]["plan_fingerprint"] \
        == trainer.plan.fingerprint(), doc.get("host_context")

    # fallback trainer (dp=4,mp=2 mesh): overlap refused, and the
    # report carries NO exposed_s — it can never enter the overlap
    # baseline
    mesh2 = make_mesh(n_devices=8, dp=4, mp=2)
    trainer2 = _make_trainer(mesh2, 24 << 10)
    trainer2.step(feeds)
    assert trainer2.step_mode == "gspmd" \
        and trainer2.overlap_fallback_reason
    rep2 = obs_comm.overlap_report(trainer2, feeds, reps=2)
    assert not rep2["supported"] and "exposed_s" not in rep2 \
        and rep2["overlap_fallback_reason"], rep2
    return rep, bucket_report


def _selftest_calibration(workdir, bucket_report):
    """Leg 3: drift blob -> tune.fit comm coefficient, same-class
    only."""
    from paddle_tpu.obs import comm as obs_comm
    from paddle_tpu.tune import fit as tune_fit

    blob = obs_comm.calibration_blob(bucket_report, model="pcomm-mlp")
    assert blob and blob["n"] >= 2 and blob["comm_ratio"] > 0, blob
    cal_path = os.path.join(workdir, "comm_cal.json")
    obs_comm.save_calibration(blob, cal_path)
    pairs = tune_fit.load_comm_calibration(cal_path)
    assert len(pairs) == blob["n"] \
        and pairs[0]["platform_class"] == blob["platform_class"]
    cal = tune_fit.fit_calibration([], comm_pairs=pairs)
    assert abs(cal.coef["comm"] - blob["comm_ratio"]) < 1e-9, \
        (cal.coef, blob["comm_ratio"])
    # same-platform-class discipline: training on a DIFFERENT class
    # keeps the analytic prior instead of ingesting these pairs
    foreign = [{"leg": "ptune:x", "measured_s": 0.1,
                "meas_compute_s": 0.08, "overhead_s": 0.01,
                "platform_class": "tpu:d8:dp=8"}]
    cal2 = tune_fit.fit_calibration(foreign, comm_pairs=pairs)
    assert cal2.coef["comm"] == 1.0, cal2.coef
    assert "kept analytic" in cal2.note, cal2.note
    # a wrong-kind blob must be refused, not silently skipped
    bad_path = os.path.join(workdir, "not_comm.json")
    with open(bad_path, "w") as f:
        json.dump({"kind": "paddle_tpu.mem_calibration",
                   "pairs": []}, f)
    try:
        tune_fit.load_comm_calibration(bad_path)
        raise AssertionError("wrong-kind blob loaded")
    except ValueError:
        pass
    return blob, cal


def _selftest_merge(workdir):
    """Leg 4: span windows + clock exchange + merged trace over a
    REAL master lease store."""
    from paddle_tpu import native
    from paddle_tpu.obs import comm as obs_comm
    from paddle_tpu.obs import fleet as obs_fleet
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.tools.obs_dump import validate_chrome_trace

    master = native.Master()
    addr = "127.0.0.1:%d" % master.port
    responder = None
    reporter = None
    try:
        # hostA rides the FleetReporter (snapshot + span window in one
        # push); hostB is a bare push with a skewed clock responder
        reporter = obs_fleet.FleetReporter(addr, host="hostA",
                                           interval_s=60.0,
                                           span_window=256)
        assert reporter.push_once() \
            and reporter._span_lease is not None
        assert obs_comm.push_span_window(addr, host="hostB",
                                         limit=256) is not None
        responder = obs_comm.ClockResponder(addr, host="hostB",
                                            poll_s=0.02,
                                            skew_s=0.5).start()
        offsets = obs_comm.estimate_clock_offsets(
            addr, ["hostB"], reps=3, timeout_s=5.0)
        off = offsets["hostB"]
        assert off is not None and abs(off - 0.5) < 0.2, offsets

        windows = obs_comm.collect_span_windows(addr)
        assert {"hostA", "hostB"} <= set(windows), sorted(windows)
        for w in windows.values():
            assert w["events"] and w["epoch_wall"] > 0, w["host"]
        merged = obs_comm.merge_windows(windows, offsets)
        events = validate_chrome_trace(merged)
        names = {e["args"]["name"] for e in events
                 if e.get("name") == "process_name"}
        assert {"hostA", "hostB"} <= names, names
        assert merged["otherData"]["clock_offsets"]["hostB"] == off
        # the offset actually re-bases: hostA's events shift by ~the
        # recovered skew relative to an uncorrected merge
        plain = obs_comm.merge_windows(windows, None)
        pick = [e for e in merged["traceEvents"]
                if e["pid"] == 1 and e["ph"] == "X"][0]
        pick0 = [e for e in plain["traceEvents"]
                 if e["pid"] == 1 and e["ph"] == "X"][0]
        shift_s = (pick["ts"] - pick0["ts"]) / 1e6
        assert abs(shift_s - off) < 0.05, (shift_s, off)

        # satellite: the aggregator publishes per-host snapshot age
        # and retires it when the host's lease dies
        agg = obs_fleet.FleetAggregator()
        assert agg.collect(addr) >= 1
        agg.stragglers()
        age = obs_registry.get_registry().gauge(
            "fleet_snapshot_age_seconds", labelnames=("host",))
        ages = {s["labels"]["host"]: s["value"]
                for s in age.samples()}
        assert "hostA" in ages and ages["hostA"] >= 0, ages
        reporter.stop(unregister=True)
        reporter = None
        agg.collect(addr)
        agg.stragglers()
        assert not any(s["labels"]["host"] == "hostA"
                       for s in age.samples()), age.samples()
        assert "hostA" not in obs_comm.collect_span_windows(addr)
        return len(windows), off, len(events)
    finally:
        if responder is not None:
            responder.stop()
        if reporter is not None:
            reporter.stop(unregister=True)
        master.stop()


def _comm_history(path, regress=False):
    """Six rounds of multichip records with ±2% exposed-comm noise
    (and one gspmd-fallback record that carries no exposed_s — it
    must not drag the overlap baseline); optionally a 20% exposed
    regression as the candidate."""
    from paddle_tpu.obs import perf as obs_perf

    noise = [1.0, 0.99, 1.012, 0.994, 1.009, 0.98]
    base_v, base_e = 512.0, 0.004
    if os.path.exists(path):
        os.remove(path)
    ts = 1_700_000_000.0
    for i, n in enumerate(noise):
        e = base_e * (1.2 if (regress and i == len(noise) - 1) else n)
        obs_perf.append_history(
            {"metric": "mlp_multichip_imgs_per_sec",
             "value": round(base_v * n, 2), "unit": "img/s",
             "step_ms": 31.0, "platform": "cpu",
             "comm": {"measured_s": 0.005,
                      "exposed_s": round(e, 6),
                      "overlap_efficiency": 0.8,
                      "step_mode": "overlap-dp",
                      "plan_fingerprint": "fp0"}},
            path, leg="dp=8", ts=ts + i)
        if i == 2:
            # the fallback run: huge standalone ring, NO exposed_s
            obs_perf.append_history(
                {"metric": "mlp_multichip_imgs_per_sec",
                 "value": round(base_v, 2), "unit": "img/s",
                 "step_ms": 31.0, "platform": "cpu",
                 "comm": {"measured_s": 10.0, "step_mode": "gspmd",
                          "overlap_fallback_reason": "mesh is not "
                          "pure data-parallel"}},
                path, leg="dp=8", ts=ts + i + 0.5)
    return path


def _selftest_gate(workdir):
    """Leg 5: the comm gate discriminates — noise passes, an injected
    exposed-comm regression fails, fallback records don't pollute."""
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tools import perf_cli

    path = _comm_history(os.path.join(workdir, "comm_hist.jsonl"))
    res = obs_perf.gate_history(obs_perf.load_history(path),
                                comm_tolerance=0.1)
    assert res.ok, obs_perf.format_gate(res)
    rc = perf_cli.main(["gate", "--history", path,
                        "--comm-tolerance", "0.1"])
    assert rc == 0, rc

    bad = _comm_history(os.path.join(workdir, "comm_bad.jsonl"),
                        regress=True)
    res = obs_perf.gate_history(obs_perf.load_history(bad),
                                comm_tolerance=0.1)
    assert not res.ok and res.failures[0]["kind"] == "comm", \
        res.to_dict()
    assert "exposed_s" in res.failures[0]["why"], res.failures
    # without the opt-in flag the same history passes (throughput
    # noise hides the regression — exactly why the gate exists)
    assert obs_perf.gate_history(obs_perf.load_history(bad)).ok
    rc = perf_cli.main(["gate", "--history", bad,
                        "--comm-tolerance", "0.1"])
    assert rc == 1, rc
    return res.failures[0]["why"]


def selftest(args):
    import shutil

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _ensure_virtual_devices(8)
    workdir = tempfile.mkdtemp(prefix="paddle_pcomm_")
    try:
        rep, bucket_report = _selftest_spans_and_overlap(workdir)
        blob, cal = _selftest_calibration(workdir, bucket_report)
        n_hosts, off, n_events = _selftest_merge(workdir)
        gate_why = _selftest_gate(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    print("[pcomm] selftest green: %d bucket(s) traced in reduce "
          "order, overlap split step %.2fms = compute %.2fms + "
          "exposed %.2fms (efficiency %.0f%%); calibration %d "
          "pair(s) -> comm coef %.2f (foreign class kept analytic); "
          "%d host window(s) merged on a common timebase (%d events, "
          "recovered skew %.3fs); comm gate discriminates: %s"
          % (len(bucket_report["buckets"]), rep["step_s"] * 1e3,
             rep["compute_s"] * 1e3, rep["exposed_s"] * 1e3,
             rep["overlap_efficiency"] * 100, blob["n"],
             cal.coef["comm"], n_hosts, n_events, off, gate_why),
          flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "report":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _ensure_virtual_devices(max(8, args.dp))
        return cmd_report(args)
    if args.cmd == "merge":
        return cmd_merge(args)
    raise SystemExit("nothing to do: pass report|merge or --selftest")


if __name__ == "__main__":
    sys.exit(main())
