"""Cluster job launcher: spawn pservers + trainers for one training job.

reference: paddle/scripts/cluster_train/paddle.py (fabric/ssh job
spawner setting PADDLE_* env per process) and the env-var role protocol
of tests/book_distribute/notest_dist_fit_a_line.py:45-53
(TRAINING_ROLE / PSERVERS / TRAINER_ID).  Local mode runs everything on
this host; remote mode emits the per-host commands (ssh execution is
site-specific by design).

Usage:
    python -m paddle_tpu.tools.cluster_launch \
        --pservers=127.0.0.1:7164,127.0.0.1:7165 --trainers=2 \
        [--async] train.py [script args...]
"""

import argparse
import os
import signal
import subprocess
import sys

__all__ = ["launch", "main"]


def launch(script_argv, pservers, trainers, sync=True, env=None,
           python=sys.executable, elastic=False):
    """Spawn len(pservers) pserver processes + `trainers` trainer
    processes; returns (pserver_procs, trainer_procs[, master]).

    Returns (pserver_procs, trainer_procs, master); `master` is None
    unless elastic.

    elastic=True runs the reference's etcd-style flow instead of static
    endpoints: a master process carries the TTL-lease registry, each
    pserver binds a free port and registers its slot with heartbeats,
    trainers discover the live set via
    `distributed.discover_pservers()` (PADDLE_MASTER /
    PADDLE_PSERVER_COUNT env).  `pservers` then only sets the COUNT;
    the endpoints in it are ignored."""
    base_env = dict(os.environ)
    base_env.update(env or {})
    base_env["TRAINERS"] = str(trainers)
    base_env["PADDLE_SYNC"] = "1" if sync else "0"

    master = None
    if elastic:
        from .. import native

        master = native.Master()
        base_env["PADDLE_MASTER"] = "127.0.0.1:%d" % master.port
        base_env["PADDLE_PSERVER_COUNT"] = str(len(pservers))
        code = (
            "import os,signal;"
            "from paddle_tpu import native;"
            "from paddle_tpu.distributed import ElasticRegistry;"
            "s=native.ParameterServer(port=0,"
            "num_trainers=int(os.environ['TRAINERS']),"
            "sync=os.environ['PADDLE_SYNC']=='1');"
            "host,port=os.environ['PADDLE_MASTER'].rsplit(':',1);"
            "reg=ElasticRegistry(host,int(port));"
            "slot,lease=reg.register_pserver("
            "'127.0.0.1:%d'%s.port,"
            "int(os.environ['PADDLE_PSERVER_COUNT']));"
            "print('pserver ready slot',slot,flush=True);"
            "signal.pause()")
    else:
        base_env["PSERVERS"] = ",".join(pservers)
        code = ("import os,sys,signal;"
                "from paddle_tpu.distributed import run_pserver;"
                "s=run_pserver(os.environ['PSERVER_ENDPOINT'],"
                "trainers=int(os.environ['TRAINERS']),"
                "sync=os.environ['PADDLE_SYNC']=='1');"
                "print('pserver ready', flush=True);"
                "signal.pause()")

    ps_procs = []
    try:
        for ep in pservers:
            ps_procs.append(subprocess.Popen(
                [python, "-c", code],
                env={**base_env, "TRAINING_ROLE": "PSERVER",
                     "PSERVER_ENDPOINT": ep},
                stdout=subprocess.PIPE, text=True))
        # trainers have no connect retry: wait until every pserver has
        # bound its port (and, elastic, registered) before spawning them
        for p in ps_procs:
            line = p.stdout.readline()
            if "ready" not in line:
                raise RuntimeError("pserver failed to start: %r" % line)
    except BaseException:
        for p in ps_procs:
            p.kill()
        if master is not None:
            master.stop()
        raise

    tr_procs = []
    for tid in range(trainers):
        tr_procs.append(subprocess.Popen(
            [python] + list(script_argv),
            env={**base_env, "TRAINING_ROLE": "TRAINER",
                 "TRAINER_ID": str(tid)}))
    return ps_procs, tr_procs, master


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pservers", required=True,
                    help="comma-separated host:port endpoints")
    ap.add_argument("--trainers", type=int, default=1)
    ap.add_argument("--async", dest="sync", action="store_false",
                    help="async SGD (reference: asyncSGD)")
    ap.add_argument("--elastic", action="store_true",
                    help="etcd-style flow: master registry + pserver "
                         "slot registration + trainer discovery")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="trainer script + args")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("missing trainer script")

    pservers = args.pservers.split(",")
    ps_procs, tr_procs, master = launch(
        args.script, pservers, args.trainers, sync=args.sync,
        elastic=args.elastic)
    rc = 0
    try:
        for p in tr_procs:
            rc |= p.wait()
    finally:
        for p in ps_procs:
            p.send_signal(signal.SIGTERM)
        for p in ps_procs:
            p.wait()
        if master is not None:
            master.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
