"""Cluster job launcher: spawn pservers + trainers for one training job.

reference: paddle/scripts/cluster_train/paddle.py (fabric/ssh job
spawner setting PADDLE_* env per process, job_dispatch/job_pserver
:33-104) and the env-var role protocol of
tests/book_distribute/notest_dist_fit_a_line.py:45-53
(TRAINING_ROLE / PSERVERS / TRAINER_ID).  Local mode runs everything on
this host; remote mode (--hosts) executes one pserver + N trainers per
host over ssh (override the transport with --ssh for bastions/tests).

Usage:
    python -m paddle_tpu.tools.cluster_launch \
        --pservers=127.0.0.1:7164,127.0.0.1:7165 --trainers=2 \
        [--async] train.py [script args...]
    python -m paddle_tpu.tools.cluster_launch \
        --hosts=host1,host2 --trainers-per-host=1 train.py ...
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys

__all__ = ["launch", "launch_remote", "stop_remote",
           "print_fleet_view", "main"]


def launch(script_argv, pservers, trainers, sync=True, env=None,
           python=sys.executable, elastic=False):
    """Spawn len(pservers) pserver processes + `trainers` trainer
    processes; returns (pserver_procs, trainer_procs[, master]).

    Returns (pserver_procs, trainer_procs, master); `master` is None
    unless elastic.

    elastic=True runs the reference's etcd-style flow instead of static
    endpoints: a master process carries the TTL-lease registry, each
    pserver binds a free port and registers its slot with heartbeats,
    trainers discover the live set via
    `distributed.discover_pservers()` (PADDLE_MASTER /
    PADDLE_PSERVER_COUNT env).  `pservers` then only sets the COUNT;
    the endpoints in it are ignored."""
    base_env = dict(os.environ)
    base_env.update(env or {})
    base_env["TRAINERS"] = str(trainers)
    base_env["PADDLE_SYNC"] = "1" if sync else "0"

    master = None
    if elastic:
        from .. import native

        master = native.Master()
        base_env["PADDLE_MASTER"] = "127.0.0.1:%d" % master.port
        base_env["PADDLE_PSERVER_COUNT"] = str(len(pservers))
        # fleet observability rides the same master: workers that call
        # distributed.init_multihost (or start_fleet_reporter) publish
        # registry snapshots under /obs/<host>, and the launcher
        # prints the aggregated per-host view after the job
        base_env["PADDLE_OBS_MASTER"] = base_env["PADDLE_MASTER"]
        code = (
            "import os,signal;"
            "from paddle_tpu import native;"
            "from paddle_tpu.distributed import ElasticRegistry;"
            "s=native.ParameterServer(port=0,"
            "num_trainers=int(os.environ['TRAINERS']),"
            "sync=os.environ['PADDLE_SYNC']=='1');"
            "host,port=os.environ['PADDLE_MASTER'].rsplit(':',1);"
            "reg=ElasticRegistry(host,int(port));"
            "slot,lease=reg.register_pserver("
            "'127.0.0.1:%d'%s.port,"
            "int(os.environ['PADDLE_PSERVER_COUNT']));"
            "print('pserver ready slot',slot,flush=True);"
            "signal.pause()")
    else:
        base_env["PSERVERS"] = ",".join(pservers)
        code = _PSERVER_CODE

    ps_procs = []
    try:
        for ep in pservers:
            ps_procs.append(subprocess.Popen(
                [python, "-c", code],
                env={**base_env, "TRAINING_ROLE": "PSERVER",
                     "PSERVER_ENDPOINT": ep},
                stdout=subprocess.PIPE, text=True))
        # trainers have no connect retry: wait until every pserver has
        # bound its port (and, elastic, registered) before spawning them
        for p in ps_procs:
            line = p.stdout.readline()
            if "ready" not in line:
                raise RuntimeError("pserver failed to start: %r" % line)
    except BaseException:
        for p in ps_procs:
            p.kill()
        if master is not None:
            master.stop()
        raise

    tr_procs = []
    for tid in range(trainers):
        tr_procs.append(subprocess.Popen(
            [python] + list(script_argv),
            env={**base_env, "TRAINING_ROLE": "TRAINER",
                 "TRAINER_ID": str(tid),
                 "PADDLE_FLEET_HOST": "trainer%d" % tid}))
    return ps_procs, tr_procs, master


def print_fleet_view(master, out=sys.stdout):
    """Aggregate whatever /obs/<host> snapshots the job's workers
    published into the master's lease store and print the host-labeled
    view + straggler report (obs.fleet).  Quietly a no-op when no
    worker reported."""
    from ..obs.fleet import FleetAggregator

    agg = FleetAggregator()
    try:
        n = agg.collect("127.0.0.1:%d" % master.port)
    except Exception as exc:  # noqa: BLE001 — an observability
        # printout must never turn a successful job into a failed
        # launcher exit (list_prefix buffer overflow, corrupt
        # snapshot, master already gone)
        out.write("[cluster] fleet view unavailable: %s\n" % exc)
        return None
    if not n:
        return None
    report = agg.stragglers()
    out.write(agg.render_text())
    out.write("[cluster] fleet: %d host snapshot(s), step_ms=%s, "
              "stragglers=%s\n"
              % (n, report["step_ms"], report["flagged"] or "none"))
    return report


def _pserver_code(wait):
    """`wait="signal"` parks on signal.pause() (local mode — SIGTERM
    reaches the process directly).  `wait="stdin"` parks on reading
    stdin (remote mode — without a pty, sshd does NOT forward signals
    to the remote command, but closing the ssh channel delivers EOF,
    so stdin-EOF is the reliable remote shutdown edge)."""
    park = ("signal.pause()" if wait == "signal"
            else "sys.stdin.read()")
    return (
        "import os,sys,signal;"
        "from paddle_tpu.distributed import run_pserver;"
        "s=run_pserver(os.environ['PSERVER_ENDPOINT'],"
        "trainers=int(os.environ['TRAINERS']),"
        "sync=os.environ['PADDLE_SYNC']=='1');"
        "print('pserver ready', flush=True);"
        + park)


_PSERVER_CODE = _pserver_code("signal")


def _ssh_popen(ssh_cmd, host, workdir, role_env, argv, python,
               **popen_kwargs):
    """Execute `argv` on `host` through `ssh_cmd`.  The remote side runs
    one shell command string (ssh concatenates its trailing args with
    spaces), so every token is shell-quoted and the env rides inline —
    the reference launcher builds its remote commands the same way
    (cluster_train/paddle.py job_pserver/job_trainer)."""
    envs = " ".join("%s=%s" % (k, shlex.quote(str(v)))
                    for k, v in sorted(role_env.items()))
    cmd = "cd %s && env %s %s %s" % (
        shlex.quote(workdir), envs, shlex.quote(python),
        " ".join(shlex.quote(a) for a in argv))
    return subprocess.Popen(list(ssh_cmd) + [host, cmd], **popen_kwargs)


def launch_remote(script_argv, hosts, trainers_per_host=1, base_port=7164,
                  sync=True, env=None, python="python",
                  ssh_cmd=("ssh", "-o", "BatchMode=yes"), workdir=None,
                  port_step=0):
    """Run the job across `hosts` over ssh: one pserver per host (bound
    at base_port) plus trainers_per_host trainers per host with global
    TRAINER_IDs.  Returns (pserver_procs, trainer_procs) — the Popen
    handles of the ssh transports.  Shut pservers down with
    `stop_remote(proc)`: without a pty sshd does not forward signals
    to the remote command, so the remote side parks on reading stdin
    and exits on the EOF that closing the channel delivers.

    `ssh_cmd` is the transport argv prefix; tests substitute a local
    shim, bastion setups prepend ProxyJump options.  `port_step`
    staggers the per-host pserver ports (single-machine smoke runs
    where every "host" is a loopback alias)."""
    workdir = workdir or os.getcwd()
    pservers = ["%s:%d" % (h, base_port + i * port_step)
                for i, h in enumerate(hosts)]
    base_env = dict(env or {})
    base_env["TRAINERS"] = str(trainers_per_host * len(hosts))
    base_env["PADDLE_SYNC"] = "1" if sync else "0"
    base_env["PSERVERS"] = ",".join(pservers)

    ps_procs = []
    try:
        for host, ep in zip(hosts, pservers):
            ps_procs.append(_ssh_popen(
                ssh_cmd, host, workdir,
                {**base_env, "TRAINING_ROLE": "PSERVER",
                 "PSERVER_ENDPOINT": ep},
                ["-c", _pserver_code("stdin")], python,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, text=True))
        for p in ps_procs:
            line = p.stdout.readline()
            if "ready" not in line:
                raise RuntimeError("remote pserver failed: %r" % line)
    except BaseException:
        for p in ps_procs:
            p.kill()
        raise

    tr_procs = []
    tid = 0
    for host in hosts:
        for _ in range(trainers_per_host):
            tr_procs.append(_ssh_popen(
                ssh_cmd, host, workdir,
                {**base_env, "TRAINING_ROLE": "TRAINER",
                 "TRAINER_ID": str(tid)},
                list(script_argv), python))
            tid += 1
    return ps_procs, tr_procs


def stop_remote(proc, timeout=30):
    """Shut down a launch_remote pserver: EOF on the channel (the
    remote's stdin read returns), then terminate the local transport."""
    if proc.stdin is not None:
        try:
            proc.stdin.close()
        except OSError:
            pass
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        proc.wait(timeout=timeout)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pservers",
                    help="comma-separated host:port endpoints (local mode)")
    ap.add_argument("--trainers", type=int, default=1)
    ap.add_argument("--async", dest="sync", action="store_false",
                    help="async SGD (reference: asyncSGD)")
    ap.add_argument("--elastic", action="store_true",
                    help="etcd-style flow: master registry + pserver "
                         "slot registration + trainer discovery")
    ap.add_argument("--hosts",
                    help="comma-separated ssh hosts (remote mode: one "
                         "pserver per host + --trainers-per-host "
                         "trainers per host)")
    ap.add_argument("--trainers-per-host", type=int, default=1)
    ap.add_argument("--base-port", type=int, default=7164)
    ap.add_argument("--ssh", default="ssh -o BatchMode=yes",
                    help="transport command prefix for remote mode")
    ap.add_argument("--workdir", default=None,
                    help="remote working directory (default: cwd)")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="trainer script + args")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("missing trainer script")
    if bool(args.pservers) == bool(args.hosts):
        ap.error("exactly one of --pservers (local) or --hosts (remote)")
    if args.hosts and args.trainers != 1:
        ap.error("--hosts mode sizes trainers with --trainers-per-host")
    if args.hosts and args.elastic:
        ap.error("--elastic is a local-mode flow (remote elastic runs "
                 "the master on one host; launch it there locally)")

    master = None
    if args.hosts:
        ps_procs, tr_procs = launch_remote(
            args.script, args.hosts.split(","),
            trainers_per_host=args.trainers_per_host,
            base_port=args.base_port, sync=args.sync,
            ssh_cmd=tuple(shlex.split(args.ssh)), workdir=args.workdir)
    else:
        pservers = args.pservers.split(",")
        ps_procs, tr_procs, master = launch(
            args.script, pservers, args.trainers, sync=args.sync,
            elastic=args.elastic)
    rc = 0
    try:
        for p in tr_procs:
            rc |= p.wait()
        if master is not None:
            # before pservers stop: their /obs/ leases are still live
            print_fleet_view(master)
    finally:
        if args.hosts:
            for p in ps_procs:
                stop_remote(p)
        else:
            for p in ps_procs:
                p.send_signal(signal.SIGTERM)
            for p in ps_procs:
                p.wait()
        if master is not None:
            master.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
