"""Chaos harness CLI: supervised training under injected failure.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.chaos_cli --selftest

    # a custom chaos run (fault spec: point:kind[:after[:times]]):
    python -m paddle_tpu.tools.chaos_cli --epochs 3 --seed 11 \
        --faults reader/pump:io_error:5,supervisor/step:preempt:9

`--selftest` certifies the resilience contract end to end: an
MNIST-scale MLP classifier trains twice on the same seed — once
fault-free, once under chaos (one transient reader IOError, one real
SIGTERM preemption, one forced-nonfinite step) with the
`TrainingSupervisor` driving checkpoint/resume.  It asserts that

  * the supervised run completes despite all three faults,
  * its final parameters are IDENTICAL to the fault-free run's (the
    urgent checkpoint + batch-skip resume + nonfinite rollback
    reconstruct the exact trajectory),
  * the per-step loss trajectory matches step for step, and
  * `faults_injected_total{point,kind}` / `supervisor_restarts_total`
    confirm the faults actually fired and recovery actually ran —
    a chaos test that silently injected nothing proves nothing.

See docs/RESILIENCE.md for the fault-point catalogue and the
supervisor lifecycle.
"""

import argparse
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_chaos")
    p.add_argument("--selftest", action="store_true",
                   help="chaos certification: supervised run with "
                        "injected faults must match a fault-free run")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--steps", type=int, default=8,
                   help="batches per epoch")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seed", type=int, default=7,
                   help="data/fault-plan seed")
    p.add_argument("--ckpt-every", type=int, default=1,
                   help="supervisor steps_per_checkpoint")
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--faults", default=None,
                   help="comma list of point:kind[:after[:times]] "
                        "(default: the selftest trio)")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint directory (default: a tmpdir)")
    return p.parse_args(argv)


def _fresh_workspace():
    """Fresh default programs/scope so two runs in one process can't
    share state (the same reset the test suite does per test)."""
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.fluid import framework
    from paddle_tpu.v2 import layer as v2_layer

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()
    v2_layer._reset_data_layers()


def _build_mnist_mlp():
    """MNIST-scale classifier on the v2 API: 64-dim class-templated
    synthetic images -> tanh MLP -> softmax over 10 digits."""
    import paddle_tpu.v2 as paddle

    paddle.init()
    img = paddle.layer.data(name="img",
                            type=paddle.data_type.dense_vector(64))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(10))
    hidden = paddle.layer.fc(input=img, size=32,
                             act=paddle.activation.Tanh())
    pred = paddle.layer.fc(input=hidden, size=10,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    params = paddle.parameters.create(cost)
    sgd = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05))
    return sgd


def _make_batches(args):
    from paddle_tpu.dataset.common import synthetic_images

    imgs, labels = synthetic_images(args.steps * args.batch, (64,), 10,
                                    seed=args.seed)
    return [
        [(imgs[i], int(labels[i]))
         for i in range(b * args.batch, (b + 1) * args.batch)]
        for b in range(args.steps)
    ]


def _final_params(sgd):
    import numpy as np

    from paddle_tpu.core.scope import global_scope
    from paddle_tpu.fluid.io import is_persistable

    out = {}
    for v in sgd._main_program.list_vars():
        if not is_persistable(v):
            continue
        val = global_scope().get(v.name)
        if val is not None:
            out[v.name] = np.array(val)
    return out


def _parse_fault_specs(text):
    specs = []
    for item in text.split(","):
        parts = item.strip().split(":")
        if len(parts) < 2:
            raise SystemExit("bad fault spec %r (want "
                             "point:kind[:after[:times]])" % item)
        point, kind = parts[0], parts[1]
        after = int(parts[2]) if len(parts) > 2 else 0
        times = int(parts[3]) if len(parts) > 3 else 1
        specs.append((point, kind, after, times))
    return specs


def _default_fault_specs(args):
    # one of each: a transient reader I/O error, a real SIGTERM
    # preemption, a forced-nonfinite step — placed inside epoch 0/1 so
    # every recovery path runs before the final checkpoint
    mid = max(2, args.steps // 2)
    return [
        ("supervisor/step", "preempt", mid, 1),
        ("supervisor/step", "nonfinite", mid + 2, 1),
        ("reader/pump", "io_error", args.steps + 2, 1),
    ]


def _supervised_run(args, chaos, ckpt_dir):
    """One full training run; returns (summary, losses-by-step,
    final-params, fired-fault-counts)."""
    from paddle_tpu.reader import host_prefetch
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.supervisor import TrainingSupervisor

    _fresh_workspace()
    sgd = _build_mnist_mlp()
    batches = _make_batches(args)

    def reader():
        for b in batches:
            yield b

    if chaos:
        faults.enable(seed=args.seed)
        specs = (_parse_fault_specs(args.faults) if args.faults
                 else _default_fault_specs(args))
        for point, kind, after, times in specs:
            faults.inject(point, kind, after=after, times=times)
    try:
        sup = TrainingSupervisor(
            ckpt_dir, program=sgd._main_program,
            steps_per_checkpoint=args.ckpt_every,
            max_restarts=args.max_restarts)
        losses = {}
        summary = sup.run(
            sgd.step_runner(feeding={"img": 0, "label": 1}),
            host_prefetch(reader, depth=2), num_epochs=args.epochs,
            on_step=lambda step, loss: losses.__setitem__(step, loss))
        fired = faults.fired_counts()
    finally:
        faults.disable()
    return summary, losses, _final_params(sgd), fired


def _warm_cache_resume_leg(args, workdir):
    """The compile-cache resilience contract: a supervised run that is
    SIGTERM-preempted with the persistent executable cache enabled,
    then 'restarted' (fresh programs, fresh executor, fresh scope —
    everything a real process restart clears), must resume and finish
    with ZERO new XLA compiles — `executor_jit_traces_total` is the
    ground truth (docs/COMPILE_CACHE.md)."""
    from paddle_tpu.compile import pcache
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.reader import host_prefetch
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.supervisor import (Preempted,
                                                  TrainingSupervisor)
    from paddle_tpu.utils import flags

    cache_dir = os.path.join(workdir, "pcache")
    ckpt_dir = os.path.join(workdir, "warm")
    flags.set_flag("compile_cache_dir", cache_dir)
    pcache.reset()
    try:
        # phase 1: cold run, killed by a real SIGTERM mid-epoch
        # (on_preempt="raise" — the production mode: the process
        # exits on the urgent checkpoint and is rescheduled)
        _fresh_workspace()
        sgd = _build_mnist_mlp()
        batches = _make_batches(args)

        def reader():
            for b in batches:
                yield b

        faults.enable(seed=args.seed)
        faults.inject("supervisor/step", "preempt",
                      after=max(2, args.steps // 2), times=1)
        preempted = False
        try:
            TrainingSupervisor(
                ckpt_dir, program=sgd._main_program,
                steps_per_checkpoint=args.ckpt_every,
                max_restarts=args.max_restarts,
                on_preempt="raise").run(
                sgd.step_runner(feeding={"img": 0, "label": 1}),
                host_prefetch(reader, depth=2),
                num_epochs=args.epochs)
        except Preempted:
            preempted = True
        finally:
            faults.disable()
        assert preempted, "the preemption fault never fired"

        # phase 2: the restart.  Everything in-process is rebuilt
        # from scratch; only the checkpoint dir and the on-disk
        # executable cache survive — exactly a rescheduled process.
        _fresh_workspace()
        pcache.reset()
        sgd = _build_mnist_mlp()
        traces_before = obs_tele.jit_trace_count()
        summary = TrainingSupervisor(
            ckpt_dir, program=sgd._main_program,
            steps_per_checkpoint=args.ckpt_every,
            max_restarts=args.max_restarts).run(
            sgd.step_runner(feeding={"img": 0, "label": 1}),
            host_prefetch(reader, depth=2), num_epochs=args.epochs)
        new_compiles = obs_tele.jit_trace_count() - traces_before
        assert new_compiles == 0, \
            "post-SIGTERM restart performed %d fresh XLA compile(s); " \
            "the persistent cache missed" % new_compiles
        snap = obs_tele.snapshot()
        assert snap.get("compile_cache_hits_total", 0) > 0, \
            "restart never touched the executable cache: %s" % {
                k: v for k, v in snap.items()
                if k.startswith("compile_cache")}
        return summary, new_compiles
    finally:
        flags.set_flag("compile_cache_dir", "")
        pcache.reset()


def selftest(args):
    import numpy as np

    from paddle_tpu.obs import telemetry as obs_tele

    workdir = tempfile.mkdtemp(prefix="paddle_chaos_")
    clean_sum, clean_loss, clean_params, _ = _supervised_run(
        args, chaos=False, ckpt_dir=os.path.join(workdir, "clean"))
    chaos_sum, chaos_loss, chaos_params, fired = _supervised_run(
        args, chaos=True, ckpt_dir=os.path.join(workdir, "chaos"))

    # every planned fault fired (a chaos run that injects nothing
    # certifies nothing)
    for point, kind, _, times in _default_fault_specs(args) \
            if not args.faults else _parse_fault_specs(args.faults):
        assert fired.get((point, kind), 0) >= 1, \
            "fault %s:%s never fired: %s" % (point, kind, fired)

    # the registry agrees: injections counted, restarts counted
    snap = obs_tele.snapshot()
    injected = sum(v for k, v in snap.items()
                   if k.startswith("faults_injected_total{"))
    restarts = sum(v for k, v in snap.items()
                   if k.startswith("supervisor_restarts_total"))
    assert injected >= 3, \
        "faults_injected_total says %d (<3):\n%s" % (injected, snap)
    assert restarts >= 2 and chaos_sum["restarts"] >= 2, \
        "expected >=2 supervisor restarts, got %s / registry %s" \
        % (chaos_sum, restarts)

    # the supervised chaos run reconstructed the exact trajectory
    assert clean_sum["steps"] == chaos_sum["steps"], (clean_sum,
                                                      chaos_sum)
    assert sorted(clean_loss) == sorted(chaos_loss)
    for step in clean_loss:
        assert abs(clean_loss[step] - chaos_loss[step]) < 1e-9, \
            "loss diverged at step %d: %.9g vs %.9g" \
            % (step, clean_loss[step], chaos_loss[step])
    # var names can differ across the two builds (unique_name counts
    # on); compare by sorted order — same architecture, same count
    ka, kb = sorted(clean_params), sorted(chaos_params)
    assert len(ka) == len(kb), (ka, kb)
    for a, b in zip(ka, kb):
        np.testing.assert_array_equal(
            clean_params[a], chaos_params[b],
            err_msg="final params diverged: %s vs %s" % (a, b))

    # warm-cache resume: a preempted run restarted from disk must
    # replay with zero new XLA compiles (persistent executable cache)
    warm_sum, warm_compiles = _warm_cache_resume_leg(args, workdir)
    assert warm_sum["steps"] == clean_sum["steps"], (warm_sum,
                                                     clean_sum)

    print("[chaos] selftest green: %d faults fired %s, %d supervisor "
          "restart(s), final params and %d-step loss trajectory "
          "IDENTICAL to the fault-free run; post-SIGTERM warm-cache "
          "restart resumed with %d fresh XLA compile(s) (ckpts under "
          "%s)"
          % (injected,
             sorted("%s:%s=%d" % (p, k, n)
                    for (p, k), n in fired.items()),
             chaos_sum["restarts"], len(clean_loss), warm_compiles,
             workdir),
          flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    # chaos runs must never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.selftest:
        return selftest(args)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="paddle_chaos_")
    summary, losses, _, fired = _supervised_run(
        args, chaos=True, ckpt_dir=ckpt_dir)
    print("[chaos] run complete: %s; faults fired: %s; final loss "
          "%.6g; checkpoints under %s"
          % (summary,
             sorted("%s:%s=%d" % (p, k, n)
                    for (p, k), n in fired.items()) or "none",
             losses[max(losses)] if losses else float("nan"),
             ckpt_dir), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
