"""Perf CLI ("pperf"): bottleneck classification, perf-history
inspection, and the noise-aware regression gate over
`paddle_tpu.obs.perf`.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.perf_cli --selftest

    # roofline + bottleneck verdict for a bench model (pass --step-ms
    # to classify a measured step against its floors):
    PYTHONPATH= JAX_PLATFORMS=cpu python -m paddle_tpu.tools.perf_cli \
        classify --model resnet50 --batch 128 --step-ms 51.8

    # the regression gate (exit 1 on regression — wire into CI after
    # a bench round; docs/PERF.md has the runbook):
    python -m paddle_tpu.tools.perf_cli gate --history perf_history.jsonl

    # the trajectory, one line per run:
    python -m paddle_tpu.tools.perf_cli history --metric resnet50

`--selftest` certifies the perf subsystem end to end:

  1. **gate discrimination** — a seeded synthetic history (median ~2470
     img/s, ±1.5% noise) must PASS the gate; the same history with an
     injected 20% regression must FAIL it (non-zero exit, output
     naming the metric, leg and bottleneck verdict); a `tpu-stale`
     re-emit must HARD-fail the platform check (the round-5 incident
     class);
  2. **step profiler** — a real v2 SGD run with the profiler installed
     must produce ring records with retrace/wall/time-split fields and
     valid Chrome-trace + JSONL exports, and the classifier must
     return a verdict;
  3. **SLO burn on a loopback engine** — requests through a real
     serving engine + server (in-process), /healthz must carry
     `slo_burn_rate`: ~0 under a generous objective, > 1 under an
     impossible one;
  4. **warm compile-cache blob** — with FLAGS_compile_cache_dir set, a
     restart-simulated second run must report pcache hits in the
     mega_bench-style compile_cache summary (the ROADMAP item 3
     flip, asserted).
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pperf")
    p.add_argument("cmd", nargs="?",
                   choices=["classify", "gate", "history"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="profiler + classifier + gate + SLO burn "
                        "certification")
    # classify
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--class-dim", type=int, default=1000)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--f32", dest="bf16", action="store_false")
    p.add_argument("--peak-tflops", type=float, default=None,
                   help="MXU peak (default: fluid/analysis.py v5e "
                        "numbers, halved for f32)")
    p.add_argument("--hbm-gbps", type=float, default=None)
    p.add_argument("--topk", type=int, default=12)
    p.add_argument("--step-ms", type=float, default=None,
                   help="classify: a measured step time to fold into "
                        "the verdict (floors only when absent)")
    # gate / history
    p.add_argument("--history", default="perf_history.jsonl",
                   help="perf history path (bench.py appends here)")
    p.add_argument("--metric", action="append", default=None,
                   help="restrict gate/history to metric name(s); "
                        "history treats it as a substring")
    p.add_argument("--baseline-n", type=int, default=None,
                   help="gate: rolling-median window (default 5)")
    p.add_argument("--tolerance", type=float, default=None,
                   help="gate: relative throughput tolerance "
                        "(default 0.05)")
    p.add_argument("--step-tolerance", type=float, default=None,
                   help="gate: relative step_ms tolerance (defaults "
                        "to --tolerance)")
    p.add_argument("--mem-tolerance", type=float, default=None,
                   help="gate: OPT-IN relative peak-memory tolerance "
                        "over the records' \"memory\" blobs "
                        "(bench.py stamps them; obs/mem.py) — an HBM "
                        "regression fails CI like a step-time one; "
                        "omitted = memory is not gated")
    p.add_argument("--comm-tolerance", type=float, default=None,
                   help="gate: OPT-IN relative comm-time tolerance "
                        "over the records' \"comm\" blobs (exposed_s "
                        "for overlapped runs, else measured_s; "
                        "obs/comm.py) — an overlap regression fails "
                        "CI even while throughput noise hides it; "
                        "omitted = comm is not gated")
    p.add_argument("--latency-tolerance", type=float, default=None,
                   help="gate: OPT-IN relative tail-latency tolerance "
                        "over the records' \"latency\" blobs (pload "
                        "runs; best percentile present, p99.9 first; "
                        "obs/load.py) — a serving p99 regression "
                        "fails CI even while throughput holds; "
                        "omitted = latency is not gated")
    p.add_argument("--allow-stale", action="store_true",
                   help="gate: downgrade stale-platform hard fails "
                        "to skips")
    p.add_argument("--prune-stale", action="store_true",
                   help="history: drop tpu-stale/cpu-fallback platform "
                        "records from the history file (dry-run "
                        "unless --yes) so the tuner's calibration fit "
                        "never trains on the round-5 incident class")
    p.add_argument("--yes", action="store_true",
                   help="history --prune-stale: actually rewrite the "
                        "file (atomically)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# classify
# ---------------------------------------------------------------------------

def cmd_classify(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.fluid import analysis
    from paddle_tpu.obs import perf as obs_perf

    try:
        # the bench model builder lives at the repo root (it is the
        # same program bench.py times, deliberately not packaged)
        from __graft_entry__ import _build_model
    except ImportError:
        raise SystemExit(
            "pperf classify builds the bench models via the repo's "
            "__graft_entry__ module — run it from the repo root "
            "(cd <repo> && python -m paddle_tpu.tools.perf_cli "
            "classify ...).  `pperf gate`/`history`/--selftest work "
            "from anywhere.")

    if args.bf16:
        fluid.amp.enable_bf16()
    fn = {"resnet50": models.resnet50, "alexnet": models.alexnet,
          "vgg16": models.vgg16, "vgg19": models.vgg19,
          "googlenet": models.googlenet,
          "smallnet": models.smallnet_mnist_cifar}[args.model]
    main_prog, _, _, _ = _build_model(fn, args.batch, args.image_size,
                                      args.class_dim, with_loss=True)
    peak = args.peak_tflops or (analysis.DEFAULT_PEAK_TFLOPS
                                if args.bf16
                                else analysis.DEFAULT_PEAK_TFLOPS / 2)
    bw = args.hbm_gbps or analysis.DEFAULT_HBM_GBPS
    rep = analysis.roofline_report(main_prog, peak_tflops=peak,
                                   hbm_gbps=bw, bf16_act=args.bf16)
    if args.step_ms is not None:
        blob = obs_perf.leg_perf_blob(
            main_prog, args.step_ms / 1e3, bf16_act=args.bf16,
            peak_tflops=peak, hbm_gbps=bw)
        if args.json:
            print(json.dumps(blob, sort_keys=True))
            return 0
        print(analysis.format_report(rep, topk=args.topk))
        print("\nmeasured %.2f ms -> %s (dominant: %s)  [%s]"
              % (args.step_ms, blob["verdict"], blob["dominant"],
                 blob["reason"]))
        return 0
    if args.json:
        floors = obs_perf.roofline_floors(main_prog,
                                          bf16_act=args.bf16,
                                          peak_tflops=peak,
                                          hbm_gbps=bw,
                                          topk=args.topk)
        print(json.dumps(floors, sort_keys=True))
        return 0
    print(analysis.format_report(rep, topk=args.topk))
    print("\n(no --step-ms given: floors only; pass the measured step "
          "to get a bottleneck verdict)")
    return 0


# ---------------------------------------------------------------------------
# history / gate
# ---------------------------------------------------------------------------

def _prune_stale(args):
    from paddle_tpu.obs import perf as obs_perf

    kept, dropped = obs_perf.prune_stale_history(args.history,
                                                 apply=args.yes)
    if not dropped:
        print("[pperf] no stale-platform records in %s (%d kept)"
              % (args.history, kept))
        return 0
    verb = "dropped" if args.yes else "would drop"
    print("[pperf] %s %d stale-platform record(s) from %s (%d kept):"
          % (verb, len(dropped), args.history, kept))
    for rec in dropped:
        print("  %-52s %-12s %s" % (rec.get("metric", "?"),
                                    rec.get("platform", "?"),
                                    rec.get("leg") or ""))
    if not args.yes:
        print("[pperf] dry run — pass --yes to rewrite the file")
    return 0


def cmd_history(args):
    from paddle_tpu.obs import perf as obs_perf

    if args.prune_stale:
        return _prune_stale(args)
    records = obs_perf.load_history(args.history)
    if not records:
        print("[pperf] no history at %s" % args.history)
        return 2
    wanted = args.metric
    shown = 0
    for r in records:
        metric = r.get("metric", "?")
        if wanted and not any(w in metric for w in wanted):
            continue
        shown += 1
        if args.json:
            print(json.dumps(r, sort_keys=True))
            continue
        print("%-52s %10.4g %-9s step %8s ms  %-12s %s%s"
              % (metric, r.get("value") or 0.0, r.get("unit") or "",
                 ("%.2f" % r["step_ms"]) if r.get("step_ms") else "?",
                 r.get("platform") or "?",
                 r.get("verdict") or "-",
                 (" (%s)" % r["leg"]) if r.get("leg") else ""))
    if not shown:
        print("[pperf] no history rows match %s" % wanted)
        return 2
    return 0


def cmd_gate(args):
    from paddle_tpu.obs import perf as obs_perf

    records = obs_perf.load_history(args.history)
    if not records:
        print("[pperf] gate: no usable history at %s — nothing to "
              "gate" % args.history)
        return 2
    result = obs_perf.gate_history(
        records,
        baseline_n=args.baseline_n or obs_perf.DEFAULT_BASELINE_N,
        tolerance=(obs_perf.DEFAULT_TOLERANCE
                   if args.tolerance is None else args.tolerance),
        step_tolerance=args.step_tolerance,
        allow_stale=args.allow_stale,
        metrics=set(args.metric) if args.metric else None,
        mem_tolerance=args.mem_tolerance,
        comm_tolerance=args.comm_tolerance,
        latency_tolerance=args.latency_tolerance)
    if args.json:
        print(json.dumps(result.to_dict(), sort_keys=True))
    else:
        print(obs_perf.format_gate(result))
    return result.exit_code


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _synthetic_history(path, regress=False, stale=False):
    """Two metrics x 6 rounds of plausible TPU records with ±1.5%
    deterministic noise; optionally a 20% regression or a tpu-stale
    re-emit as the newest resnet50 round."""
    from paddle_tpu.obs import perf as obs_perf

    noise = [1.0, 0.988, 1.012, 0.994, 1.009, 0.991]
    legs = {
        "resnet50_train_imgs_per_sec_batch128":
            dict(base=2471.1, unit="img/s", step=51.8, leg="default-b128",
                 verdict="hbm_bound", dominant="conv2d_grad"),
        "vgg16_train_imgs_per_sec_batch128":
            dict(base=1024.0, unit="img/s", step=125.0, leg="vgg16",
                 verdict="compute_bound", dominant="conv2d"),
    }
    if os.path.exists(path):
        os.remove(path)
    ts = 1_700_000_000.0
    for i, n in enumerate(noise):
        for metric, spec in legs.items():
            last = i == len(noise) - 1
            value = spec["base"] * n
            platform = "tpu"
            if last and metric.startswith("resnet50"):
                if regress:
                    value = spec["base"] * 0.80
                if stale:
                    platform = "tpu-stale"
            obs_perf.append_history(
                {"metric": metric, "value": round(value, 2),
                 "unit": spec["unit"],
                 "step_ms": round(spec["step"] / n, 2),
                 "mfu": 0.29, "amp_bf16": True, "platform": platform,
                 "perf": {"verdict": spec["verdict"],
                          "dominant": spec["dominant"]}},
                path, leg=spec["leg"], ts=ts + i)
    return path


def _selftest_gate(workdir):
    from paddle_tpu.obs import perf as obs_perf

    # clean trajectory: within-noise movement must pass
    path = _synthetic_history(os.path.join(workdir, "hist_ok.jsonl"))
    res = obs_perf.gate_history(obs_perf.load_history(path))
    assert res.ok, "noise-only history failed the gate:\n%s" \
        % obs_perf.format_gate(res)
    assert len(res.checked) == 2, res.to_dict()

    # injected 20% regression: must fail, naming metric + leg + verdict
    path = _synthetic_history(os.path.join(workdir, "hist_bad.jsonl"),
                              regress=True)
    res = obs_perf.gate_history(obs_perf.load_history(path))
    assert not res.ok, "20%% regression passed the gate"
    text = obs_perf.format_gate(res)
    f = res.failures[0]
    assert f["metric"].startswith("resnet50"), res.failures
    assert f["kind"] == "throughput", res.failures
    assert "resnet50" in text and "hbm_bound" in text \
        and "default-b128" in str(res.failures[0]["leg"]), text
    # CLI exit-code contract, end to end
    rc = main(["gate", "--history", path])
    assert rc == 1, "pperf gate exit code %r for a regression" % rc

    # tpu-stale newest record: hard platform fail, skip when allowed
    path = _synthetic_history(os.path.join(workdir, "hist_stale.jsonl"),
                              stale=True)
    res = obs_perf.gate_history(obs_perf.load_history(path))
    assert not res.ok and res.failures[0]["kind"] == "platform", \
        res.to_dict()
    res = obs_perf.gate_history(obs_perf.load_history(path),
                                allow_stale=True)
    assert res.ok and res.skipped, res.to_dict()
    return text


def _selftest_profiler(workdir):
    from paddle_tpu.obs import perf as obs_perf
    from paddle_tpu.tools.obs_dump import (validate_chrome_trace,
                                           _train_tiny_v2)

    profiler = obs_perf.install(capacity=64, sample_every=1)
    try:
        _train_tiny_v2()
    finally:
        obs_perf.uninstall()
    recs = profiler.records()
    assert recs, "profiler saw no steps"
    for r in recs:
        assert r["wall_s"] > 0 and "retraces" in r \
            and "pcache_hits" in r, r
    assert any(r["sampled"] and r["device_s"] is not None
               for r in recs), "no sampled step captured a time split"
    assert sum(r["retraces"] for r in recs) > 0, \
        "first step's jit builds left no retrace count"
    summary = profiler.summary()
    assert summary["steps"] == len(recs) and "split_ms" in summary, \
        summary
    verdict = profiler.classify()
    assert verdict and verdict["verdict"] in obs_perf.VERDICTS, verdict
    # exports: Chrome trace loads, JSONL parses line by line
    trace_path = os.path.join(workdir, "perf_trace.json")
    profiler.export_chrome_trace(trace_path)
    events = validate_chrome_trace(trace_path)
    assert any(ev.get("cat") == "perf" and ev["ph"] == "X"
               for ev in events), "no per-step spans in export"
    for line in profiler.export_jsonl().strip().splitlines():
        json.loads(line)
    return len(recs), verdict["verdict"]


def _selftest_slo():
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import (InferenceEngine, EngineConfig,
                                    InferenceServer, ServerConfig)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    engine = InferenceEngine(program, ["img"], [probs], scope=scope,
                             config=EngineConfig(batch_buckets=[2, 4]))
    # loopback: batcher running, no HTTP listener — handle_infer and
    # health_signals are exactly what the HTTP handlers call
    server = InferenceServer(
        engine, ServerConfig(warmup=False, slo_ms=0.0001,
                             slo_target=0.99, model_name="tiny-fc"))
    server.batcher.start()
    try:
        for _ in range(4):
            status, body = server.handle_infer(
                {"inputs": {"img": np.zeros((2, 8)).tolist()}})
            assert status == 200, (status, body)
        health = server.health_signals()
    finally:
        server.batcher.close()
    assert "slo_burn_rate" in health, health
    assert health["slo"]["model"] == "tiny-fc", health
    # a 0.1µs objective is unmeetable: the whole window violates, so
    # burn = 1 / (1 - target) = 100x budget
    assert health["slo_burn_rate"] > 1, health
    # generous objective on the same histogram: burn ~ 0
    from paddle_tpu.serving.metrics import SLOTracker

    relaxed = SLOTracker(server.metrics, objective_ms=20_000,
                         target=0.99, model="tiny-fc-relaxed")
    assert relaxed.update() == 0.0
    # an objective beyond the histogram's largest finite bucket is
    # unmeasurable and must be rejected at construction
    try:
        SLOTracker(server.metrics, objective_ms=60_000)
    except ValueError:
        pass
    else:
        raise AssertionError("out-of-range slo_ms was accepted")
    return health["slo_burn_rate"]


def _selftest_warm_cache(workdir):
    """The mega_bench compile-cache flip, asserted: a second
    (restart-simulated) run of the same program must serve its
    executables from the persistent cache and say so in the
    mega-style compile_cache summary blob."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.utils import flags

    cache_dir = os.path.join(workdir, "pcache")
    prev = flags.get_flag("compile_cache_dir")
    flags.set_flag("compile_cache_dir", cache_dir)
    try:
        def one_run():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name="x", shape=[6],
                                      dtype="float32")
                h = fluid.layers.fc(input=x, size=4)
                cost = fluid.layers.mean(x=h)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(scope):
                exe.run(startup)
                return exe.run(main,
                               feed={"x": np.ones((2, 6), np.float32)},
                               fetch_list=[cost])

        one_run()  # cold: populates the cache
        snap = obs_tele.snapshot()
        one_run()  # fresh programs/executor/scope: must reload
        delta = obs_tele.snapshot_delta(snap)
        blob = {"hits": delta.get("compile_cache_hits_total", 0),
                "misses": delta.get("compile_cache_misses_total", 0)}
        assert blob["hits"] > 0, \
            "warm rerun reported no pcache hits: %r" % (delta,)
        return blob
    finally:
        flags.set_flag("compile_cache_dir", prev)


def selftest(args):
    import shutil

    # never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="paddle_pperf_")

    try:
        gate_text = _selftest_gate(workdir)
        steps, verdict = _selftest_profiler(workdir)
        burn = _selftest_slo()
        warm = _selftest_warm_cache(workdir)
    finally:
        # ci.sh/smoke.sh run this every time: don't stack /tmp dirs
        shutil.rmtree(workdir, ignore_errors=True)

    print("[pperf] selftest green: gate discriminates (sample fail "
          "line below), %d profiled steps (verdict %s), loopback "
          "slo_burn_rate %.1f, warm cache blob %s\n%s"
          % (steps, verdict, burn, warm,
             gate_text.splitlines()[1] if len(gate_text.splitlines())
             > 1 else gate_text), flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "classify":
        return cmd_classify(args)
    if args.cmd == "gate":
        return cmd_gate(args)
    if args.cmd == "history":
        return cmd_history(args)
    raise SystemExit("nothing to do: pass a command (classify | gate "
                     "| history) or --selftest")


if __name__ == "__main__":
    sys.exit(main())
