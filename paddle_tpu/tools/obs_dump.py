"""Dump/export paddle_tpu observability state: Chrome trace-event JSON
(Perfetto-loadable) and the unified metrics registry.

    # validate a trace file someone handed you:
    python -m paddle_tpu.tools.obs_dump --check trace.json

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.obs_dump --selftest

    # IN-PROCESS, at the end of a run you instrumented with
    # obs.trace.tracing() (trace/registry state lives in the process
    # that ran the workload — a fresh shell invocation has nothing to
    # dump and says so):
    from paddle_tpu.tools import obs_dump
    obs_dump.main(["--trace-out", "trace.json",
                   "--metrics-out", "metrics.prom"])

`--selftest` runs a tiny REAL workload under tracing — a v2 SGD
trainer (executor underneath) plus a serving InferenceEngine request
pair (compile miss + cache hit) — then asserts the exported trace is
valid Chrome trace-event JSON with nested executor/trainer spans and
that ONE registry render carries executor, trainer and serving
metrics.  See docs/OBSERVABILITY.md for naming conventions.
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_obs_dump")
    p.add_argument("--trace-out", default=None,
                   help="write the collected trace as Chrome "
                        "trace-event JSON")
    p.add_argument("--metrics-out", default=None,
                   help="write the unified metrics registry ('-' for "
                        "stdout)")
    p.add_argument("--format", choices=("prom", "jsonl"),
                   default="prom",
                   help="metrics format: Prometheus text or JSONL")
    p.add_argument("--check", default=None, metavar="TRACE_JSON",
                   help="validate an existing Chrome trace file and "
                        "exit")
    p.add_argument("--selftest", action="store_true",
                   help="run a tiny traced workload and assert the "
                        "whole obs pipeline works end to end")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# validation helpers (also used by tests)
# ---------------------------------------------------------------------------

def validate_chrome_trace(doc):
    """Assert `doc` (dict or path) is a loadable Chrome trace-event
    document; returns the traceEvents list."""
    if not isinstance(doc, dict):
        with open(doc) as f:
            doc = json.load(f)
    events = doc.get("traceEvents")
    assert isinstance(events, list) and events, \
        "traceEvents missing or empty"
    for ev in events:
        assert isinstance(ev.get("name"), str), ev
        assert ev.get("ph") in ("X", "B", "E", "i", "M", "C"), ev
        if ev["ph"] in ("X", "B", "E", "i"):
            assert isinstance(ev.get("ts"), (int, float)), ev
            assert "pid" in ev and "tid" in ev, ev
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float)), ev
    return events


def validate_prometheus_text(text):
    """Assert every exposition line parses as comment or
    `name[{labels}] value`; returns the set of metric names seen."""
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        body, _, value = line.rpartition(" ")
        assert body, "unparseable line: %r" % line
        float(value)  # raises if the sample value isn't numeric
        name = body.split("{", 1)[0]
        assert name and " " not in name, "bad metric name: %r" % line
        names.add(name)
    assert names, "no metric samples in exposition"
    return names


def _find_span(events, prefix):
    return [ev for ev in events
            if ev["ph"] == "X" and ev["name"].startswith(prefix)]


def _nested_within(outer, inner):
    return (outer["tid"] == inner["tid"]
            and outer["ts"] <= inner["ts"] + 1e-3
            and inner["ts"] + inner.get("dur", 0)
            <= outer["ts"] + outer["dur"] + 1e-3)


# ---------------------------------------------------------------------------
# selftest workload
# ---------------------------------------------------------------------------

def _train_tiny_v2():
    """Three SGD steps through the real v2 trainer (executor + jit
    segments underneath)."""
    import numpy as np

    import paddle_tpu.v2 as paddle

    paddle.init()
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.1))
    rs = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rs.rand(4).astype("f"), rs.rand(1).astype("f"))
                   for _ in range(4)]

    trainer.train(reader=reader, num_passes=1,
                  feeding={"x": 0, "y": 1})


def _serve_tiny():
    """One compile-miss and one cache-hit request through the serving
    engine, with ServingMetrics mounted on the unified registry."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import InferenceEngine, EngineConfig
    from paddle_tpu.serving.metrics import ServingMetrics

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    metrics = ServingMetrics()
    engine = InferenceEngine(
        program, ["img"], [probs], scope=scope, metrics=metrics,
        config=EngineConfig(batch_buckets=[2, 4]))
    engine.run({"img": np.zeros((2, 8), np.float32)})  # miss: compile
    engine.run({"img": np.ones((1, 8), np.float32)})   # same bucket: hit
    assert metrics.cache_miss_total.value >= 1
    assert metrics.cache_hit_total.value >= 1
    return metrics


def selftest(args):
    # the selftest must never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.obs import trace as obs_trace

    obs_trace.enable(clear=True)
    try:
        _train_tiny_v2()
        metrics = _serve_tiny()
    finally:
        obs_trace.disable()

    # --- trace side: valid Chrome JSON, nested executor+trainer spans
    trace_path = args.trace_out or os.path.join(
        tempfile.mkdtemp(prefix="paddle_obs_"), "trace.json")
    obs_trace.export_chrome_trace(trace_path)
    events = validate_chrome_trace(trace_path)
    steps = _find_span(events, "v2/step")
    runs = _find_span(events, "executor/run")
    segs = _find_span(events, "executor/jit_segment")
    serving_spans = _find_span(events, "serving/engine_run")
    assert steps, "no trainer spans in trace"
    assert runs, "no executor spans in trace"
    assert segs, "no jit-segment spans in trace"
    assert serving_spans, "no serving spans in trace"
    assert any(_nested_within(st, r) for st in steps for r in runs), \
        "executor/run span not nested inside a v2/step span"
    assert any(_nested_within(r, sg) for r in runs for sg in segs), \
        "jit-segment span not nested inside an executor/run span"

    # --- metrics side: ONE registry render carries all three layers
    text = metrics.render_text()  # unified render via ServingMetrics
    names = validate_prometheus_text(text)
    for needed in ("executor_runs_total", "executor_jit_traces_total",
                   "trainer_steps_total", "trainer_step_seconds",
                   "serving_compile_cache_miss_total",
                   "serving_compile_cache_hit_total"):
        # histograms expose only _bucket/_sum/_count sample names
        assert any(n == needed or n.startswith(needed + "_")
                   for n in names), \
            "%s missing from unified exposition:\n%s" % (needed, text)
    assert obs_tele.jit_trace_count() > 0
    assert obs_tele.transfer_bytes("h2d") > 0

    # the same data is exportable as JSONL for offline diffing
    jsonl = obs_registry.get_registry().render_jsonl()
    for line in jsonl.strip().splitlines():
        json.loads(line)

    if args.metrics_out:
        _write_metrics(args, text if args.format == "prom" else jsonl)
    print("[obs] selftest green: %d trace events (%d trainer steps, "
          "%d executor runs, %d jit segments, %d serving spans), "
          "unified /metrics has %d metric families, trace at %s"
          % (len(events), len(steps), len(runs), len(segs),
             len(serving_spans), len(names), trace_path), flush=True)
    return 0


# ---------------------------------------------------------------------------
# plain dump modes
# ---------------------------------------------------------------------------

def _write_metrics(args, payload):
    if args.metrics_out == "-":
        sys.stdout.write(payload)
        return
    with open(args.metrics_out, "w") as f:
        f.write(payload)


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.check:
        events = validate_chrome_trace(args.check)
        print("[obs] %s: valid Chrome trace with %d events"
              % (args.check, len(events)), flush=True)
        return 0
    if not args.trace_out and not args.metrics_out:
        raise SystemExit("nothing to do: pass --selftest, --check, "
                         "--trace-out and/or --metrics-out")
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.obs import trace as obs_trace

    if args.trace_out:
        doc = obs_trace.export_chrome_trace(args.trace_out)
        n = sum(1 for e in doc["traceEvents"] if e["ph"] != "M")
        print("[obs] wrote trace: %s (%d events)%s"
              % (args.trace_out, n,
                 "" if n else " — EMPTY: dump modes export THIS "
                 "process's state; call obs_dump.main() in-process "
                 "after obs.trace.tracing()"), flush=True)
    if args.metrics_out:
        reg = obs_registry.get_registry()
        _write_metrics(args, reg.render_text() if args.format == "prom"
                       else reg.render_jsonl())
        if args.metrics_out != "-":
            print("[obs] wrote metrics: %s" % args.metrics_out,
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
