"""Dump/export paddle_tpu observability state: Chrome trace-event JSON
(Perfetto-loadable) and the unified metrics registry.

    # validate a trace file someone handed you:
    python -m paddle_tpu.tools.obs_dump --check trace.json

    # pretty-print a crash flight bundle (obs.flight):
    python -m paddle_tpu.tools.obs_dump --flight flight_1234_001.json

    # pretty-print a tail-capture dump (obs.tail / GET /debug/tail):
    python -m paddle_tpu.tools.obs_dump --tail tail.json

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.obs_dump --selftest

    # IN-PROCESS, at the end of a run you instrumented with
    # obs.trace.tracing() (trace/registry state lives in the process
    # that ran the workload — a fresh shell invocation has nothing to
    # dump and says so):
    from paddle_tpu.tools import obs_dump
    obs_dump.main(["--trace-out", "trace.json",
                   "--metrics-out", "metrics.prom"])

`--selftest` runs a tiny REAL workload under tracing — a v2 SGD
trainer (executor underneath), a serving InferenceEngine request pair
(compile miss + cache hit), a request-tracing leg (loopback server:
traceparent continued + request_id echoed incl. on an error reply, an
injected-slow request's exemplar in /metrics and its span tree in the
tail ring), and a deliberately-NaN health/flight leg (NumericsMonitor
counts, locate_nonfinite names the op, an induced crash writes a
flight bundle) — then asserts the exported trace is valid Chrome
trace-event JSON with nested executor/trainer spans, that ONE
registry render carries executor, trainer and serving metrics, and
that the per-segment xla_* memory/cost gauges landed.  See
docs/OBSERVABILITY.md for naming conventions.
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_obs_dump")
    p.add_argument("--trace-out", default=None,
                   help="write the collected trace as Chrome "
                        "trace-event JSON")
    p.add_argument("--metrics-out", default=None,
                   help="write the unified metrics registry ('-' for "
                        "stdout)")
    p.add_argument("--format", choices=("prom", "jsonl"),
                   default="prom",
                   help="metrics format: Prometheus text or JSONL")
    p.add_argument("--check", default=None, metavar="TRACE_JSON",
                   help="validate an existing Chrome trace file and "
                        "exit")
    p.add_argument("--flight", default=None, metavar="BUNDLE_JSON",
                   help="validate and pretty-print a flight-recorder "
                        "bundle (obs.flight) and exit")
    p.add_argument("--tail", default=None, metavar="TAIL_JSON",
                   help="validate and pretty-print a tail-capture "
                        "dump (obs.tail / the server's /debug/tail "
                        "body) and exit")
    p.add_argument("--selftest", action="store_true",
                   help="run a tiny traced workload and assert the "
                        "whole obs pipeline works end to end")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# validation helpers (also used by tests)
# ---------------------------------------------------------------------------

def validate_chrome_trace(doc):
    """Assert `doc` (dict or path) is a loadable Chrome trace-event
    document; returns the traceEvents list."""
    if not isinstance(doc, dict):
        with open(doc) as f:
            doc = json.load(f)
    events = doc.get("traceEvents")
    assert isinstance(events, list) and events, \
        "traceEvents missing or empty"
    for ev in events:
        assert isinstance(ev.get("name"), str), ev
        assert ev.get("ph") in ("X", "B", "E", "i", "M", "C"), ev
        if ev["ph"] in ("X", "B", "E", "i"):
            assert isinstance(ev.get("ts"), (int, float)), ev
            assert "pid" in ev and "tid" in ev, ev
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float)), ev
    return events


def validate_prometheus_text(text):
    """Assert every exposition line parses as comment or
    `name[{labels}] value[ # {exemplar} value ts]` (the bracketed
    suffix is OpenMetrics exemplar syntax on histogram buckets);
    returns the set of metric names seen."""
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        sample, _, exemplar = line.partition(" # ")
        if exemplar:
            labels, _, rest = exemplar.partition("} ")
            assert labels.startswith("{"), "bad exemplar: %r" % line
            ex_value, _, ex_ts = rest.partition(" ")
            float(ex_value)
            if ex_ts:
                float(ex_ts)
        body, _, value = sample.rpartition(" ")
        assert body, "unparseable line: %r" % line
        float(value)  # raises if the sample value isn't numeric
        name = body.split("{", 1)[0]
        assert name and " " not in name, "bad metric name: %r" % line
        names.add(name)
    assert names, "no metric samples in exposition"
    return names


def validate_flight_bundle(doc):
    """Assert `doc` (dict or path) is a well-formed flight-recorder
    bundle; returns the loaded dict."""
    if not isinstance(doc, dict):
        with open(doc) as f:
            doc = json.load(f)
    assert doc.get("kind") == "paddle_tpu.flight", \
        "not a flight bundle (kind=%r)" % doc.get("kind")
    assert isinstance(doc.get("version"), int)
    assert isinstance(doc.get("steps"), list)
    assert isinstance(doc.get("registry"), dict)
    assert isinstance(doc.get("notes"), list)
    for rec in doc["steps"]:
        assert "step" in rec and "trainer" in rec, rec
        assert isinstance(rec.get("telemetry_delta", {}), dict)
    exc = doc.get("exception")
    if exc is not None:
        assert "type" in exc and "message" in exc, exc
    return doc


def render_flight(doc, max_steps=8):
    """Human-readable summary of a flight bundle (the --flight CLI
    output)."""
    doc = validate_flight_bundle(doc)
    lines = []
    lines.append("flight bundle v%d  reason=%s  steps=%d (%d dropped)"
                 % (doc["version"], doc.get("reason"),
                    len(doc["steps"]), doc.get("dropped_steps", 0)))
    ctx = doc.get("trace_context")
    if ctx:
        lines.append("request: id=%s trace=%s span=%s"
                     % (ctx.get("request_id"), ctx.get("trace_id"),
                        ctx.get("span_id")))
    exc = doc.get("exception")
    if exc:
        lines.append("exception: %s: %s" % (exc["type"], exc["message"]))
        tb = exc.get("traceback") or ""
        lines.extend("  " + l for l in tb.rstrip().splitlines()[-3:])
    for note in doc.get("notes", []):
        ctx = {k: v for k, v in note.items()
               if k not in ("t", "origin", "oom")}
        lines.append("note [%s] %s" % (note.get("origin"), ctx))
        oom = note.get("oom")
        if oom:
            # the obs.mem post-mortem: name WHICH buffers were
            # resident, not just "out of memory"
            if oom.get("total_peak_bytes") is not None:
                lines.append(
                    "  OOM post-mortem: static peak %.1f MiB "
                    "(params+state %.1f + activations %.1f at op "
                    "%s %s)"
                    % (oom["total_peak_bytes"] / 2**20,
                       oom.get("params_bytes", 0) / 2**20,
                       oom.get("static_peak_bytes", 0) / 2**20,
                       oom.get("peak_op"), oom.get("peak_op_type")))
            for b in oom.get("top_buffers", [])[:5]:
                lines.append("    %-40s %10.2f MiB  def op %s (%s)"
                             % (b["name"], b["bytes"] / 2**20,
                                b.get("def_op"),
                                b.get("def_op_type")))
            for k, v in sorted((oom.get("mem_gauges") or {}).items()):
                lines.append("    gauge %s = %g" % (k, v))
            for dev, stats in sorted((oom.get("device") or {}).items()):
                lines.append("    device %s: %.1f MiB in use, peak "
                             "%.1f MiB"
                             % (dev,
                                stats.get("bytes_in_use", 0) / 2**20,
                                stats.get("peak_bytes_in_use", 0)
                                / 2**20))
    steps = doc["steps"][-max_steps:]
    if steps:
        lines.append("last %d step(s):" % len(steps))
    for rec in steps:
        delta = rec.get("telemetry_delta") or {}
        bits = ["step=%s" % rec.get("step"),
                "trainer=%s" % rec.get("trainer")]
        if rec.get("loss") is not None:
            bits.append("loss=%.6g" % rec["loss"])
        if rec.get("feeds"):
            bits.append("feeds=%s" % rec["feeds"])
        bits.append("%d metric(s) moved" % len(delta))
        lines.append("  " + "  ".join(bits))
    reg = doc.get("registry", {})
    interesting = {k: v for k, v in sorted(reg.items())
                   if k.startswith(("numerics_", "grad_global_norm",
                                    "amp_loss_scale", "xla_", "mem_",
                                    "trainer_last_loss",
                                    "executor_jit_traces_total"))}
    lines.append("registry: %d metric sample(s)%s"
                 % (len(reg), "" if not interesting
                    else ", notable:"))
    for k, v in interesting.items():
        lines.append("  %s = %g" % (k, v))
    lines.append("recent spans: %d" % len(doc.get("recent_spans", [])))
    return "\n".join(lines)


def validate_tail_dump(doc):
    """Assert `doc` (dict or path) is a well-formed tail-capture dump
    (obs.tail.TailRecorder.dump / the /debug/tail body); returns the
    loaded dict."""
    if not isinstance(doc, dict):
        with open(doc) as f:
            doc = json.load(f)
    assert doc.get("kind") == "paddle_tpu.tail", \
        "not a tail dump (kind=%r)" % doc.get("kind")
    assert isinstance(doc.get("version"), int)
    assert isinstance(doc.get("requests"), list)
    for rec in doc["requests"]:
        assert rec.get("reason") in ("slow", "error"), rec
        assert "trace_id" in rec and "request_id" in rec, rec
        assert isinstance(rec.get("latency_ms"), (int, float)), rec
        assert isinstance(rec.get("spans"), list), rec
    return doc


def _render_span_node(node, depth, lines):
    args = node.get("args") or {}
    arg_str = "" if not args else "  %s" % args
    lines.append("  %s%s %.3fms%s"
                 % ("  " * depth, node["name"],
                    node.get("dur_ms", 0.0), arg_str))
    for child in node.get("children", []):
        _render_span_node(child, depth + 1, lines)


def render_tail(doc, max_requests=8):
    """Human-readable summary of a tail dump (the --tail CLI output):
    one block per captured request with its indented span tree."""
    doc = validate_tail_dump(doc)
    lines = ["tail dump v%d  slow_ms=%s  captured=%d (%d evicted)"
             % (doc["version"], doc.get("slow_ms"),
                doc.get("total_captured", len(doc["requests"])),
                doc.get("evicted", 0))]
    for rec in doc["requests"][-max_requests:]:
        head = ("request %s  trace %s  %s  %.1fms  status=%s"
                % (rec["request_id"], rec["trace_id"], rec["reason"],
                   rec["latency_ms"], rec.get("status")))
        if rec.get("error"):
            head += "  error=%s" % rec["error"]
        lines.append(head)
        for root in rec["spans"]:
            _render_span_node(root, 0, lines)
    return "\n".join(lines)


def _find_span(events, prefix):
    return [ev for ev in events
            if ev["ph"] == "X" and ev["name"].startswith(prefix)]


def _nested_within(outer, inner):
    return (outer["tid"] == inner["tid"]
            and outer["ts"] <= inner["ts"] + 1e-3
            and inner["ts"] + inner.get("dur", 0)
            <= outer["ts"] + outer["dur"] + 1e-3)


# ---------------------------------------------------------------------------
# selftest workload
# ---------------------------------------------------------------------------

def _train_tiny_v2():
    """Three SGD steps through the real v2 trainer (executor + jit
    segments underneath)."""
    import numpy as np

    import paddle_tpu.v2 as paddle

    paddle.init()
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.square_error_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.1))
    rs = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rs.rand(4).astype("f"), rs.rand(1).astype("f"))
                   for _ in range(4)]

    trainer.train(reader=reader, num_passes=1,
                  feeding={"x": 0, "y": 1})


def _serve_tiny():
    """One compile-miss and one cache-hit request through the serving
    engine, with ServingMetrics mounted on the unified registry."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.serving import InferenceEngine, EngineConfig
    from paddle_tpu.serving.metrics import ServingMetrics

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main, [probs])
    metrics = ServingMetrics()
    engine = InferenceEngine(
        program, ["img"], [probs], scope=scope, metrics=metrics,
        config=EngineConfig(batch_buckets=[2, 4]))
    engine.run({"img": np.zeros((2, 8), np.float32)})  # miss: compile
    engine.run({"img": np.ones((1, 8), np.float32)})   # same bucket: hit
    assert metrics.cache_miss_total.value >= 1
    assert metrics.cache_hit_total.value >= 1
    return metrics


def _trace_serve_tiny(workdir):
    """The request-tracing contract end to end over a REAL loopback
    server (docs/SERVING.md): a traceparent header is continued and
    echoed with a minted request_id (also on an error reply), a
    deterministically-injected slow request leaves an OpenMetrics
    exemplar carrying its trace id on the /metrics latency histogram,
    and the tail ring keeps that request's full span tree — rendered
    by this CLI's own --tail path."""
    import http.client

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import io as fluid_io
    from paddle_tpu.resilience import faults as r_faults
    from paddle_tpu.serving import (InferenceEngine, EngineConfig,
                                    InferenceServer, ServerConfig)

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[8], dtype="float32")
        probs = fluid.layers.fc(input=img, size=3, act="softmax")
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    program = fluid_io.prune_program(main_prog, [probs])
    engine = InferenceEngine(program, ["img"], [probs], scope=scope,
                             config=EngineConfig(batch_buckets=[2]))
    server = InferenceServer(engine, ServerConfig(
        port=0, tail_slow_ms=50.0)).start()
    host, port = server.address

    def post(payload, headers=None):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("POST", "/v1/infer", json.dumps(payload),
                         dict({"Content-Type": "application/json"},
                              **(headers or {})))
            resp = conn.getresponse()
            return (resp.status, json.loads(resp.read()),
                    dict(resp.getheaders()))
        finally:
            conn.close()

    def get(path, headers=None):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("GET", path, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read().decode()
        finally:
            conn.close()

    trace_id = "0af7651916cd43dd8448eb211c80319c"
    traceparent = "00-%s-b7ad6b7169203331-01" % trace_id
    # the SLOW request gets its OWN trace id: the exemplar/tail
    # assertions below must not be satisfiable by the fast request
    slow_trace_id = "deadbeefcafe43dd8448eb211c80319c"
    slow_traceparent = "00-%s-b7ad6b7169203331-01" % slow_trace_id
    payload = {"inputs": {"img": [[0.5] * 8]}}
    try:
        # contract 1: traceparent continued + request_id minted/echoed
        status, body, headers = post(payload,
                                     {"traceparent": traceparent})
        assert status == 200 and body.get("request_id"), body
        assert headers.get("traceparent", "").split("-")[1] \
            == trace_id, headers
        assert headers.get("x-request-id") == body["request_id"]

        # contract 2: an injected-slow request (deterministic fault,
        # not a sleep race) leaves an exemplar + a tail capture
        plan = r_faults.enable(seed=0)
        plan.inject("serving/run", "latency", latency_s=0.12, times=1)
        try:
            status, _, _ = post(payload,
                                {"traceparent": slow_traceparent})
            assert status == 200
        finally:
            r_faults.disable()

        # exemplars render only on a negotiated OpenMetrics scrape;
        # a plain 0.0.4 scrape must stay free of the suffix syntax
        _, plain_text = get("/metrics")
        validate_prometheus_text(plain_text)
        assert not any(" # " in line
                       for line in plain_text.splitlines()), \
            "plain text-format scrape leaked OpenMetrics exemplars"
        _, metrics_text = get(
            "/metrics",
            {"Accept": "application/openmetrics-text"})
        validate_prometheus_text(metrics_text)
        assert any("serving_total_seconds_bucket" in line
                   and " # " in line and slow_trace_id in line
                   for line in metrics_text.splitlines()), \
            "no latency-bucket exemplar carries the slow request's " \
            "trace id"

        tail_path = os.path.join(workdir, "tail.json")
        server.tail.dump(tail_path)
        rendered = render_tail(tail_path)
        for needed in ("serving/queue_wait", "serving/device_execute",
                       slow_trace_id):
            assert needed in rendered, \
                "%s missing from --tail render:\n%s" % (needed,
                                                        rendered)
        status, tail_body = get("/debug/tail")
        assert status == 200 and \
            validate_tail_dump(json.loads(tail_body))["requests"]

        # contract 3: error replies still carry the request_id
        server.draining = True
        status, body, _ = post(payload)
        server.draining = False
        assert status == 503 and body.get("request_id"), body
        error_request_id = body["request_id"]
    finally:
        server.shutdown()
    return {"trace_id": slow_trace_id, "tail_path": tail_path,
            "error_request_id": error_request_id}


def _health_flight_tiny(workdir):
    """The diagnosis loop end to end: a deliberately-NaN step makes the
    NumericsMonitor count, locate_nonfinite names the offending op, and
    an induced crash leaves a flight bundle this CLI can render."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.obs import flight as obs_flight
    from paddle_tpu.obs import health as obs_health
    from paddle_tpu.utils import flags

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=3)
        cost = fluid.layers.mean(x=h)
        _, pg = fluid.optimizer.SGDOptimizer(
            learning_rate=0.1).minimize(cost)
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        monitor = obs_health.NumericsMonitor.for_train_program(
            main_prog, cost=cost, params_grads=pg).install()
        bad = np.full((2, 4), np.nan, np.float32)
        outs = exe.run(main_prog, feed={"x": bad},
                       fetch_list=[cost] + monitor.fetch_names)
        summary = monitor.record(dict(zip(monitor.fetch_names,
                                          outs[1:])))
        assert summary["found_nonfinite"], summary
        report = obs_health.locate_nonfinite(main_prog, {"x": bad},
                                             scope=scope)
        assert report and report["op_type"], report

        # induced crash through the executor's exception hook
        recorder = obs_flight.install(out_dir=workdir, capacity=8)
        flag_prev = flags.get_flag("check_nan_inf")
        flags.set_flag("check_nan_inf", True)
        try:
            exe.run(main_prog, feed={"x": bad}, fetch_list=[cost],
                    eager=True, use_program_cache=False)
            raise AssertionError("NaN feed did not trip check_nan_inf")
        except fluid.executor.NonfiniteError:
            pass
        finally:
            flags.set_flag("check_nan_inf", flag_prev)
            obs_flight.uninstall()
    bundle = recorder.last_bundle_path
    assert bundle and os.path.exists(bundle), "no flight bundle written"
    rendered = render_flight(bundle)
    assert "NonfiniteError" in rendered
    return report, bundle


def selftest(args):
    # the selftest must never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.obs import trace as obs_trace

    from paddle_tpu.utils import flags as pt_flags

    workdir = tempfile.mkdtemp(prefix="paddle_obs_")
    obs_trace.enable(clear=True)
    # exercise the memory/cost attribution path (off by default; the
    # serving warmup and bench suite enable it in production)
    attr_prev = pt_flags.get_flag("xla_cost_attribution")
    pt_flags.set_flag("xla_cost_attribution", True)
    try:
        _train_tiny_v2()
        metrics = _serve_tiny()
        tracing_report = _trace_serve_tiny(workdir)
        health_report, flight_bundle = _health_flight_tiny(workdir)
    finally:
        pt_flags.set_flag("xla_cost_attribution", attr_prev)
        obs_trace.disable()

    # --- trace side: valid Chrome JSON, nested executor+trainer spans
    trace_path = args.trace_out or os.path.join(workdir, "trace.json")
    obs_trace.export_chrome_trace(trace_path)
    events = validate_chrome_trace(trace_path)
    steps = _find_span(events, "v2/step")
    runs = _find_span(events, "executor/run")
    segs = _find_span(events, "executor/jit_segment")
    serving_spans = _find_span(events, "serving/engine_run")
    assert steps, "no trainer spans in trace"
    assert runs, "no executor spans in trace"
    assert segs, "no jit-segment spans in trace"
    assert serving_spans, "no serving spans in trace"
    assert any(_nested_within(st, r) for st in steps for r in runs), \
        "executor/run span not nested inside a v2/step span"
    assert any(_nested_within(r, sg) for r in runs for sg in segs), \
        "jit-segment span not nested inside an executor/run span"

    # --- metrics side: ONE registry render carries all three layers
    text = metrics.render_text()  # unified render via ServingMetrics
    names = validate_prometheus_text(text)
    for needed in ("executor_runs_total", "executor_jit_traces_total",
                   "trainer_steps_total", "trainer_step_seconds",
                   "serving_compile_cache_miss_total",
                   "serving_compile_cache_hit_total"):
        # histograms expose only _bucket/_sum/_count sample names
        assert any(n == needed or n.startswith(needed + "_")
                   for n in names), \
            "%s missing from unified exposition:\n%s" % (needed, text)
    assert obs_tele.jit_trace_count() > 0
    assert obs_tele.transfer_bytes("h2d") > 0

    # --- health side: the NaN loop counted, and the compile-time
    # memory/cost attribution landed as per-segment xla_* gauges
    # (graceful skip where the runtime exposes no analyses)
    snap = obs_tele.snapshot()
    assert any(k.startswith("numerics_nonfinite_total{") and v > 0
               for k, v in snap.items()), \
        "NaN run left no numerics_nonfinite_total samples"
    xla_gauges = sorted({k.split("{", 1)[0] for k in snap
                         if k.startswith("xla_")})
    if not xla_gauges:
        print("[obs] note: runtime exposes no XLA memory/cost "
              "analyses; xla_* gauges skipped", flush=True)

    # the same data is exportable as JSONL for offline diffing
    jsonl = obs_registry.get_registry().render_jsonl()
    for line in jsonl.strip().splitlines():
        json.loads(line)

    if args.metrics_out:
        _write_metrics(args, text if args.format == "prom" else jsonl)
    print("[obs] selftest green: %d trace events (%d trainer steps, "
          "%d executor runs, %d jit segments, %d serving spans), "
          "unified /metrics has %d metric families, xla gauges %s, "
          "first nonfinite op %r, flight bundle at %s, trace at %s; "
          "tracing leg: exemplar trace %s in /metrics, tail dump at "
          "%s, error reply request_id %s"
          % (len(events), len(steps), len(runs), len(segs),
             len(serving_spans), len(names),
             ",".join(xla_gauges) or "n/a",
             health_report["op_type"], flight_bundle, trace_path,
             tracing_report["trace_id"], tracing_report["tail_path"],
             tracing_report["error_request_id"]),
          flush=True)
    return 0


# ---------------------------------------------------------------------------
# plain dump modes
# ---------------------------------------------------------------------------

def _write_metrics(args, payload):
    if args.metrics_out == "-":
        sys.stdout.write(payload)
        return
    with open(args.metrics_out, "w") as f:
        f.write(payload)


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.check:
        events = validate_chrome_trace(args.check)
        print("[obs] %s: valid Chrome trace with %d events"
              % (args.check, len(events)), flush=True)
        return 0
    if args.flight:
        print(render_flight(args.flight), flush=True)
        return 0
    if args.tail:
        print(render_tail(args.tail), flush=True)
        return 0
    if not args.trace_out and not args.metrics_out:
        raise SystemExit("nothing to do: pass --selftest, --check, "
                         "--flight, --tail, --trace-out and/or "
                         "--metrics-out")
    from paddle_tpu.obs import registry as obs_registry
    from paddle_tpu.obs import trace as obs_trace

    if args.trace_out:
        doc = obs_trace.export_chrome_trace(args.trace_out)
        n = sum(1 for e in doc["traceEvents"] if e["ph"] != "M")
        print("[obs] wrote trace: %s (%d events)%s"
              % (args.trace_out, n,
                 "" if n else " — EMPTY: dump modes export THIS "
                 "process's state; call obs_dump.main() in-process "
                 "after obs.trace.tracing()"), flush=True)
    if args.metrics_out:
        reg = obs_registry.get_registry()
        _write_metrics(args, reg.render_text() if args.format == "prom"
                       else reg.render_jsonl())
        if args.metrics_out != "-":
            print("[obs] wrote metrics: %s" % args.metrics_out,
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
