"""pelastic — elastic data-parallel training CLI + chaos drill.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.elastic_cli --selftest

    # one elastic worker process (the multi-process drill spawns two):
    python -m paddle_tpu.tools.elastic_cli worker \
        --master 127.0.0.1:7164 --host w0 --ckpt-root /tmp/ck \
        --status /tmp/w0.json --steps 40

    # pin the densify-restore reassembly cost (8 shards -> 4 shards):
    python -m paddle_tpu.tools.elastic_cli densify-bench

`--selftest` certifies the elastic contract end to end, three phases:

  1. **protocol** — three in-process members bootstrap a view over a
     real native master; one member's heartbeat is killed, its lease
     expires, and the survivors commit a SHRINK at a higher
     generation (with an injected `elastic/propose` IOError retried
     along the way); the dead member rejoins and a GROW commits.
  2. **resize** — a single-process simulated fleet (2 hosts × 4 CPU
     devices) trains an MLP with zero1 state on a dp=8 mesh; losing a
     host REALLY rebuilds the mesh at dp=4 and restores the sharded
     snapshot through the densify path; the rejoin grows back to dp=8.
     The densify-bench measurement runs here too.
  3. **chaos** — two real worker processes on the simulated 8-device
     CPU mesh; a fault plan inside one delivers a real SIGTERM
     mid-step (`elastic/step:preempt`), the survivor commits a new
     generation and continues at dp−1 with finite losses, restoring
     shard-exact (`densified == []` — the layout held); a respawned
     worker triggers the grow back.  The survivor's status file must
     show `elastic_resizes_total`-equivalent history of EXACTLY one
     shrink and one grow.

See docs/DISTRIBUTED.md ("Elastic training") for the protocol and the
runbook.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

GLOBAL_BATCH = 16
DIM = 8
CLASSES = 4


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pelastic")
    p.add_argument("--selftest", action="store_true",
                   help="elastic certification: protocol drill + "
                        "simulated-fleet resize + 2-process chaos "
                        "drill")
    sub = p.add_subparsers(dest="cmd")

    w = sub.add_parser("worker", help="run one elastic worker process")
    w.add_argument("--master", required=True,
                   help="host:port of the native master")
    w.add_argument("--host", required=True, help="this worker's host id")
    w.add_argument("--ckpt-root", required=True)
    w.add_argument("--status", default=None,
                   help="path for per-step status JSON")
    w.add_argument("--steps", type=int, default=40)
    w.add_argument("--global-batch", type=int, default=GLOBAL_BATCH)
    w.add_argument("--min-hosts", type=int, default=1)
    w.add_argument("--save-every", type=int, default=3)
    w.add_argument("--step-sleep", type=float, default=0.0)
    w.add_argument("--ttl-ms", type=int, default=500)
    w.add_argument("--hidden", type=int, default=64)
    w.add_argument("--seed", type=int, default=7,
                   help="fault-plan seed")
    w.add_argument("--faults", default=None,
                   help="comma list of point:kind[:after[:times]] "
                        "(e.g. elastic/step:preempt:5:1)")

    b = sub.add_parser("densify-bench",
                       help="measure the 8-shard -> 4-shard densify "
                            "restore")
    b.add_argument("--from-dp", type=int, default=8)
    b.add_argument("--to-dp", type=int, default=4)
    b.add_argument("--vars", type=int, default=4)
    b.add_argument("--rows", type=int, default=1024)
    b.add_argument("--cols", type=int, default=256)

    return p.parse_args(argv)


def _builder(rows_fn, hidden):
    """build_fn for ElasticTrainer: an MLP classifier whose batch dim
    is re-derived from the committed view at every rebuild.  Same var
    names every call (reset_unique_name) so the rebuilt state dict
    lines up with the checkpointed one."""
    import paddle_tpu.fluid as fluid

    def build():
        rows = rows_fn()
        fluid.framework.reset_unique_name()
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[rows, DIM],
                                  dtype="float32",
                                  append_batch_size=False)
            label = fluid.layers.data(name="label", shape=[rows, 1],
                                      dtype="int64",
                                      append_batch_size=False)
            h = fluid.layers.fc(input=x, size=hidden, act="relu")
            logits = fluid.layers.fc(input=h, size=CLASSES, act=None)
            loss = fluid.layers.softmax_with_cross_entropy(logits,
                                                           label)
            avg = fluid.layers.mean(loss)
            fluid.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(avg)
        return main, startup, ["x", "label"], [avg.name]

    return build


def _make_feeds_fn(global_batch):
    import numpy as np

    def make_feeds(step, start, stop):
        # the FULL global batch is derived from the step alone, then
        # sliced — every member of any view feeds disjoint rows of the
        # same data, so a resize re-splits the same trajectory
        rs = np.random.RandomState(1000 + int(step))
        x = rs.rand(global_batch, DIM).astype(np.float32)
        label = rs.randint(0, CLASSES, size=(global_batch, 1)) \
            .astype(np.int64)
        return {"x": x[start:stop], "label": label[start:stop]}

    return make_feeds


def run_worker(args):
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.elastic import (ElasticMembership,
                                               feed_slice,
                                               run_elastic_worker)

    if args.faults:
        faults.enable(seed=args.seed)
        for item in args.faults.split(","):
            parts = item.strip().split(":")
            if len(parts) < 2:
                raise SystemExit("bad fault spec %r (want "
                                 "point:kind[:after[:times]])" % item)
            faults.inject(parts[0], parts[1],
                          after=int(parts[2]) if len(parts) > 2 else 0,
                          times=int(parts[3]) if len(parts) > 3 else 1)

    membership = ElasticMembership(args.master, host=args.host,
                                   ttl_ms=args.ttl_ms)

    def rows():
        start, stop = feed_slice(args.host, membership.view.hosts,
                                 args.global_batch)
        return stop - start

    try:
        summary = run_elastic_worker(
            membership, _builder(rows, args.hidden),
            _make_feeds_fn(args.global_batch), args.ckpt_root,
            steps=args.steps, global_batch=args.global_batch,
            min_hosts=args.min_hosts, save_every=args.save_every,
            status_path=args.status, step_sleep=args.step_sleep,
            local=True)
    finally:
        faults.disable()
        membership.close()
    print("[pelastic] worker %s done: %s" % (args.host, json.dumps(
        {k: summary[k] for k in ("host", "steps", "generation",
                                 "preempted")})), flush=True)
    return 0


def run_densify_bench(args):
    from paddle_tpu.spmd.checkpoint import measure_densify_restore

    root = tempfile.mkdtemp(prefix="pelastic_densify_")
    blob = measure_densify_restore(root, from_dp=args.from_dp,
                                   to_dp=args.to_dp, n_vars=args.vars,
                                   rows=args.rows, cols=args.cols)
    print(json.dumps(blob, sort_keys=True), flush=True)
    return 0


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _poll_converged(members, predicate, timeout=15.0, dead=()):
    """Drive every live member's protocol turn until `predicate(views)`
    holds (views keyed by host)."""
    deadline = time.time() + timeout
    while True:
        views = {}
        for m in members:
            if m in dead:
                continue
            try:
                views[m.host] = m.poll()
            except (IOError, OSError):
                views[m.host] = m.view  # injected fault: next turn
        if predicate(views):
            return views
        if time.time() >= deadline:
            raise AssertionError("protocol did not converge: %r"
                                 % views)
        time.sleep(0.03)


def _selftest_protocol():
    """Phase 1: bootstrap/shrink/grow of the bare membership protocol
    over a real master, with a lease ACTUALLY expiring (no
    survivor-side guesses) and an injected propose fault retried."""
    from paddle_tpu import native
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.elastic import ElasticMembership

    ttl = 300
    master = native.Master()
    members = []
    try:
        faults.enable(seed=3)
        # the FIRST propose attempt dies with an IOError; the leader's
        # next poll turn must retry and still converge
        propose_fault = faults.inject("elastic/propose", "io_error",
                                      times=1)
        for host in ("pa", "pb", "pc"):
            members.append(ElasticMembership(
                "127.0.0.1:%d" % master.port, host=host,
                ttl_ms=ttl).join())
        a, b, c = members

        _poll_converged(members, lambda vs: all(
            v.gen >= 1 and len(v.hosts) == 3 for v in vs.values()))
        assert propose_fault.fired == 1, \
            "elastic/propose fault never fired"
        gen0 = a.view.gen
        assert a.view.hosts == ["pa", "pb", "pc"]

        # pb stops heartbeating (NOT a graceful leave): only the TTL
        # reclaiming its lease may remove it from the live set
        b._member_lease._stop.set()
        b._member_lease._thread.join(timeout=5)
        _poll_converged(members, lambda vs: all(
            v.gen > gen0 and v.hosts == ["pa", "pc"]
            for h, v in vs.items() if h != "pb"), dead=(b,))
        assert a.view.reason == "host_lost", a.view
        gen1 = a.view.gen

        # pb rejoins (its orphaned lease must lapse first) -> grow
        b._member_lease = None
        b.join()
        _poll_converged(members, lambda vs: all(
            v.gen > gen1 and v.hosts == ["pa", "pb", "pc"]
            for v in vs.values()))
        assert a.view.reason == "rejoin", a.view
        assert a.view.gen > gen1 > gen0 >= 1
        return {"generations": [gen0, gen1, a.view.gen]}
    finally:
        faults.disable()
        for m in members:
            m.close()
        master.stop()


def _selftest_resize(workdir):
    """Phase 2: the simulated fleet — a REAL mesh shrink dp=8 -> dp=4
    with zero1 state restored through the densify path, then the grow
    back."""
    import numpy as np

    from paddle_tpu import native
    from paddle_tpu.resilience.elastic import (ElasticMembership,
                                               ElasticTrainer)

    ttl = 300
    master = native.Master()
    h0 = h1 = None
    try:
        h0 = ElasticMembership("127.0.0.1:%d" % master.port, host="h0",
                               ttl_ms=ttl).join()
        h1 = ElasticMembership("127.0.0.1:%d" % master.port, host="h1",
                               ttl_ms=ttl).join()
        et = ElasticTrainer(
            h0, _builder(lambda: GLOBAL_BATCH, 1024),
            os.path.join(workdir, "resize_ckpts"),
            devices_per_host=4, zero_stage=1)
        _poll_converged([h0, h1], lambda vs: all(
            v.gen >= 1 and len(v.hosts) == 2 for v in vs.values()))
        et.maybe_resize()
        assert et.dp == 8, et.dp

        def train(n, start_step):
            # one FIXED batch throughout (step 0's): across two mesh
            # rebuilds + restores the loss on it decreases iff the
            # optimizer state genuinely carried over each resize
            out = []
            for i in range(n):
                feeds = _make_feeds_fn(GLOBAL_BATCH)(0, 0, GLOBAL_BATCH)
                out.append(float(np.asarray(
                    et.step(feeds)[0]).reshape(-1)[0]))
            return out

        losses = train(4, 0)
        et.save(4)

        # h1 dies (heartbeat stops, lease expires) -> shrink to dp=4
        h1._member_lease._stop.set()
        h1._member_lease._thread.join(timeout=5)
        deadline = time.time() + 15
        shrink = None
        while shrink is None:
            assert time.time() < deadline, "shrink never committed"
            shrink = et.maybe_resize(save_step=4)
            time.sleep(0.03)
        assert shrink["direction"] == "shrink", shrink
        assert et.dp == 4, et.dp
        assert shrink["densified"], \
            "dp 8->4 with zero1 state should have densified " \
            "something: %r" % shrink
        losses += train(4, 4)
        et.save(8)

        # h1 rejoins -> grow back to dp=8 (densified again: 4->8)
        h1._member_lease = None
        h1.join()
        deadline = time.time() + 15
        grow = None
        while grow is None:
            assert time.time() < deadline, "grow never committed"
            h1.poll()  # the rejoiner must ack the grow proposal
            grow = et.maybe_resize(save_step=8)
            time.sleep(0.03)
        assert grow["direction"] == "grow", grow
        assert et.dp == 8, et.dp
        losses += train(4, 8)
        assert all(np.isfinite(l) for l in losses), losses
        assert losses[-1] < losses[0], losses
        return {"losses": losses, "shrink": shrink, "grow": grow}
    finally:
        for m in (h0, h1):
            if m is not None:
                m.close()
        master.stop()


def _read_status(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (IOError, OSError, ValueError):
        return None


def _wait_status(path, predicate, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = _read_status(path)
        if st is not None and predicate(st):
            return st
        time.sleep(0.05)
    raise AssertionError("timed out waiting for %s (last status: %r)"
                         % (what, _read_status(path)))


def _spawn_worker(master_port, host, workdir, steps, faults=None):
    status = os.path.join(workdir, "%s.status.json" % host)
    cmd = [sys.executable, "-m", "paddle_tpu.tools.elastic_cli",
           "worker", "--master", "127.0.0.1:%d" % master_port,
           "--host", host, "--ckpt-root",
           os.path.join(workdir, "ckpts"), "--status", status,
           "--steps", str(steps), "--min-hosts", "2",
           "--ttl-ms", "500", "--step-sleep", "0.08",
           "--save-every", "3"]
    if faults:
        cmd += ["--faults", faults]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS",
                   "--xla_force_host_platform_device_count=8")
    log = open(os.path.join(workdir, "%s.log" % host), "w")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=env)
    proc._log = log
    return proc, status


def _selftest_chaos(workdir):
    """Phase 3: two real worker processes; a fault plan inside w1
    delivers a real SIGTERM mid-step; the survivor shrinks and
    continues shard-exact; a respawned w1 grows the fleet back."""
    from paddle_tpu import native

    steps = 60
    master = native.Master()
    procs = []
    try:
        w0, st0 = _spawn_worker(master.port, "w0", workdir, steps)
        procs.append(w0)
        # w1's own fault plan raises a REAL SIGTERM at its 6th step
        w1, st1 = _spawn_worker(master.port, "w1", workdir, steps,
                                faults="elastic/step:preempt:5:1")
        procs.append(w1)

        # both bound to the 2-host view and stepping
        _wait_status(st0, lambda s: s["generation"] >= 1
                     and s["n_hosts"] == 2 and s["step"] >= 2,
                     90, "w0 to start on the 2-host view")
        _wait_status(st1, lambda s: s["generation"] >= 1
                     and s["n_hosts"] == 2 and s["step"] >= 2,
                     90, "w1 to start on the 2-host view")

        # the injected SIGTERM fires; w1 exits preempted, gracefully
        assert w1.wait(timeout=60) == 0, "preempted worker exit code"
        final1 = _wait_status(st1, lambda s: s.get("preempted"),
                              10, "w1's preempted status")

        # the survivor commits the shrink and keeps stepping at dp-1
        shrunk = _wait_status(
            st0, lambda s: s["n_hosts"] == 1 and any(
                r["direction"] == "shrink" for r in s["resizes"]),
            60, "w0 to commit the shrink")
        step_at_shrink = shrunk["step"]
        _wait_status(st0, lambda s: s["step"] > step_at_shrink + 1,
                     60, "w0 to keep training after the shrink")

        # a replacement registers under the same host id -> grow back
        w1b, st1 = _spawn_worker(master.port, "w1", workdir, steps)
        procs.append(w1b)
        _wait_status(
            st0, lambda s: s["n_hosts"] == 2 and any(
                r["direction"] == "grow" for r in s["resizes"]),
            90, "w0 to commit the grow")

        assert w0.wait(timeout=180) == 0, "w0 exit code"
        assert w1b.wait(timeout=180) == 0, "respawned w1 exit code"
        final0 = _read_status(st0)

        # the acceptance criterion: exactly one shrink and one grow in
        # the survivor's committed history, shard-exact restores
        # (the per-host layout held -> nothing densified), training
        # completed with finite losses at a bumped generation
        directions = [r["direction"] for r in final0["resizes"]]
        assert directions.count("shrink") == 1 \
            and directions.count("grow") == 1, directions
        for r in final0["resizes"]:
            assert r["densified"] == [], \
                "chaos-drill restore densified %r (layout held — " \
                "must be shard-exact)" % r
        assert final0["done"] and final0["step"] == steps, final0
        assert final0["generation"] >= 3, final0
        for st in (final0, final1):
            assert all(l is not None and l == l
                       for l in st["losses"]), st
        return {"w0": final0, "w1_preempted_at": final1["step"],
                "resizes": final0["resizes"]}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p._log.close()
        master.stop()


def selftest():
    workdir = tempfile.mkdtemp(prefix="pelastic_")

    proto = _selftest_protocol()
    print("[pelastic] phase 1 (protocol) green: generations %s "
          "(bootstrap -> lease-expiry shrink -> rejoin grow)"
          % proto["generations"], flush=True)

    resize = _selftest_resize(workdir)
    print("[pelastic] phase 2 (resize) green: dp 8->4->8, shrink "
          "densified %d var(s), grow densified %d, loss %.4f -> %.4f"
          % (len(resize["shrink"]["densified"]),
             len(resize["grow"]["densified"]),
             resize["losses"][0], resize["losses"][-1]), flush=True)

    from paddle_tpu.spmd.checkpoint import measure_densify_restore

    bench = measure_densify_restore(
        os.path.join(workdir, "densify_bench"))
    assert bench["verified"] and bench["densified"] == bench["n_vars"]
    print("[pelastic] densify-bench: %s"
          % json.dumps(bench, sort_keys=True), flush=True)

    chaos = _selftest_chaos(workdir)
    print("[pelastic] phase 3 (chaos) green: w1 SIGTERM'd at step %d "
          "by its fault plan, survivor resized %s and finished %d "
          "steps at generation %d (workdir %s)"
          % (chaos["w1_preempted_at"],
             [(r["direction"], r["generation"])
              for r in chaos["resizes"]],
             chaos["w0"]["step"], chaos["w0"]["generation"], workdir),
          flush=True)

    # the in-process registry saw both directions (phases 1+2)
    from paddle_tpu.obs import telemetry as obs_tele

    snap = obs_tele.snapshot()
    shrinks = sum(v for k, v in snap.items()
                  if k.startswith("elastic_resizes_total{")
                  and "direction=shrink" in k)
    grows = sum(v for k, v in snap.items()
                if k.startswith("elastic_resizes_total{")
                and "direction=grow" in k)
    assert shrinks >= 1 and grows >= 1, snap
    print("[pelastic] selftest green: elastic_resizes_total "
          "shrink=%d grow=%d, elastic_generation=%s"
          % (shrinks, grows, snap.get("elastic_generation")),
          flush=True)
    return 0


def main(argv=None):
    # elastic drills must never contend for a real accelerator, and
    # the simulated fleet needs its 8 virtual CPU devices
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    args = parse_args(argv)
    if args.cmd == "worker":
        os.environ.setdefault("PADDLE_FLEET_HOST", args.host)
        return run_worker(args)
    if args.cmd == "densify-bench":
        return run_densify_bench(args)
    if args.selftest:
        return selftest()
    parse_args(["--help"])
    return 2


if __name__ == "__main__":
    sys.exit(main())
