"""pshard — SPMD partition-plan CLI (paddle_tpu.spmd).

    # build the partition-plan artifact for a model x mesh: run the
    # static sharding analyzer (rules layered over the param_spec
    # heuristics), print the layout summary, save the JSON document
    # the trainer / pcache key / CI consume
    pshard plan --model lenet5 --mesh dp=4,mp=2 --batch 64 \\
                [--rules rules.json] [--zero-stage 1] [--out plan.json]

    # render a saved plan artifact (layout, comm floor, diagnostics)
    pshard show --plan plan.json

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh)
    pshard --selftest

`plan` needs ZERO devices: the analyzer works on a static MeshConfig,
so a dev box can pre-compute and review the 256-chip layout the job
will launch with.  `--selftest` proves the whole loop on whatever
devices exist (CI provisions 8 virtual CPU devices): rule matching
precedence, a plan build whose rules change the layout, save/load
round-trip with a stable fingerprint, a REAL SpmdTrainer step driven
by the loaded plan, and a sharded checkpoint save -> restore with
zero densified vars.
"""

import argparse
import json
import os
import sys
import tempfile


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pshard")
    p.add_argument("cmd", nargs="?", choices=["plan", "show"],
                   help="plan: build + save the partition plan; "
                        "show: render a saved plan")
    p.add_argument("--model", default="lenet5",
                   help="tune/models name (default lenet5)")
    p.add_argument("--mesh", default="dp=8",
                   help="mesh spec, e.g. dp=4,mp=2 (default dp=8)")
    p.add_argument("--batch", type=int, default=64,
                   help="global batch the plan is built for")
    p.add_argument("--rules", default=None,
                   help="partition-rules JSON path "
                        "(spmd.plan.load_rules format)")
    p.add_argument("--zero-stage", type=int, default=0,
                   choices=[0, 1],
                   help="zero1 optimizer-state sharding")
    p.add_argument("--out", default=None,
                   help="write the plan JSON here")
    p.add_argument("--plan", default=None,
                   help="saved plan path (for `show`)")
    p.add_argument("--selftest", action="store_true",
                   help="prove the plan->train->checkpoint loop")
    return p.parse_args(argv)


def _build_program(model, batch):
    from ..tune import models as tune_models

    return tune_models.builder(model, with_startup=True)(batch)


def cmd_plan(args):
    from ..parallel.mesh import parse_mesh_spec
    from ..spmd.plan import build_partition_plan, load_rules

    main, _startup, loss_name = _build_program(args.model, args.batch)
    mesh = parse_mesh_spec(args.mesh)
    rules = load_rules(args.rules) if args.rules else None
    # print the findings instead of raising: the CLI is the review
    # surface, a human reads the S0xx lines and fixes the layout
    plan = build_partition_plan(
        main, mesh, ["image", "label"], [loss_name], rules=rules,
        zero_stage=args.zero_stage, model=args.model,
        raise_on_error=False)
    print(plan.summary())
    if args.out:
        plan.save(args.out)
        print("plan written to %s (fingerprint %s)"
              % (args.out, plan.fingerprint()))
    errors = [d for d in plan.diagnostics
              if d.get("severity") == "error"]
    return 1 if errors else 0


def cmd_show(args):
    from ..spmd.plan import PartitionPlan

    if not args.plan:
        raise SystemExit("pshard show needs --plan <path>")
    plan = PartitionPlan.load(args.plan)
    print(plan.summary())
    print("fingerprint: %s" % plan.fingerprint())
    return 0


def selftest(args):
    import numpy as np

    from ..parallel.mesh import parse_mesh_spec
    from ..spmd.plan import (PartitionPlan, build_partition_plan,
                             load_rules, match_partition_rules)

    failures = []

    def check(name, ok, detail=""):
        print("  %-44s %s%s" % (name, "PASS" if ok else "FAIL",
                                (" " + detail if detail else "")))
        if not ok:
            failures.append(name)

    print("pshard selftest:")

    # 1. rule matching: first match wins, full-name anchoring
    rules = load_rules([[r"fc_.*\.w_0", ["mp", None]],
                        [r".*\.w_0", [None, "mp"]]])
    check("rule precedence (first match wins)",
          match_partition_rules(rules, "fc_1.w_0")[0] == ("mp", None)
          and match_partition_rules(rules, "conv0.w_0")[0]
          == (None, "mp")
          and match_partition_rules(rules, "fc_1.b_0")
          == (None, None))

    # 2. plan build on a static mesh (no devices), rules change layout
    main, startup, loss_name = _build_program("lenet5", 32)
    mesh = parse_mesh_spec("dp=2,mp=2")
    base = build_partition_plan(main, mesh, ["image", "label"],
                                [loss_name], model="lenet5")
    ruled = build_partition_plan(
        main, mesh, ["image", "label"], [loss_name],
        rules=load_rules([[r"fc_.*\.w_0", ["mp", None]]]),
        model="lenet5")
    moved = [n for n in ruled.sharded_params()
             if n.startswith("fc_") and n.endswith(".w_0")
             and tuple(ruled.var_specs[n])[0] == "mp"]
    check("rules reshape the layout", bool(moved),
          "fc w_0 -> %s" % (moved and
                            list(ruled.var_specs[moved[0]])))
    check("plan fingerprints differ under rules",
          base.fingerprint() != ruled.fingerprint())

    # 3. save/load round-trip, fingerprint stable
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "plan.json")
        ruled.save(path)
        loaded = PartitionPlan.load(path)
        check("save/load round-trip keeps the fingerprint",
              loaded.fingerprint() == ruled.fingerprint())
        check("round-trip keeps every var spec",
              loaded.var_specs == ruled.var_specs)

    # 4. a REAL plan-driven training step + sharded checkpoint on
    # whatever devices exist (CI provisions 8 virtual CPU devices)
    import jax

    from ..parallel.mesh import make_mesh
    from ..spmd.trainer import SpmdTrainer

    n = len(jax.devices())
    mesh = make_mesh(dp=n)
    batch = 4 * n
    main, startup, loss_name = _build_program("lenet5", batch)
    trainer = SpmdTrainer(main, startup, ["image", "label"],
                          [loss_name], mesh, model="lenet5",
                          use_pcache=False)
    trainer.init()
    rs = np.random.RandomState(7)
    feeds = {"image": rs.rand(batch, 1, 28, 28).astype(np.float32),
             "label": rs.randint(0, 10, size=(batch, 1))
             .astype(np.int64)}
    (loss0,) = trainer.step(feeds)
    (loss1,) = trainer.step(feeds)
    loss0 = float(np.ravel(np.asarray(loss0))[0])
    loss1 = float(np.ravel(np.asarray(loss1))[0])
    check("plan-driven step trains (%d device(s))" % n,
          np.isfinite(loss0) and loss1 < loss0,
          "loss %.4f -> %.4f" % (loss0, loss1))

    with tempfile.TemporaryDirectory() as tmp:
        trainer.save_checkpoint(tmp, step=2)
        fresh = SpmdTrainer(main, startup, ["image", "label"],
                            [loss_name], mesh, model="lenet5",
                            use_pcache=False)
        fresh.init()
        info = fresh.restore_checkpoint(tmp)
        same = all(
            np.allclose(np.asarray(fresh.state[k]),
                        np.asarray(trainer.state[k]))
            for k in trainer.state)
        check("sharded checkpoint round-trip, nothing densified",
              info["step"] == 2 and not info["densified"] and same)

    if failures:
        print("pshard selftest: FAIL (%s)" % ", ".join(failures))
        return 1
    print("pshard selftest: green")
    return 0


def main(argv=None):
    args = parse_args(argv)
    if args.selftest:
        return selftest(args)
    if args.cmd == "plan":
        return cmd_plan(args)
    if args.cmd == "show":
        return cmd_show(args)
    raise SystemExit("nothing to do: pass plan|show or --selftest")


if __name__ == "__main__":
    sys.exit(main())
