"""Persistent compile cache CLI ("pcc"): stats / prewarm / gc /
selftest for `paddle_tpu.compile`.

    # the CI entry point (scripts/ci.sh, scripts/smoke.sh):
    python -m paddle_tpu.tools.pcache_cli --selftest

    # operator surface (docs/COMPILE_CACHE.md has the runbook):
    python -m paddle_tpu.tools.pcache_cli stats   --cache-dir /ssd/pcc
    python -m paddle_tpu.tools.pcache_cli gc      --cache-dir /ssd/pcc \
        --max-bytes 1073741824
    python -m paddle_tpu.tools.pcache_cli prewarm --cache-dir /ssd/pcc \
        --model-dir /models/resnet50

`--selftest` certifies the compile subsystem end to end:

  1. **cold compile populates the cache** — a lenet5 forward runs with
     the cache enabled; every jitted segment AOT-compiles once and
     lands on disk;
  2. **restart-simulated reload hits** — fresh Programs, a fresh
     Executor and a fresh Scope (everything a process restart clears)
     re-run the same content: `executor_jit_traces_total` must NOT
     move (zero new XLA compiles) and outputs must be bit-identical
     to the cold run;
  3. **corruption quarantines, never crashes** — an entry is
     bit-flipped on disk; the next run must detect it (CRC), move it
     to quarantine, recompile, and still produce correct output;
  4. **rewrite passes preserve semantics** — pass-optimized vs
     unoptimized lenet5 forward outputs are bit-identical with the
     verifier green before/after every pass, a crafted program proves
     each pass (dce/fold/cse/dve) actually rewrites, and pass-config
     changes change the fingerprint (no cache aliasing).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="pcc")
    p.add_argument("cmd", nargs="?",
                   choices=["stats", "prewarm", "gc"],
                   help="operator command (or use --selftest)")
    p.add_argument("--selftest", action="store_true",
                   help="compile-cache + rewrite-pass certification")
    p.add_argument("--cache-dir", default=None,
                   help="cache root (default: FLAGS_compile_cache_dir)")
    p.add_argument("--model-dir", default=None,
                   help="prewarm: a save_inference_model export to "
                        "compile through the serving engine")
    p.add_argument("--max-bytes", type=int, default=None,
                   help="gc: override the LRU size cap")
    p.add_argument("--keep-quarantine", action="store_true",
                   help="gc: do not clear the quarantine directory")
    p.add_argument("--passes", default="default",
                   help="prewarm/selftest rewrite pipeline spec")
    p.add_argument("--explain", action="store_true",
                   help="selftest/prewarm: dump the per-pass rewrite "
                        "diff")
    p.add_argument("--json", action="store_true",
                   help="stats/gc: machine-readable output")
    return p.parse_args(argv)


def _cache(args):
    from paddle_tpu.compile import pcache
    from paddle_tpu.utils import flags

    root = args.cache_dir or flags.get_flag("compile_cache_dir")
    if not root:
        raise SystemExit("no cache dir: pass --cache-dir or set "
                         "FLAGS_compile_cache_dir")
    return pcache.PersistentCache(root)


def cmd_stats(args):
    stats = _cache(args).stats()
    if args.json:
        print(json.dumps(stats, indent=1, sort_keys=True))
    else:
        print("[pcc] %(root)s: %(entries)d entries, %(bytes)d bytes "
              "(cap %(max_bytes)d), %(quarantined)d quarantined"
              % stats)
    return 0


def cmd_gc(args):
    summary = _cache(args).gc(
        max_bytes=args.max_bytes,
        clear_quarantine=not args.keep_quarantine)
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print("[pcc] gc: evicted %(evicted)d, cleared %(quarantine_"
              "cleared)d quarantined; now %(entries)d entries / "
              "%(bytes)d bytes" % summary)
    return 0


def cmd_prewarm(args):
    """Populate the cache by compiling a saved inference model through
    the serving engine's warmup (every batch bucket), so the NEXT
    process — the real deploy — starts warm."""
    from paddle_tpu.compile import pcache
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.utils import flags

    if not args.model_dir:
        raise SystemExit("prewarm needs --model-dir (a "
                         "save_inference_model export)")
    root = args.cache_dir or flags.get_flag("compile_cache_dir")
    if not root:
        raise SystemExit("no cache dir: pass --cache-dir or set "
                         "FLAGS_compile_cache_dir")
    flags.set_flag("compile_cache_dir", root)
    if args.passes:
        flags.set_flag("compile_passes", args.passes)
    t0 = time.perf_counter()
    traces0 = obs_tele.jit_trace_count()
    engine = InferenceEngine.from_saved_model(args.model_dir)
    warmed = engine.warmup()
    dt = time.perf_counter() - t0
    compiles = obs_tele.jit_trace_count() - traces0
    stats = pcache.get_cache().stats()
    print("[pcc] prewarmed %d bucket(s) in %.1fs (%d fresh XLA "
          "compile(s)); cache now %d entries / %d bytes"
          % (warmed, dt, compiles, stats["entries"], stats["bytes"]))
    return 0


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def _fresh_workspace():
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.fluid import framework

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    scope_mod._global_scope = scope_mod.Scope()


def _build_lenet5_forward():
    """lenet5 forward in a fresh Program pair — built identically on
    every call (deterministic names), the restart-simulation
    property the fingerprint relies on."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.image import lenet5

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        probs = lenet5(img, class_dim=10)
    return main, startup, probs.name


def _run_forward(main, startup, probs_name, img):
    import numpy as np

    from paddle_tpu.core.scope import Scope
    from paddle_tpu.fluid import executor as executor_mod

    exe = executor_mod.Executor(executor_mod.CPUPlace())
    with executor_mod.scope_guard(Scope()):
        exe.run(startup)
        out = exe.run(main, feed={"img": img},
                      fetch_list=[probs_name])[0]
    return np.asarray(out)


def _selftest_cache(workdir, report):
    import numpy as np

    from paddle_tpu.compile import pcache
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.utils import flags

    cache_dir = os.path.join(workdir, "cache")
    flags.set_flag("compile_cache_dir", cache_dir)
    pcache.reset()
    rng = np.random.RandomState(0)
    img = rng.rand(4, 1, 28, 28).astype(np.float32)

    # 1. cold compile populates the cache
    _fresh_workspace()
    t0 = time.perf_counter()
    traces0 = obs_tele.jit_trace_count()
    main, startup, probs = _build_lenet5_forward()
    out_cold = _run_forward(main, startup, probs, img)
    cold_s = time.perf_counter() - t0
    cold_compiles = obs_tele.jit_trace_count() - traces0
    stats = pcache.get_cache().stats()
    assert cold_compiles > 0, "cold run compiled nothing"
    assert stats["entries"] > 0, "cold run stored nothing: %s" % stats

    # 2. restart-simulated reload: fresh programs/executor/scope must
    #    serve every segment from disk — ZERO new XLA compiles
    _fresh_workspace()
    pcache.reset()  # drop the in-process handle too
    t0 = time.perf_counter()
    traces1 = obs_tele.jit_trace_count()
    main, startup, probs = _build_lenet5_forward()
    out_warm = _run_forward(main, startup, probs, img)
    warm_s = time.perf_counter() - t0
    warm_compiles = obs_tele.jit_trace_count() - traces1
    assert warm_compiles == 0, \
        "warm reload performed %d XLA compile(s); cache missed" \
        % warm_compiles
    np.testing.assert_array_equal(out_cold, out_warm)
    snap = obs_tele.snapshot()
    assert snap.get("compile_cache_hits_total", 0) >= cold_compiles, \
        "expected >=%d disk hits: %s" % (cold_compiles, snap)

    # 3. a corrupt entry is quarantined, not fatal
    entry = None
    for sub in sorted(os.listdir(os.path.join(cache_dir, "entries"))):
        d = os.path.join(cache_dir, "entries", sub)
        for f in sorted(os.listdir(d)):
            if f.endswith(".ptx"):
                entry = os.path.join(d, f)
                break
        if entry:
            break
    assert entry, "no cache entry on disk"
    blob = bytearray(open(entry, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(entry, "wb") as f:
        f.write(bytes(blob))
    _fresh_workspace()
    pcache.reset()
    errors0 = snap.get("compile_cache_errors_total{kind=corrupt}", 0)
    main, startup, probs = _build_lenet5_forward()
    out_fixed = _run_forward(main, startup, probs, img)
    np.testing.assert_array_equal(out_cold, out_fixed)
    snap = obs_tele.snapshot()
    assert snap.get("compile_cache_errors_total{kind=corrupt}",
                    0) > errors0, "corruption was not detected"
    qdir = os.path.join(cache_dir, "quarantine")
    assert any(f.endswith(".ptx") for f in os.listdir(qdir)), \
        "corrupt entry was not quarantined"

    flags.set_flag("compile_cache_dir", "")
    pcache.reset()
    report["cold_s"] = round(cold_s, 3)
    report["warm_s"] = round(warm_s, 3)
    report["cold_compiles"] = cold_compiles
    report["entries"] = stats["entries"]
    print("[pcc] cache leg green: %d segment(s) cold-compiled in "
          "%.1fs -> restart reload in %.1fs with 0 XLA compiles, "
          "bit-identical outputs; corrupt entry quarantined"
          % (cold_compiles, cold_s, warm_s), flush=True)


def _selftest_passes(args, report):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.compile import fingerprint, passes

    # 4a. lenet5 forward: optimized vs unoptimized, bit-identical;
    #     the PassManager re-verifies around every pass (verify=True
    #     is the default; "full" re-derives every op meta)
    rng = np.random.RandomState(0)
    img = rng.rand(4, 1, 28, 28).astype(np.float32)
    _fresh_workspace()
    main, startup, probs = _build_lenet5_forward()
    pm = passes.PassManager(args.passes, verify_level="full",
                            explain=args.explain)
    optimized = pm.run(main, fetches=[probs])
    out_plain = _run_forward(main, startup, probs, img)
    out_opt = _run_forward(optimized, startup, probs, img)
    np.testing.assert_array_equal(out_plain, out_opt)
    if args.explain:
        print(pm.explain_text(), flush=True)

    # 4b. every pass proves it rewrites, on a crafted program
    _fresh_workspace()
    m2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(m2, s2):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.scale(x=x, scale=2.0)
        fluid.layers.scale(x=x, scale=9.0)          # dead (dce)
        y2 = fluid.layers.scale(x=x, scale=2.0)     # duplicate (cse)
        z = fluid.layers.elementwise_add(x=y, y=y2)
        blk = m2.global_block()
        sv = blk.create_var(name="shp_vec", dtype="int32", shape=[1])
        blk.append_op(type="shape", inputs={"Input": [y.name]},
                      outputs={"Out": [sv.name]},
                      infer_shape=False)             # foldable
        shp = fluid.layers.cast(x=sv, dtype="float32")
        fin = fluid.layers.elementwise_add(
            x=z, y=fluid.layers.reduce_sum(shp))
    pm2 = passes.PassManager("default", verify_level="full",
                             explain=True)
    o2 = pm2.run(m2, fetches=[fin.name])
    changed = {r["pass"]: r["changed"] for r in pm2.records}
    assert all(changed.values()), \
        "some pass rewrote nothing on the crafted program: %s" % changed
    xv = np.arange(4, dtype=np.float32)

    def run_feed_x(prog):
        from paddle_tpu.core.scope import Scope
        from paddle_tpu.fluid import executor as executor_mod

        exe = executor_mod.Executor(executor_mod.CPUPlace())
        with executor_mod.scope_guard(Scope()):
            exe.run(s2)
            return np.asarray(exe.run(prog, feed={"x": xv},
                                      fetch_list=[fin.name])[0])

    np.testing.assert_array_equal(run_feed_x(m2), run_feed_x(o2))

    # 4c. the pipeline id feeds the fingerprint: entries never alias
    #     across pass configs
    fp_plain = fingerprint.program_fingerprint(main, pipeline_id="")
    fp_piped = fingerprint.program_fingerprint(
        main, pipeline_id=pm.pipeline_id)
    assert fp_plain != fp_piped, "pipeline id did not change the key"

    report["passes"] = {r["pass"]: "%d->%d" % (r["ops_before"],
                                               r["ops_after"])
                        for r in pm2.records}
    print("[pcc] passes leg green: lenet5 forward bit-identical "
          "under %s (verifier green around every pass); crafted "
          "program rewritten by every pass (%s); pass config "
          "changes the cache key" % (pm.pipeline_id,
                                     report["passes"]), flush=True)


def _selftest_opt_passes(workdir, report):
    """5. the cost-model-guided opt pipeline x the persistent cache:
    a layout+fuse pipeline must produce a DIFFERENT cache key than
    `default` (knob settings included — entries never alias), and the
    optimized program must reload from disk with 0 fresh XLA compiles
    while staying bit-identical to the unoptimized forward."""
    import numpy as np

    from paddle_tpu.compile import pcache
    from paddle_tpu.compile import passes as passes_mod
    from paddle_tpu.obs import telemetry as obs_tele
    from paddle_tpu.utils import flags

    spec = "default+layout:force=1+fuse"
    ids = {passes_mod.pipeline_id("default"),
           passes_mod.pipeline_id(spec),
           passes_mod.pipeline_id(spec + ":cap=2")}
    assert len(ids) == 3, \
        "pipeline ids alias across pass/knob configs: %s" % ids

    rng = np.random.RandomState(0)
    img = rng.rand(4, 1, 28, 28).astype(np.float32)
    cache_dir = os.path.join(workdir, "optcache")
    try:
        # the unoptimized reference output first (no cache, no passes)
        _fresh_workspace()
        main, startup, probs = _build_lenet5_forward()
        out_plain = _run_forward(main, startup, probs, img)

        flags.set_flag("compile_cache_dir", cache_dir)
        flags.set_flag("compile_passes", spec)
        pcache.reset()
        _fresh_workspace()
        traces0 = obs_tele.jit_trace_count()
        main, startup, probs = _build_lenet5_forward()
        out_cold = _run_forward(main, startup, probs, img)
        cold = obs_tele.jit_trace_count() - traces0
        assert cold > 0, "optimized cold run compiled nothing"
        np.testing.assert_array_equal(out_plain, out_cold)

        _fresh_workspace()
        pcache.reset()
        traces1 = obs_tele.jit_trace_count()
        main, startup, probs = _build_lenet5_forward()
        out_warm = _run_forward(main, startup, probs, img)
        warm = obs_tele.jit_trace_count() - traces1
        assert warm == 0, \
            "optimized warm reload performed %d XLA compile(s)" % warm
        np.testing.assert_array_equal(out_cold, out_warm)
    finally:
        flags.set_flag("compile_cache_dir", "")
        flags.set_flag("compile_passes", "")
        pcache.reset()
    report["opt_pipeline"] = passes_mod.pipeline_id(spec)
    print("[pcc] opt-passes leg green: %s keys apart from default "
          "(and per knob), optimized program bit-identical and "
          "reloaded from disk with 0 fresh compiles"
          % passes_mod.pipeline_id(spec), flush=True)


def selftest(args):
    workdir = tempfile.mkdtemp(prefix="paddle_pcc_")
    report = {}
    try:
        _selftest_cache(workdir, report)
        _selftest_passes(args, report)
        _selftest_opt_passes(workdir, report)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    print("[pcc] selftest green: cold %ss -> warm %ss (%d segments), "
          "quarantine + rewrite contracts hold"
          % (report["cold_s"], report["warm_s"],
             report["cold_compiles"]), flush=True)
    return 0


def main(argv=None):
    args = parse_args(argv)
    # cache certification must never contend for a real accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.selftest:
        return selftest(args)
    if args.cmd == "stats":
        return cmd_stats(args)
    if args.cmd == "gc":
        return cmd_gc(args)
    if args.cmd == "prewarm":
        return cmd_prewarm(args)
    parse_args(["--help"])
    return 2


if __name__ == "__main__":
    sys.exit(main())
