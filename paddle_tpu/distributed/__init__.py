"""Distributed training (DCN/pserver path).

Two complementary mechanisms, matching the reference's split:

  * In-mesh data/model parallelism over ICI — `paddle_tpu.parallel`
    (pjit/shard_map; replaces NCCL ops and MultiGradientMachine's ring).
  * Parameter-server distribution over hosts — this package: a graph
    transpiler that rewrites the trainer program to ship gradients to
    native C++ pservers that run the optimizer server-side
    (reference: python/paddle/v2/fluid/distribute_transpiler.py:81,
    operators/send_op.cc, recv_op.cc, paddle/pserver/ParameterServer2,
    go/pserver/service.go).
"""

from .transpiler import (DistributeTranspiler, split_dense_variable,
                         run_pserver)

from .coordinator import (init_multihost, global_mesh, process_count,
                          process_index, ElasticRegistry, ServiceLease,
                          discover_pservers, start_fleet_reporter,
                          stop_fleet_reporter)

__all__ = ["DistributeTranspiler", "split_dense_variable", "run_pserver",
           "init_multihost", "global_mesh", "process_count",
           "process_index", "ElasticRegistry", "ServiceLease",
           "discover_pservers", "start_fleet_reporter",
           "stop_fleet_reporter"]
