"""Multi-host coordination: device-mesh init + elastic service registry.

Two coordination layers, mirroring the reference's split:

* Dense collective path — on TPU pods the runtime itself provides
  rendezvous: every host calls `jax.distributed.initialize` against one
  coordinator address and the PJRT client wires ICI/DCN
  (init_multihost / global_mesh below).

* Pserver path — the reference coordinates pservers through etcd:
  TTL-lease slot registration with keep-alive, desired-count
  rendezvous, and trainer-side re-discovery (reference:
  go/pserver/etcd_client.go:31-97 registration, client/etcd_client.go
  discovery, go/master/etcd_client.go leader lock).  Here the native
  master service carries an equivalent TTL-lease registry
  (native/master.cc kRegister/kKeepAlive/kList) and ElasticRegistry /
  ServiceLease below are the client surface: a pserver registers its
  endpoint under /ps/<slot> and heartbeats; when it dies, the lease
  lapses, discovery stops returning it, and a replacement can claim
  the slot and restore from checkpoint.

Env protocol (set by tools/cluster_launch.py or any scheduler):
    PADDLE_COORDINATOR   host:port of process 0
    PADDLE_NUM_PROCESSES world size
    PADDLE_PROCESS_ID    this host's rank
"""

import os
import threading
import time

from ..obs import registry as registry_mod
from ..resilience import faults as faults_mod
from ..resilience.retry import RetryPolicy

# keep-alive RPC latency buckets: sub-ms loopback beats up to the
# multi-second stalls that lapse a lease
HEARTBEAT_SECONDS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01,
                             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                             5.0)


def _heartbeat_hist():
    return registry_mod.get_registry().histogram(
        "coordinator_heartbeat_seconds", HEARTBEAT_SECONDS_BUCKETS,
        "lease keep-alive RPC latency (per attempt, including "
        "injected faults)")


def _heartbeat_failures():
    return registry_mod.get_registry().counter(
        "coordinator_heartbeat_failures_total",
        "keep-alive attempts that raised (retried within the beat "
        "budget before the lease lapses)")

__all__ = ["init_multihost", "global_mesh", "process_count",
           "process_index", "ElasticRegistry", "ServiceLease",
           "discover_pservers", "start_fleet_reporter",
           "stop_fleet_reporter"]


def discover_pservers(count=None, timeout=60.0, master=None):
    """Trainer-side pserver discovery through the registry (reference:
    go/pserver/client/etcd_client.go — trainers watch etcd for the
    pserver set).  Reads PADDLE_MASTER (host:port) and
    PADDLE_PSERVER_COUNT when args are omitted; returns endpoints
    ordered by slot after the desired-count rendezvous."""
    master = master or os.environ["PADDLE_MASTER"]
    if count is None:
        count = int(os.environ["PADDLE_PSERVER_COUNT"])
    host, port = master.rsplit(":", 1)
    reg = ElasticRegistry(host, int(port))
    try:
        return reg.wait_for_pservers(count, timeout=timeout)
    finally:
        reg.close()

_initialized = [False]
_fleet_reporter = [None]


def start_fleet_reporter(master=None, host=None, interval_s=2.0):
    """Start (or return) this process's fleet snapshot reporter
    (obs.fleet.FleetReporter): periodic registry snapshots pushed
    under /obs/<host> in the master's TTL-lease store, so an
    aggregator anywhere can merge per-host metrics and flag
    stragglers.  `master` defaults to $PADDLE_OBS_MASTER; returns
    None when neither is set (reporting is strictly opt-in)."""
    from ..obs import fleet as fleet_mod

    existing = _fleet_reporter[0]
    if existing is not None:
        # explicit args that contradict the running reporter must not
        # be silently dropped — the caller would believe snapshots
        # reach the master it named
        running = "%s:%d" % existing._master
        if (master is not None and str(master) != running) \
                or (host is not None and host != existing.host):
            raise RuntimeError(
                "fleet reporter already running (master %s, host %s); "
                "stop_fleet_reporter() before starting one for "
                "master=%r host=%r" % (running, existing.host,
                                       master, host))
        return existing
    master = master or os.environ.get(fleet_mod.MASTER_ENV)
    if not master:
        return None
    _fleet_reporter[0] = fleet_mod.FleetReporter(
        master, host=host, interval_s=interval_s).start()
    return _fleet_reporter[0]


def stop_fleet_reporter():
    rep = _fleet_reporter[0]
    _fleet_reporter[0] = None
    if rep is not None:
        rep.stop()
    return rep


def init_multihost(coordinator=None, num_processes=None, process_id=None,
                   local_device_ids=None):
    """Bring up the multi-host JAX runtime.  No-ops on single-host
    (nothing set and no args) so user scripts can call it
    unconditionally.  When the launcher exported PADDLE_OBS_MASTER
    (cluster_launch.py --elastic does), the fleet snapshot reporter
    starts alongside, so every multihost worker's metrics reach the
    aggregated /obs/ view without per-script wiring."""
    import jax

    coordinator = coordinator or os.environ.get("PADDLE_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_NUM_PROCESSES", "0")) \
            or None
    if process_id is None:
        pid = os.environ.get("PADDLE_PROCESS_ID")
        process_id = int(pid) if pid is not None else None

    start_fleet_reporter()
    if coordinator is None and num_processes in (None, 1):
        return False  # single host; jax is already usable
    if _initialized[0]:
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized[0] = True
    return True


def process_count():
    import jax

    return jax.process_count()


def process_index():
    import jax

    return jax.process_index()


class ServiceLease:
    """A held registration: renews its TTL lease on a daemon thread
    until released (the reference pserver's etcd keep-alive loop,
    go/pserver/etcd_client.go).  `lapsed` flips if a renewal finds the
    lease expired (e.g. the process stalled past the TTL) — the holder
    must re-register.

    `client` must be a connection DEDICATED to this lease: the
    heartbeat runs on its own thread and the framed transport is not
    thread-safe."""

    def __init__(self, client, lease_id, ttl_ms, retry=None,
                 reconnect=None):
        self._client = client
        self._lease = lease_id
        self._ttl_ms = ttl_ms
        # `reconnect` (zero-arg -> fresh dedicated client): the native
        # transport never recovers a failed fd, so a retried beat MUST
        # run on a new connection or the retry is dead weight
        self._reconnect = reconnect
        # transient connection blips within ONE beat retry quickly
        # instead of dropping the slot; the whole retry budget stays
        # under one beat interval so a genuinely dead master still
        # lapses the lease before the TTL reclaims it.  Renew at 1/3
        # TTL so one missed beat doesn't drop the slot.
        self._beat_interval = max(0.01, ttl_ms / 3000.0)
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay=0.01,
            max_delay=self._beat_interval / 4,
            deadline=self._beat_interval * 0.9,
            retryable=(ConnectionError, OSError),
            name="lease_heartbeat")
        self.lapsed = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def _one_beat(self):
        # timed per ATTEMPT (fault sleeps included): the histogram is
        # how an operator sees a master getting slow BEFORE leases
        # start lapsing — renewals run at 1/3 TTL, so p99 creeping
        # toward the beat interval is the early warning
        t0 = time.perf_counter()
        try:
            fired = faults_mod.check("coordinator/heartbeat")
            if fired is not None and fired.kind == "lease_expiry":
                # the deterministic host-death drill: stall past the
                # TTL so the master GENUINELY reclaims the lease —
                # the next keep_alive finds it lapsed, exactly like a
                # host that stopped heartbeating
                time.sleep(self._ttl_ms / 1000.0 * 1.5 + 0.05)
            alive = self._client.keep_alive(self._lease)
        except (ConnectionError, OSError):
            _heartbeat_failures().inc()
            _heartbeat_hist().observe(time.perf_counter() - t0)
            if self._reconnect is not None:
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = self._reconnect()
            raise
        _heartbeat_hist().observe(time.perf_counter() - t0)
        return alive

    def _beat(self):
        while not self._stop.wait(self._beat_interval):
            try:
                if not self._retry.call(self._one_beat):
                    self.lapsed = True
                    return
            except (ConnectionError, OSError):
                self.lapsed = True
                return

    def release(self):
        self._stop.set()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # heartbeat wedged inside a blocking call: the transport is
            # not thread-safe, so leak the connection rather than race
            # an in-flight keep_alive; the TTL reclaims the slot
            return
        try:
            self._client.unregister(self._lease)
        except ConnectionError:
            pass
        self._client.close()


class ElasticRegistry:
    """Service registration/discovery over the native master's
    TTL-lease store — the etcd-equivalent for pserver elasticity."""

    PS_PREFIX = "/ps/"

    def __init__(self, host, port, retry=None):
        from .. import native

        self._host, self._port = host, port
        # registry RPCs retry transient connection failures (master
        # restarting, dropped frames) before surfacing them
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5,
            retryable=(ConnectionError, OSError),
            name="registry_rpc")
        self._client = native.MasterClient(host, port)

    # -- registration ---------------------------------------------------
    def _register_rpc(self, key, value, ttl_ms):
        """One register attempt over a FRESH dedicated connection (a
        retried attempt must not reuse a connection whose framing died
        mid-RPC).  NOTE a retry after a lost reply can find the key
        held by our own orphaned lease — the TTL reclaims it within
        one `ttl_ms`, exactly like the reference's etcd CAS loop."""
        from .. import native

        faults_mod.check("coordinator/register", key=key)
        client = native.MasterClient(self._host, self._port)
        try:
            lease = client.register(key, value, ttl_ms)
        except BaseException:
            client.close()
            raise
        if lease is None:
            client.close()
            return None
        return client, lease

    def register(self, key, value, ttl_ms=2000):
        """Claim `key`; returns a ServiceLease, or None if a live lease
        holds the key.  The lease heartbeats over its own dedicated
        connection (the framed transport is not thread-safe)."""
        got = self._retry.call(self._register_rpc, key, value, ttl_ms)
        if got is None:
            return None
        client, lease = got

        def fresh_client():
            from .. import native

            return native.MasterClient(self._host, self._port)

        return ServiceLease(client, lease, ttl_ms,
                            reconnect=fresh_client)

    def register_pserver(self, endpoint, desired_count, ttl_ms=2000,
                         timeout=30.0):
        """Claim the first free pserver slot /ps/0../ps/N-1 (the
        reference's index-slot CAS loop, etcd_client.go:57-83),
        retrying until a slot frees up or `timeout` lapses.
        Returns (slot, ServiceLease)."""
        deadline = time.time() + timeout
        while True:
            for slot in range(desired_count):
                lease = self.register("%s%d" % (self.PS_PREFIX, slot),
                                      endpoint, ttl_ms=ttl_ms)
                if lease is not None:
                    return slot, lease
            if time.time() >= deadline:
                raise TimeoutError(
                    "no free pserver slot of %d within %.1fs"
                    % (desired_count, timeout))
            time.sleep(min(0.05, ttl_ms / 1000.0))

    # -- discovery ------------------------------------------------------
    def _list_rpc(self, prefix):
        faults_mod.check("coordinator/discover")
        try:
            return self._client.list_prefix(prefix)
        except (ConnectionError, OSError):
            # the native transport never recovers a failed fd: swap in
            # a fresh connection so the NEXT retry attempt can succeed
            from .. import native

            try:
                self._client.close()
            except Exception:
                pass
            self._client = native.MasterClient(self._host, self._port)
            raise

    def list(self, prefix):
        """{key: value} of unexpired leases under any `prefix` — the
        generic discovery surface the elastic membership protocol
        (resilience/elastic.py) reads views/acks/commits through, with
        the same retry + `coordinator/discover` fault point as pserver
        discovery."""
        return self._retry.call(self._list_rpc, prefix)

    def pservers(self):
        """{slot: endpoint} of live pservers."""
        entries = self.list(self.PS_PREFIX)
        return {int(k[len(self.PS_PREFIX):]): v
                for k, v in entries.items()}

    def wait_for_pservers(self, count, timeout=60.0):
        """Desired-count rendezvous: block until `count` live pservers
        are registered (reference: etcd_client.go desired-count wait);
        returns endpoints ordered by slot."""
        deadline = time.time() + timeout
        while True:
            live = self.pservers()
            if len(live) >= count:
                return [live[s] for s in sorted(live)]
            if time.time() >= deadline:
                raise TimeoutError(
                    "only %d of %d pservers registered within %.1fs"
                    % (len(live), count, timeout))
            time.sleep(0.05)

    def close(self):
        self._client.close()


def global_mesh(dp=None, mp=1, sp=1, pp=1, ep=1, devices=None):
    """Build a Mesh over ALL hosts' devices (jax.devices() is global
    after init_multihost).  Delegates to parallel.make_mesh with
    drop_unit_axes=True: only the axes actually >1 appear (plus "dp"),
    in (dp, mp, sp, pp, ep) order."""
    import jax
    from ..parallel.mesh import make_mesh

    devices = devices if devices is not None else jax.devices()
    return make_mesh(n_devices=len(devices), dp=dp, mp=mp, sp=sp, pp=pp,
                     ep=ep, axes=("dp", "mp", "sp", "pp", "ep"),
                     devices=devices, drop_unit_axes=True)
