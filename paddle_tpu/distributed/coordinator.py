"""Multi-host device-mesh initialization (ICI/DCN rendezvous).

Replaces the reference's etcd coordination layer (reference:
go/pserver/etcd_client.go:31-97 TTL-lease registration + desired-count
rendezvous, go/master/etcd_client.go leader lock) for the collective
path: on TPU pods the runtime itself provides rendezvous — every host
calls `jax.distributed.initialize` against one coordinator address and
the PJRT client wires ICI/DCN; there is no parameter-server in the
loop.  The pserver/transpiler stack (native/pserver.cc) remains the
DCN path for sparse/CTR-style workloads; this module is the dense
collective path's entry point.

Env protocol (set by tools/cluster_launch.py or any scheduler):
    PADDLE_COORDINATOR   host:port of process 0
    PADDLE_NUM_PROCESSES world size
    PADDLE_PROCESS_ID    this host's rank
"""

import os

__all__ = ["init_multihost", "global_mesh", "process_count",
           "process_index"]

_initialized = [False]


def init_multihost(coordinator=None, num_processes=None, process_id=None,
                   local_device_ids=None):
    """Bring up the multi-host JAX runtime.  No-ops on single-host
    (nothing set and no args) so user scripts can call it
    unconditionally."""
    import jax

    coordinator = coordinator or os.environ.get("PADDLE_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_NUM_PROCESSES", "0")) \
            or None
    if process_id is None:
        pid = os.environ.get("PADDLE_PROCESS_ID")
        process_id = int(pid) if pid is not None else None

    if coordinator is None and num_processes in (None, 1):
        return False  # single host; jax is already usable
    if _initialized[0]:
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized[0] = True
    return True


def process_count():
    import jax

    return jax.process_count()


def process_index():
    import jax

    return jax.process_index()


def global_mesh(dp=None, mp=1, sp=1, pp=1, ep=1, devices=None):
    """Build a Mesh over ALL hosts' devices (jax.devices() is global
    after init_multihost).  Delegates to parallel.make_mesh with
    drop_unit_axes=True: only the axes actually >1 appear (plus "dp"),
    in (dp, mp, sp, pp, ep) order."""
    import jax
    from ..parallel.mesh import make_mesh

    devices = devices if devices is not None else jax.devices()
    return make_mesh(n_devices=len(devices), dp=dp, mp=mp, sp=sp, pp=pp,
                     ep=ep, axes=("dp", "mp", "sp", "pp", "ep"),
                     devices=devices, drop_unit_axes=True)
