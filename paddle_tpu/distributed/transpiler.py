"""DistributeTranspiler: pserver distribution as a graph rewrite.

TPU-native redesign of the reference pserver path (reference:
python/paddle/v2/fluid/distribute_transpiler.py:81 — params split into
blocks round-robin across pservers (split_dense_variable:39), trainer
program's optimizer ops replaced by send; pserver side applies the
optimizer per shard).  Differences by design:

  * transport is the native framed-TCP runtime (native/pserver.cc), not
    gRPC; the pserver executes optimizers in C++ (as the reference v2
    C++/Go pservers do: ParameterServer2.h:383 doOperation,
    go/pserver/optimizer.go) rather than interpreting an optimizer
    sub-block.
  * the trainer-side `dist_send` op is a host (non-jittable) op at the
    end of the block: XLA computes forward+backward on-device; the op
    ships each grad block, blocks on the sync barrier, and writes the
    refreshed parameter back — same round-trip semantics as the
    reference send+recv pair (send_op.cc:35 / recv_op.cc:86).
  * sparse SelectedRows gradients ship rows only
    (reference: getParameterSparse ParameterServer2.h:510).
"""

import numpy as np

from .. import native
from ..core.types import VarType
from ..fluid import framework, fusion
from ..ops.dist import ClientPool as _ClientPool, _bname

__all__ = ["DistributeTranspiler", "split_dense_variable", "run_pserver"]

# optimizer op type -> (native kind, attr extraction)
_OPT_MAP = {
    "sgd": native.OPT_SGD,
    "momentum": native.OPT_MOMENTUM,
    "adagrad": native.OPT_ADAGRAD,
    "adam": native.OPT_ADAM,
}


def split_dense_variable(var_list, pserver_count, min_block_size=1024,
                         max_block_size=1 << 20):
    """Split parameters into near-equal blocks to balance pserver load
    (reference: distribute_transpiler.py split_dense_variable:39).

    Returns a list of (var_name, block_id, begin, size) over flattened
    elements.
    """
    blocks = []
    for var in var_list:
        size = int(np.prod(var.shape))
        split_count = pserver_count
        if size <= min_block_size:
            split_count = 1
        block_size = (size + split_count - 1) // split_count
        if block_size < min_block_size:
            block_size = min_block_size
        block_size = min(block_size, max_block_size)
        nblocks = (size + block_size - 1) // block_size
        for i in range(nblocks):
            begin = i * block_size
            blocks.append((var.name, i, begin,
                           min(block_size, size - begin)))
    return blocks


def _validate_split_blocks(assign, params, endpoints):
    """Every parameter's send/recv blocks must tile [0, numel) exactly:
    contiguous, non-overlapping, fully covering, each on a known
    endpoint.  A custom split_method that gets this wrong would
    otherwise surface as silently-corrupted parameters after the first
    init_pservers round-trip; fail at transpile time instead, naming
    the parameter and the first bad block."""
    numel = {p.name: int(np.prod(p.shape)) for p in params}
    dropped = sorted(set(numel) - set(assign))
    if dropped:
        raise ValueError(
            "split assigned no pserver blocks to parameter(s) %s — "
            "they would silently stay at their initial values on "
            "every trainer" % dropped)
    for pname, blocks in assign.items():
        total = numel.get(pname)
        if total is None:
            raise ValueError(
                "split assigned blocks to %r, which is not a "
                "parameter being distributed" % pname)
        cursor = 0
        for ep, begin, size in sorted(blocks, key=lambda b: b[1]):
            if ep not in endpoints:
                raise ValueError(
                    "param %r block [%d:%d) is assigned to unknown "
                    "pserver endpoint %r" % (pname, begin,
                                             begin + size, ep))
            if size <= 0:
                raise ValueError(
                    "param %r has an empty/negative block at offset "
                    "%d (size %d)" % (pname, begin, size))
            if begin != cursor:
                kind = "overlaps" if begin < cursor else "leaves a gap"
                raise ValueError(
                    "param %r split %s at offset %d: block [%d:%d) "
                    "after [..:%d)" % (pname, kind, cursor, begin,
                                       begin + size, cursor))
            cursor = begin + size
        if cursor != total:
            raise ValueError(
                "param %r split covers %d of %d elements — the "
                "pserver would train a truncated parameter"
                % (pname, cursor, total))


class DistributeTranspiler:
    """reference: distribute_transpiler.py DistributeTranspiler:81."""

    def __init__(self):
        self.param_blocks = {}     # param name -> [(endpoint, begin, size)]
        self.param_opt = {}        # param name -> (kind, lr_var, attrs)
        self.trainers = 1
        self.sync = True
        self._sparse_params = set()

    # -- program rewrite ----------------------------------------------------
    def transpile(self, optimize_ops=None, params_grads=None,
                  trainer_id=0, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync=True, sync_mode=None,
                  split_method=split_dense_variable):
        """sync_mode=False selects async SGD: each trainer's gradient
        applies immediately server-side with no cross-trainer barrier
        (reference: ParameterServer2.h asyncSGD:468); pair with
        run_pserver(sync=False, async_lagged_threshold=N) to bound
        staleness (ParameterServer2.h:243).  `sync_mode` is the
        reference-style spelling; `sync` is kept as the original
        keyword — when both are given sync_mode wins."""
        if program is None:
            program = framework.default_main_program()
        self.program = program
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.sync = sync if sync_mode is None else bool(sync_mode)
        endpoints = (pservers.split(",") if isinstance(pservers, str)
                     else list(pservers))
        self.endpoints = endpoints

        block = program.global_block()
        # pserver placement scatters per-parameter update ops across
        # endpoints, so any stacked fused_update ops must come apart first
        fusion.unfuse_update_ops(block)
        params = [p for p, g in params_grads]
        grads = {p.name: g for p, g in params_grads}

        # per-param optimizer config from the optimize ops being removed
        opt_ops = [op for op in block.ops if op.type in _OPT_MAP]
        configured = {}
        for op in opt_ops:
            if op.type not in _OPT_MAP:
                continue
            pname = op.desc.input("Param")[0]
            attrs = dict(op.desc.attrs)
            lr_name = op.desc.input("LearningRate")[0]
            if op.type == "momentum":
                hp = (float(attrs.get("mu", 0.9)), 0.0, 0.0)
            elif op.type == "adagrad":
                hp = (float(attrs.get("epsilon", 1e-6)), 0.0, 0.0)
            elif op.type == "adam":
                hp = (float(attrs.get("beta1", 0.9)),
                      float(attrs.get("beta2", 0.999)),
                      float(attrs.get("epsilon", 1e-8)))
            else:
                hp = (0.0, 0.0, 0.0)
            configured[pname] = (_OPT_MAP[op.type], lr_name, hp)
        unsupported = [p.name for p in params if p.name not in configured]
        if unsupported:
            raise NotImplementedError(
                "pserver-side optimizer supports sgd/momentum/adagrad/"
                "adam; no config found for params %s" % unsupported)
        self.param_opt = configured

        # pserver optimizer config snapshots the LR once at
        # init_pservers; an LR-decay schedule writing the LR var in the
        # trainer program would silently have no effect on updates
        # (the reference ships the current LR with every update —
        # ParameterServer2 trainingConfig). Surface that loudly.
        lr_names = {lr for _k, lr, _hp in configured.values()}
        written = {}
        for op in block.ops:
            if op in opt_ops:
                continue
            for outs in op.desc.outputs.values():
                for o in outs:
                    written.setdefault(o, []).append(op)
        def _is_static_lr_writer(op):
            # Constant producers (fill_constant LR vars, the per-param
            # `scale` that Optimizer._create_param_lr emits) yield the
            # same value every step — not a schedule. Warn only when
            # the writer updates one of its own inputs in place or its
            # inputs are produced by other ops (step counters).
            in_names = [i for ins in op.desc.inputs.values() for i in ins]
            out_names = [o for outs in op.desc.outputs.values()
                         for o in outs]
            if any(o in in_names for o in out_names):
                return False  # in-place update: evolves across steps

            def _static_src(n):
                # produced by no op AND persistable (a param/constant);
                # a non-persistable producer-less var is a feed — dynamic
                if written.get(n):
                    return False
                v = block.vars.get(n)
                return v is not None and bool(
                    getattr(v, "persistable", False))

            return all(_static_src(i) for i in in_names)
        decay_writers = [
            op.type for name in lr_names for op in written.get(name, [])
            if not _is_static_lr_writer(op)]
        if decay_writers:
            import warnings

            warnings.warn(
                "DistributeTranspiler: ops %s write the learning-rate "
                "var, but the pserver optimizer snapshots LR once at "
                "init_pservers(); the decay schedule will NOT affect "
                "pserver updates. Re-run init_pservers() to refresh, "
                "or keep the optimizer local." % sorted(set(decay_writers)),
                stacklevel=2)

        # sparse-grad params stay whole on one endpoint (rows route to a
        # single owner; reference sparse tables also shard by row
        # server-set, not by flat range)
        sparse = {p.name for p in params
                  if grads[p.name].type == VarType.SELECTED_ROWS}
        self._sparse_params = sparse

        # param -> blocks -> endpoints, round-robin over block list
        # (reference: round_robin distributed_spliter.py)
        dense_params = [p for p in params if p.name not in sparse]
        blocks = split_method(dense_params, len(endpoints))
        assign = {}
        for i, (pname, _bid, begin, size) in enumerate(blocks):
            assign.setdefault(pname, []).append(
                (endpoints[i % len(endpoints)], begin, size))
        for j, p in enumerate(p for p in params if p.name in sparse):
            assign[p.name] = [(endpoints[j % len(endpoints)], 0,
                               int(np.prod(p.shape)))]
        # a bad split_method here means every trainer ships wrong byte
        # ranges to every pserver — validate the tiling NOW, before
        # the rewrite lands in the program
        _validate_split_blocks(assign, params, set(endpoints))
        self.param_blocks = assign

        # drop the optimizer ops (+ their lr decay helpers stay; they're
        # harmless) and append one dist_send per param
        keep = [op for op in block.ops if op not in opt_ops]
        removed_descs = {id(op.desc) for op in opt_ops}
        block.ops = keep
        block.desc.ops = [d for d in block.desc.ops
                          if id(d) not in removed_descs]

        for p in params:
            g = grads[p.name]
            block.append_op(
                type="dist_send",
                inputs={"Param": [p], "Grad": [g]},
                outputs={"ParamOut": [p]},
                attrs={
                    "param_name": p.name,
                    "blocks": [(ep, int(b), int(s))
                               for ep, b, s in assign[p.name]],
                }, infer_shape=False)

        # the rewritten program ships to a whole cluster: verify its
        # structure NOW (cheap desc walk, docs/ANALYSIS.md) so a
        # transpiler bug fails at transpile time with op/var identity,
        # not as an opaque error on some remote trainer
        from .. import analysis

        analysis.verify_program(program, level="structural") \
            .publish(origin="transpiler").raise_on_error()
        return self

    # -- runtime helpers ----------------------------------------------------
    def init_pservers(self, scope=None):
        """Push initial parameter blocks + optimizer config to their
        pservers (first trainer wins server-side), then pull the
        canonical values so all trainers start identical."""
        from ..core import scope as scope_mod

        scope = scope or scope_mod.global_scope()
        for pname, blocks in self.param_blocks.items():
            kind, lr_name, hp = self.param_opt[pname]
            lr_val = scope.get(lr_name)
            lr = float(np.asarray(lr_val).reshape(-1)[0]) \
                if lr_val is not None else 0.01
            flat = np.asarray(scope.get(pname)).reshape(-1)
            for ep, begin, size in blocks:
                c = _ClientPool.get(ep)
                c.init_param(_bname(pname, begin), flat[begin:begin + size],
                             opt_kind=kind, lr=lr, hp1=hp[0], hp2=hp[1],
                             hp3=hp[2])
            # pull canonical init
            out = np.empty_like(flat)
            for ep, begin, size in blocks:
                out[begin:begin + size] = _ClientPool.get(ep).get_param(
                    _bname(pname, begin), size)
            shaped = out.reshape(np.asarray(scope.get(pname)).shape)
            scope.set(pname, shaped)

    def release(self):
        _ClientPool.reset()


def _bname(pname, begin):
    return "%s@%d" % (pname, begin)


def run_pserver(endpoint="127.0.0.1:6174", trainers=1, sync=True,
                async_lagged_threshold=0):
    """Start a pserver for `endpoint` and return the server object
    (reference: the pserver startup path of recv_op/ListenAndServ and
    paddle_pserver2 main).  sync=False serves the async-SGD path;
    async_lagged_threshold > 0 discards gradients computed against
    parameters at least that many versions old (reference:
    ParameterServer2.h:243 staleness control).  Blocks only in
    __main__ usage; tests call .stop()."""
    host, port = endpoint.rsplit(":", 1)
    return native.ParameterServer(
        port=int(port), num_trainers=trainers, sync=sync,
        async_lagged_threshold=async_lagged_threshold)
