"""Transformer built through the Program stack (fluid layers).

The raw-JAX flagship (models/transformer.py) covers scale experiments;
this is the same GPT-style decoder expressed as a fluid Program, so the
whole framework surface applies: real optimizers with accumulators,
regularizers/clipping, LR schedules, checkpointing, the transpiler, and
`ParallelTrainer` sharding over dp×mp×sp meshes.  Attention is the
registered `flash_attention` op (ops/attention.py) — pallas kernel on
TPU, ring attention over ICI when `sp_axis` names a mesh axis — which
is the in-framework surface the reference lacks (its nets-module
attention materializes the [T,T] matrix, reference:
python/paddle/v2/fluid/nets.py:338).

Activation is relu (the 2018 reference op set has no gelu; the raw-JAX
stack uses gelu where it matters for parity with modern checkpoints).
"""

import numpy as np

from .. import fluid

__all__ = ["build_transformer_program",
           "build_transformer_step_program",
           "build_transformer_cached_step_program",
           "transformer_program_feeds"]


def _block(x, n_head, d_model, d_ff, causal, sp_axis, sp_mode):
    h = fluid.layers.layer_norm(x, begin_norm_axis=2)
    qkv = fluid.layers.fc(input=h, size=3 * d_model, num_flatten_dims=2)
    q, k, v = fluid.layers.split(qkv, num_or_sections=3, dim=-1)
    o = fluid.layers.flash_attention(
        q, k, v, num_heads=n_head, causal=causal,
        sequence_parallel_axis=sp_axis,
        sequence_parallel_mode=sp_mode)
    x = x + fluid.layers.fc(input=o, size=d_model, num_flatten_dims=2)

    h = fluid.layers.layer_norm(x, begin_norm_axis=2)
    h = fluid.layers.fc(input=h, size=d_ff, num_flatten_dims=2,
                        act="relu")
    return x + fluid.layers.fc(input=h, size=d_model, num_flatten_dims=2)


def build_transformer_program(batch, seq_len, vocab_size, n_layer=2,
                              n_head=4, d_model=64, d_ff=None,
                              causal=True, sp_axis="", sp_mode="ring"):
    """Returns (main, startup, avg_loss, logits).

    Feeds: tokens/positions int64 [batch, seq_len], targets int64
    [batch, seq_len, 1] (use `transformer_program_feeds`).
    """
    if d_ff is None:
        d_ff = 4 * d_model
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        tokens = fluid.layers.data(
            name="tokens", shape=[batch, seq_len], dtype="int64",
            append_batch_size=False)
        positions = fluid.layers.data(
            name="positions", shape=[batch, seq_len], dtype="int64",
            append_batch_size=False)
        targets = fluid.layers.data(
            name="targets", shape=[batch, seq_len, 1], dtype="int64",
            append_batch_size=False)

        x = fluid.layers.embedding(tokens, size=[vocab_size, d_model]) \
            + fluid.layers.embedding(positions, size=[seq_len, d_model])
        for _ in range(n_layer):
            x = _block(x, n_head, d_model, d_ff, causal, sp_axis, sp_mode)
        x = fluid.layers.layer_norm(x, begin_norm_axis=2)
        logits = fluid.layers.fc(input=x, size=vocab_size,
                                 num_flatten_dims=2)

        flat = fluid.layers.reshape(x=logits, shape=[-1, vocab_size])
        flat_tgt = fluid.layers.reshape(x=targets, shape=[-1, 1])
        loss = fluid.layers.softmax_with_cross_entropy(flat, flat_tgt)
        avg_loss = fluid.layers.mean(x=loss)
    return main, startup, avg_loss, logits


def build_transformer_step_program(batch, window, vocab_size, n_layer=2,
                                   n_head=4, d_model=64, d_ff=None,
                                   sp_axis="", sp_mode="ring"):
    """Sliding-window decode step for `fluid.ProgramDecoder`.

    Feeds: tok [batch] (the token the decoder just chose), window
    [batch, window] int64 (the last `window` tokens), positions
    [batch, window].  Fetches: logits [batch, vocab] for the NEXT
    token, plus the shifted window — wire it as::

        dec = fluid.ProgramDecoder(
            prog.clone(for_test=True), token_name="tok",
            logits_name=logits.name,
            state_pairs=[("window", new_window.name),
                         ("positions", "positions")])

    Because name scopes are per Program, its parameters carry the SAME
    names as a `build_transformer_program` of the same architecture
    (the extra cast/split/concat ops create only temporaries), so a
    scope trained by the training program drives this step program
    directly.  A KV-cache step (O(1) work per token instead of
    O(window)) is the long-context extension; the window form needs no
    cache plumbing and is exact for contexts up to `window`.
    """
    if d_ff is None:
        d_ff = 4 * d_model
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        tok = fluid.layers.data(name="tok", shape=[batch], dtype="int32",
                                append_batch_size=False)
        win = fluid.layers.data(name="window", shape=[batch, window],
                                dtype="int64", append_batch_size=False)
        positions = fluid.layers.data(
            name="positions", shape=[batch, window], dtype="int64",
            append_batch_size=False)

        tok64 = fluid.layers.reshape(
            x=fluid.layers.cast(tok, "int64"), shape=[batch, 1])
        _, rest = fluid.layers.split(win, num_or_sections=[1, window - 1],
                                     dim=1)
        new_window = fluid.layers.concat([rest, tok64], axis=1)

        x = fluid.layers.embedding(new_window,
                                   size=[vocab_size, d_model]) \
            + fluid.layers.embedding(positions, size=[window, d_model])
        for _ in range(n_layer):
            x = _block(x, n_head, d_model, d_ff, True, sp_axis, sp_mode)
        x = fluid.layers.layer_norm(x, begin_norm_axis=2)
        logits3 = fluid.layers.fc(input=x, size=vocab_size,
                                  num_flatten_dims=2)
        _, last = fluid.layers.split(
            logits3, num_or_sections=[window - 1, 1], dim=1)
        logits = fluid.layers.reshape(x=last, shape=[batch, vocab_size])
    return main, startup, logits, new_window


def build_transformer_cached_step_program(batch, max_len, vocab_size,
                                          n_layer=2, n_head=4,
                                          d_model=64, d_ff=None):
    """KV-cached decode step: O(1) attention work per generated token.

    Feeds: tok [batch] int32, pos [batch] int64 (the slot being
    written; per-row so beam expansion can repeat it — rows advance in
    lockstep), per-layer caches k_cache_i/v_cache_i [batch, n_head,
    max_len, d_head].  Fetches: logits [batch, vocab], pos+1, and the
    updated caches.  Returns (main, startup, logits, state_pairs)
    where state_pairs wires straight into `fluid.ProgramDecoder`
    (greedy and beam; pass max_positions=max_len so decoding past the
    cache extent errors instead of clamping).

    Parameter names match `build_transformer_program` of the same
    architecture (per-program name scopes; cache feeds and the
    cast/reshape glue create no parameters), so the trained scope
    drives this program directly — max_len must not exceed the trained
    sequence length (the position embedding's extent).
    """
    if d_ff is None:
        d_ff = 4 * d_model
    d_head = d_model // n_head
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        tok = fluid.layers.data(name="tok", shape=[batch], dtype="int32",
                                append_batch_size=False)
        pos = fluid.layers.data(name="pos", shape=[-1], dtype="int64",
                                append_batch_size=False)
        caches = []
        for i in range(n_layer):
            caches.append((
                fluid.layers.data(
                    name="k_cache_%d" % i,
                    shape=[batch, n_head, max_len, d_head],
                    dtype="float32", append_batch_size=False),
                fluid.layers.data(
                    name="v_cache_%d" % i,
                    shape=[batch, n_head, max_len, d_head],
                    dtype="float32", append_batch_size=False)))

        # lookup_table squeezes a trailing size-1 ids dim (reference
        # convention), so [batch, 1, 1] ids yield [batch, 1, d]
        tok64 = fluid.layers.reshape(
            x=fluid.layers.cast(tok, "int64"), shape=[batch, 1, 1])
        # rows move in lockstep: one wpe row serves the whole batch
        pos_scalar = fluid.layers.reduce_max(pos)
        pos_ids = fluid.layers.reshape(x=pos_scalar, shape=[1, 1, 1])
        # wpe lookup is [1, 1, d]; the residual add broadcasts it over
        # the batch
        x = fluid.layers.embedding(tok64, size=[vocab_size, d_model]) \
            + fluid.layers.embedding(pos_ids, size=[max_len, d_model])

        state_pairs = []
        for i in range(n_layer):
            h = fluid.layers.layer_norm(x, begin_norm_axis=2)
            qkv = fluid.layers.fc(input=h, size=3 * d_model,
                                  num_flatten_dims=2)
            q, k, v = fluid.layers.split(qkv, num_or_sections=3, dim=-1)
            o, kc_out, vc_out = fluid.layers.cached_attention(
                q, k, v, caches[i][0], caches[i][1], pos,
                num_heads=n_head)
            state_pairs.append(("k_cache_%d" % i, kc_out.name))
            state_pairs.append(("v_cache_%d" % i, vc_out.name))
            x = x + fluid.layers.fc(input=o, size=d_model,
                                    num_flatten_dims=2)
            h = fluid.layers.layer_norm(x, begin_norm_axis=2)
            h = fluid.layers.fc(input=h, size=d_ff, num_flatten_dims=2,
                                act="relu")
            x = x + fluid.layers.fc(input=h, size=d_model,
                                    num_flatten_dims=2)

        x = fluid.layers.layer_norm(x, begin_norm_axis=2)
        logits3 = fluid.layers.fc(input=x, size=vocab_size,
                                  num_flatten_dims=2)
        logits = fluid.layers.reshape(x=logits3,
                                      shape=[batch, vocab_size])
        pos_out = fluid.layers.increment(pos, value=1, in_place=False)
        state_pairs.append(("pos", pos_out.name))
    return main, startup, logits, state_pairs


def transformer_program_feeds(batch, seq_len, vocab_size, seed=0):
    rs = np.random.RandomState(seed)
    tokens = rs.randint(0, vocab_size, size=(batch, seq_len))
    targets = rs.randint(0, vocab_size, size=(batch, seq_len, 1))
    positions = np.broadcast_to(np.arange(seq_len), (batch, seq_len))
    return {"tokens": tokens.astype(np.int64),
            "positions": np.ascontiguousarray(positions).astype(np.int64),
            "targets": targets.astype(np.int64)}
