"""Dense (static-shape) autoregressive decoding under jit.

The performance-path counterpart of the LoD beam ops (reference:
beam_search_op.cc / beam_search_decode_op.cc and the v2
RecurrentGradientMachine::beamSearch generation loop,
RecurrentGradientMachine.h:307-309).  The reference's beam state is
dynamic (ragged candidate lists); on TPU the state is dense
[batch, beam] arrays scanned to max_len with lax.top_k — XLA compiles
one executable, no host bookkeeping.
"""

import functools

import jax
import jax.numpy as jnp

__all__ = ["greedy_decode", "beam_search_decode_dense", "prefill",
           "sample_decode"]

NEG_INF = -1e30


def prefill(step_fn, init_state, prompt):
    """Feed a prompt through the step function (one scan), returning
    (state, first_token) where first_token [B] is the argmax of the
    last prompt position's logits — the natural continuation to seed
    the decode with.  prompt: int [B, P].

    Only the LAST logits ride the scan carry (the first step runs
    outside to shape the carry leaf), so prefill memory is O(B*V)
    regardless of prompt length."""
    toks = jnp.moveaxis(jnp.asarray(prompt, jnp.int32), 0, 1)  # [P, B]
    logits, state = step_fn(init_state, toks[0])

    def body(carry, tok):
        state, _ = carry
        logits, state = step_fn(state, tok)
        return (state, logits), None

    (state, logits), _ = jax.lax.scan(body, (state, logits), toks[1:])
    return state, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def greedy_decode(step_fn, init_state, bos, eos, max_len, batch_size):
    """step_fn(state, tokens[B]) -> (logits [B,V], new_state).
    Returns (tokens [B, max_len], lengths [B]).  `bos` may be a scalar
    or a per-row [B] array (e.g. prefill's first_token)."""

    def body(carry, _):
        state, tok, done = carry
        logits, state = step_fn(state, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, eos, nxt)
        done = done | (nxt == eos)
        return (state, nxt, done), nxt

    bos = jnp.asarray(bos, jnp.int32)
    tok0 = jnp.broadcast_to(bos, (batch_size,))
    # per-row seeds (prefill continuations) that are already eos emit
    # eos throughout; a SCALAR bos may deliberately equal eos (the
    # GPT-2 endoftext convention) and must still generate
    done0 = (tok0 == eos) if bos.ndim else \
        jnp.zeros((batch_size,), bool)
    (_, _, done), toks = jax.lax.scan(body, (init_state, tok0, done0),
                                      None, length=max_len)
    toks = jnp.moveaxis(toks, 0, 1)               # [B, L]
    lengths = jnp.argmax(toks == eos, axis=1) + 1
    lengths = jnp.where(jnp.any(toks == eos, axis=1), lengths, max_len)
    return toks, lengths


def sample_decode(step_fn, init_state, bos, eos, max_len, batch_size,
                  rng, temperature=1.0, top_k=0):
    """Ancestral sampling under jit: per-step categorical draw from
    the (temperature-scaled, optionally top-k-truncated) logits.
    Returns (tokens [B, max_len], lengths [B]).  `rng` is a JAX PRNG
    key; `bos` may be scalar or per-row (prefill seed)."""

    def body(carry, _):
        state, tok, done, key = carry
        logits, state = step_fn(state, tok)
        logits = logits.astype(jnp.float32) / jnp.maximum(
            temperature, 1e-6)
        if top_k:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, NEG_INF, logits)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(sub, logits, axis=-1) \
            .astype(jnp.int32)
        nxt = jnp.where(done, eos, nxt)
        done = done | (nxt == eos)
        return (state, nxt, done, key), nxt

    bos = jnp.asarray(bos, jnp.int32)
    tok0 = jnp.broadcast_to(bos, (batch_size,))
    done0 = (tok0 == eos) if bos.ndim else \
        jnp.zeros((batch_size,), bool)
    (_, _, done, _), toks = jax.lax.scan(
        body, (init_state, tok0, done0, rng), None, length=max_len)
    toks = jnp.moveaxis(toks, 0, 1)
    lengths = jnp.argmax(toks == eos, axis=1) + 1
    lengths = jnp.where(jnp.any(toks == eos, axis=1), lengths, max_len)
    return toks, lengths


def beam_search_decode_dense(step_fn, init_state, bos, eos, beam_size,
                             max_len, batch_size,
                             length_penalty=0.0):
    """Batched beam search, fully jittable.

    step_fn(state, tokens[N]) -> (logits [N,V], new_state) where N =
    batch*beam and every state leaf is [N, ...].  Returns
    (tokens [B, beam, max_len], scores [B, beam]) sorted best-first.
    """
    B, K = batch_size, beam_size

    def expand(t):
        return jnp.repeat(t, K, axis=0)

    state = jax.tree_util.tree_map(expand, init_state)
    tok = expand(jnp.broadcast_to(jnp.asarray(bos, jnp.int32), (B,)))
    # only beam 0 alive at t=0 so the first top-k doesn't pick K copies
    scores = jnp.tile(jnp.concatenate(
        [jnp.zeros((1,), jnp.float32),
         jnp.full((K - 1,), NEG_INF, jnp.float32)]), (B,))
    done = jnp.zeros((B * K,), bool)

    def body(carry, _):
        state, tok, scores, done = carry
        logits, new_state = step_fn(state, tok)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # finished beams: only eos continues, at no cost
        eos_only = jnp.full((V,), NEG_INF).at[eos].set(0.0)
        logp = jnp.where(done[:, None], eos_only[None, :], logp)
        total = scores[:, None] + logp                  # [B*K, V]
        total = total.reshape(B, K * V)
        top_scores, top_idx = jax.lax.top_k(total, K)    # [B, K]
        beam_idx = top_idx // V                          # within-batch beam
        tok_idx = (top_idx % V).astype(jnp.int32)
        flat_src = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)

        state = jax.tree_util.tree_map(
            lambda t: t[flat_src], new_state)
        tok = tok_idx.reshape(-1)
        scores = top_scores.reshape(-1)
        done = done[flat_src] | (tok == eos)
        return (state, tok, scores, done), (tok_idx, beam_idx)

    (state, tok, scores, done), (toks, parents) = jax.lax.scan(
        body, (state, tok, scores, done), None, length=max_len)

    # backtrack through the per-step parent pointers (reference:
    # beam_search_decode_op PackAllSteps backtracking)
    def back(carry, step):
        beam = carry                                   # [B, K]
        tok_t, par_t = step
        cur_tok = jnp.take_along_axis(tok_t, beam, axis=1)
        prev_beam = jnp.take_along_axis(par_t, beam, axis=1)
        return prev_beam, cur_tok

    last_beam = jnp.tile(jnp.arange(K)[None, :], (B, 1))
    _, rev_toks = jax.lax.scan(back, last_beam, (toks, parents),
                               reverse=True)
    sequences = jnp.moveaxis(rev_toks, 0, 2)           # [B, K, L]
    final_scores = scores.reshape(B, K)
    if length_penalty:
        lengths = jnp.sum(jnp.cumsum(sequences == eos, axis=2) == 0,
                          axis=2) + 1
        final_scores = final_scores / (lengths.astype(jnp.float32)
                                       ** length_penalty)
    order = jnp.argsort(-final_scores, axis=1)
    sequences = jnp.take_along_axis(sequences, order[:, :, None], axis=1)
    final_scores = jnp.take_along_axis(final_scores, order, axis=1)
    return sequences, final_scores
