"""MNIST stand-in (reference: python/paddle/v2/dataset/mnist.py —
784-float images in [-1,1], int label 0-9)."""

from .common import synthetic_images

__all__ = ["train", "test"]

_TRAIN_N = 2048
_TEST_N = 512


def _reader(n, seed):
    imgs, labels = synthetic_images(n, (784,), 10, seed)

    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def train():
    return _reader(_TRAIN_N, 42)


def test():
    return _reader(_TEST_N, 43)
