"""CIFAR-10/100 stand-in (reference: python/paddle/v2/dataset/cifar.py —
3072-float images, int label)."""

from .common import synthetic_images

__all__ = ["train10", "test10", "train100", "test100"]

_TRAIN_N = 1024
_TEST_N = 256


def _reader(n, classes, seed):
    imgs, labels = synthetic_images(n, (3072,), classes, seed)

    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def train10():
    return _reader(_TRAIN_N, 10, 100)


def test10():
    return _reader(_TEST_N, 10, 101)


def train100():
    return _reader(_TRAIN_N, 100, 102)


def test100():
    return _reader(_TEST_N, 100, 103)
