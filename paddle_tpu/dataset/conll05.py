"""CoNLL-2005 SRL stand-in (reference: python/paddle/v2/dataset/conll05.py
— 8 feature sequences + BIO label sequence)."""

from .common import rng

__all__ = ["get_dict", "get_embedding", "test"]

_WORDS = 4000
_PREDS = 300
_LABELS = 59  # BIO over roles


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_PREDS)}
    label_dict = {("l%d" % i): i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    import numpy as np

    return rng(33).uniform(-1, 1, size=(_WORDS, 32)).astype("float32")


def _reader(n, seed):
    r = rng(seed)

    def reader():
        for _ in range(n):
            length = int(r.randint(5, 35))
            word = r.randint(0, _WORDS, size=length).tolist()
            pred_idx = int(r.randint(0, length))
            predicate = [int(r.randint(0, _PREDS))] * length
            ctx_n2 = word[max(0, pred_idx - 2):][:1] * length
            ctx_n1 = word[max(0, pred_idx - 1):][:1] * length
            ctx_0 = [word[pred_idx]] * length
            ctx_p1 = word[min(length - 1, pred_idx + 1):][:1] * length
            ctx_p2 = word[min(length - 1, pred_idx + 2):][:1] * length
            mark = [1 if i == pred_idx else 0 for i in range(length)]
            label = r.randint(0, _LABELS, size=length).tolist()
            yield (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate,
                   mark, label)

    return reader


def test():
    return _reader(256, 44)
