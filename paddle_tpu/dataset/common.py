"""Shared synthetic-dataset helpers (reference: python/paddle/v2/dataset/
common.py — download/md5 cache; here: deterministic generators)."""

import numpy as np

__all__ = ["rng", "synthetic_linear", "synthetic_images",
           "synthetic_sequences"]


def rng(seed):
    return np.random.RandomState(seed)


def synthetic_linear(n, dim, w_seed=1234, x_seed=1, noise=0.1):
    """Linear-regression data with a fixed ground-truth weight vector: a
    faithful stand-in for uci_housing's learnable structure."""
    r = rng(w_seed)
    w = r.uniform(-1, 1, size=(dim,)).astype("float32")
    b = 0.5
    x = rng(w_seed + x_seed).uniform(-1, 1, size=(n, dim)).astype("float32")
    y = (x @ w + b + noise *
         rng(w_seed + x_seed + 1).randn(n).astype("float32")) \
        .astype("float32")
    return x, y.reshape(-1, 1)


def synthetic_images(n, shape, num_classes, seed):
    """Class-dependent image patterns: each class has a fixed template plus
    noise, so real learning happens (loss falls, accuracy rises)."""
    r = rng(seed)
    templates = r.uniform(-1, 1, size=(num_classes,) + shape) \
        .astype("float32")
    labels = rng(seed + 1).randint(0, num_classes, size=n)
    noise = rng(seed + 2).randn(n, *shape).astype("float32") * 0.6
    imgs = templates[labels] + noise
    return imgs.astype("float32"), labels.astype("int64")


def synthetic_sequences(n, vocab_size, num_classes, seed, min_len=4,
                        max_len=30):
    """Sequences whose class correlates with token distribution."""
    r = rng(seed)
    class_bias = rng(seed + 1).randint(0, vocab_size,
                                       size=(num_classes, 8))
    out = []
    for i in range(n):
        label = int(r.randint(0, num_classes))
        length = int(r.randint(min_len, max_len + 1))
        base = r.randint(0, vocab_size, size=length)
        # sprinkle class-marker tokens
        marker_positions = r.randint(0, length, size=max(1, length // 3))
        base[marker_positions] = class_bias[label][
            r.randint(0, class_bias.shape[1], size=marker_positions.size)]
        out.append((base.astype("int64").tolist(), label))
    return out
