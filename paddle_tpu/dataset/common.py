"""Shared dataset machinery: download cache, checksums, and synthetic
fallback generators.

Capability parity with the reference's dataset plumbing (reference:
python/paddle/v2/dataset/common.py — DATA_HOME, md5-checked download).
Real parsers live in the per-dataset modules; every module keeps a
deterministic synthetic generator as an offline fallback so training
examples and CI run with zero egress.
"""

import hashlib
import os

import numpy as np

from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy

__all__ = ["DATA_HOME", "md5file", "download", "fetch_or_none",
           "rng", "synthetic_linear", "synthetic_images",
           "synthetic_sequences"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser("~/.cache/paddle_tpu/dataset"))


def md5file(path):
    digest = hashlib.md5()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 16), b""):
            digest.update(block)
    return digest.hexdigest()


def _fetch_once(url, tmp, filename, md5sum):
    """One download attempt: url -> tmp -> rename.  The partial tmp is
    ALWAYS removed on failure (a stale .part from a died attempt must
    not shadow-corrupt the next one)."""
    _faults.check("dataset/download", url=url)
    from urllib.request import urlopen

    try:
        with urlopen(url, timeout=30) as resp, open(tmp, "wb") as out:
            for block in iter(lambda: resp.read(1 << 16), b""):
                out.write(block)
        if md5sum is not None and md5file(tmp) != md5sum:
            # retryable: a truncated/corrupt transfer re-downloads
            raise IOError("md5 mismatch for %s" % url)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    os.replace(tmp, filename)


def download(url, module_name, md5sum=None, save_name=None, retry=None):
    """Fetch `url` into DATA_HOME/<module>/ once; verify md5 when given.

    Transient failures (network errors, md5 mismatches from truncated
    transfers) retry with exponential backoff + full jitter — 3
    attempts by default, override with a
    :class:`paddle_tpu.resilience.RetryPolicy`.  Raises after the
    final attempt — use :func:`fetch_or_none` for the fallback-aware
    path."""
    cache_dir = os.path.join(DATA_HOME, module_name)
    os.makedirs(cache_dir, exist_ok=True)
    filename = os.path.join(cache_dir,
                            save_name or url.rstrip("/").split("/")[-1])
    if not (os.path.exists(filename)
            and (md5sum is None or md5file(filename) == md5sum)):
        policy = retry or RetryPolicy(max_attempts=3, base_delay=0.25,
                                      max_delay=5.0,
                                      name="dataset_download")
        policy.call(_fetch_once, url, filename + ".part", filename,
                    md5sum)
    return filename


def fetch_or_none(url, module_name, md5sum=None):
    """Cached file if present, else None — the caller then uses its
    synthetic fallback.  Network fetches are OPT-IN via
    PADDLE_TPU_ALLOW_DOWNLOAD=1: a dataset call must never surprise a
    unit test with an 80MB download (or a resolver hang in a
    blackholed-egress environment; getaddrinfo ignores urlopen's
    timeout)."""
    allow_net = os.environ.get("PADDLE_TPU_ALLOW_DOWNLOAD") == "1" \
        and not os.environ.get("PADDLE_TPU_OFFLINE")
    if not allow_net:
        cached = os.path.join(DATA_HOME, module_name,
                              url.rstrip("/").split("/")[-1])
        return cached if os.path.exists(cached) else None
    try:
        return download(url, module_name, md5sum)
    except Exception:
        return None


def rng(seed):
    return np.random.RandomState(seed)


def synthetic_linear(n, dim, w_seed=1234, x_seed=1, noise=0.1):
    """Linear-regression data with a fixed ground-truth weight vector: a
    faithful stand-in for uci_housing's learnable structure."""
    r = rng(w_seed)
    w = r.uniform(-1, 1, size=(dim,)).astype("float32")
    b = 0.5
    x = rng(w_seed + x_seed).uniform(-1, 1, size=(n, dim)).astype("float32")
    y = (x @ w + b + noise *
         rng(w_seed + x_seed + 1).randn(n).astype("float32")) \
        .astype("float32")
    return x, y.reshape(-1, 1)


def synthetic_images(n, shape, num_classes, seed):
    """Class-dependent image patterns: each class has a fixed template plus
    noise, so real learning happens (loss falls, accuracy rises)."""
    r = rng(seed)
    templates = r.uniform(-1, 1, size=(num_classes,) + shape) \
        .astype("float32")
    labels = rng(seed + 1).randint(0, num_classes, size=n)
    noise = rng(seed + 2).randn(n, *shape).astype("float32") * 0.6
    imgs = templates[labels] + noise
    return imgs.astype("float32"), labels.astype("int64")


def synthetic_sequences(n, vocab_size, num_classes, seed, min_len=4,
                        max_len=30):
    """Sequences whose class correlates with token distribution."""
    r = rng(seed)
    class_bias = rng(seed + 1).randint(0, vocab_size,
                                       size=(num_classes, 8))
    out = []
    for i in range(n):
        label = int(r.randint(0, num_classes))
        length = int(r.randint(min_len, max_len + 1))
        base = r.randint(0, vocab_size, size=length)
        # sprinkle class-marker tokens
        marker_positions = r.randint(0, length, size=max(1, length // 3))
        base[marker_positions] = class_bias[label][
            r.randint(0, class_bias.shape[1], size=marker_positions.size)]
        out.append((base.astype("int64").tolist(), label))
    return out
