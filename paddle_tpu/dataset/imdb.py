"""IMDB sentiment stand-in (reference: python/paddle/v2/dataset/imdb.py —
word-id sequences + binary label)."""

from .common import synthetic_sequences

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5147
_TRAIN_N = 512
_TEST_N = 128


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _reader(n, seed):
    data = synthetic_sequences(n, _VOCAB, 2, seed, min_len=8, max_len=60)

    def reader():
        for seq, label in data:
            yield seq, label

    return reader


def train(word_idx=None):
    return _reader(_TRAIN_N, 7)


def test(word_idx=None):
    return _reader(_TEST_N, 8)
