"""Shared helpers for the analysis passes: the one scope-chain VarDesc
walk, the one grad-op-to-forward OpInfo resolution, and the backward
builder's missing-slot placeholder — so verifier, dataflow and lints
can never disagree about name or op resolution."""

from ..ops import registry as op_registry

__all__ = ["EMPTY", "find_var_desc", "resolve_op_info"]

EMPTY = "@EMPTY@"


def resolve_op_info(op_type):
    """The OpInfo governing `op_type`, resolving `<fwd>_grad` ops to
    their forward's info (grad kernels inherit jittable/uses_rng from
    the forward registration); None when the type is unknown — the
    verifier's V001."""
    if op_registry.has_op(op_type):
        return op_registry.get_op_info(op_type)
    if op_registry.is_grad_op_type(op_type):
        fwd = op_registry.forward_type_of_grad(op_type)
        if op_registry.has_op(fwd):
            return op_registry.get_op_info(fwd)
    return None


def find_var_desc(desc, block_idx, name):
    """VarDesc for `name` resolved through the block parent chain, or
    None (mirrors framework._find_var_desc, over bare descs)."""
    bd = desc.block(block_idx)
    while True:
        if name in bd.vars:
            return bd.vars[name]
        if bd.parent_idx < 0:
            return None
        bd = desc.block(bd.parent_idx)
