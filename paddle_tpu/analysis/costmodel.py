"""Static communication cost model for sharded programs.

Given the spec assignment the sharding analyzer (`analysis.shard`)
derives, this module prices the ICI collectives a training step
implies — gradient all-reduce over dp, partial-sum all-reduce when a
matmul contracts a sharded dim, reduce-scatter/all-gather under
ZeRO-1, ppermute hops for ring attention, implicit-reshard
all-gathers at S003 conflict points — in BYTES ON THE WIRE per step.

Wire bytes use the standard ring-algorithm costs (what XLA's
collective implementations converge to on a torus):

    all-reduce       2 * (n-1)/n * payload
    all-gather       (n-1)/n * gathered payload
    reduce-scatter   (n-1)/n * payload
    all-to-all       (n-1)/n * payload
    ppermute         payload (one neighbor hop)

This is a RANKING model, not a simulator: overlap with compute,
latency terms, and multi-hop torus routing are out of scope.  Its job
is to say which tensors dominate the step's communication and how the
total scales with the mesh — before anything compiles.  Totals land in
the obs registry as `shard_comm_bytes_total{collective}` so proglint
runs and trainer-boundary analyses leave a scrapeable trail
(docs/OBSERVABILITY.md).

The sibling COMPUTE cost model is `fluid/analysis.py` (roofline
FLOPs/HBM floors); this one prices the wires between the chips.
"""

from collections import OrderedDict

__all__ = ["CommCostReport", "collective_wire_bytes",
           "DEFAULT_ICI_GBPS"]

# v5e-class ICI bandwidth per chip (all links), GB/s; override per call
DEFAULT_ICI_GBPS = 90.0

COLLECTIVES = ("allreduce", "reducescatter", "allgather", "alltoall",
               "ppermute")


def collective_wire_bytes(collective, payload_bytes, n):
    """Ring-cost wire bytes for moving `payload_bytes` across `n`
    participants."""
    if n <= 1:
        return 0
    if collective == "allreduce":
        return int(2.0 * (n - 1) / n * payload_bytes)
    if collective in ("allgather", "reducescatter", "alltoall"):
        return int(1.0 * (n - 1) / n * payload_bytes)
    if collective == "ppermute":
        return int(payload_bytes)
    raise ValueError("unknown collective %r (one of %s)"
                     % (collective, ", ".join(COLLECTIVES)))


class CommEvent:
    """One collective a step implies."""

    __slots__ = ("collective", "axis", "n", "payload_bytes",
                 "wire_bytes", "detail")

    def __init__(self, collective, axis, n, payload_bytes, detail=""):
        self.collective = collective
        self.axis = axis
        self.n = int(n)
        self.payload_bytes = int(payload_bytes)
        self.wire_bytes = collective_wire_bytes(collective,
                                                payload_bytes, n)
        self.detail = detail

    def to_dict(self):
        return {"collective": self.collective, "axis": self.axis,
                "n": self.n, "payload_bytes": self.payload_bytes,
                "wire_bytes": self.wire_bytes, "detail": self.detail}

    def __repr__(self):
        return ("CommEvent(%s over %s[%d]: %d wire bytes, %s)"
                % (self.collective, self.axis, self.n, self.wire_bytes,
                   self.detail))


class CommCostReport:
    """Accumulates CommEvents and ranks them."""

    def __init__(self, ici_gbps=DEFAULT_ICI_GBPS):
        self.events = []
        self.ici_gbps = ici_gbps

    def add(self, collective, axis, n, payload_bytes, detail=""):
        if n <= 1 or payload_bytes <= 0:
            return None  # a 1-wide axis moves nothing
        ev = CommEvent(collective, axis, n, payload_bytes, detail)
        self.events.append(ev)
        return ev

    def totals(self):
        """{collective: wire bytes}, descending."""
        out = {}
        for ev in self.events:
            out[ev.collective] = out.get(ev.collective, 0) + ev.wire_bytes
        return OrderedDict(sorted(out.items(), key=lambda kv: -kv[1]))

    def total_wire_bytes(self):
        return sum(ev.wire_bytes for ev in self.events)

    def step_seconds_floor(self):
        """Serialized ICI time floor (no overlap assumed)."""
        return self.total_wire_bytes() / (self.ici_gbps * 1e9)

    def ranked(self, topk=None):
        evs = sorted(self.events, key=lambda e: -e.wire_bytes)
        return evs if topk is None else evs[:topk]

    def to_dict(self, topk=10):
        return {
            "totals": dict(self.totals()),
            "total_wire_bytes": self.total_wire_bytes(),
            "step_seconds_floor": self.step_seconds_floor(),
            "top": [ev.to_dict() for ev in self.ranked(topk)],
        }

    def format(self, topk=10):
        lines = ["comm cost (per step, ring-cost wire bytes):"]
        for coll, b in self.totals().items():
            lines.append("  %-14s %12d bytes" % (coll, b))
        for ev in self.ranked(topk):
            lines.append("    %-12s %s[%d] %10d B  %s"
                         % (ev.collective, ev.axis, ev.n,
                            ev.wire_bytes, ev.detail))
        return "\n".join(lines)

    def publish(self):
        """Count total wire bytes per collective into the obs registry
        (`shard_comm_bytes_total{collective}`)."""
        from ..obs import registry as registry_mod

        reg = registry_mod.get_registry()
        fam = reg.counter(
            "shard_comm_bytes_total",
            "static per-step ICI wire bytes estimated by the sharding "
            "analyzer, by collective",
            labelnames=("collective",))
        for coll, b in self.totals().items():
            fam.labels(collective=coll).inc(b)
        return self
