"""paddle_tpu.analysis — static analysis over the Program IR.

Three layers (docs/ANALYSIS.md documents every diagnostic code):

  * `verifier`  — structural well-formedness: registry membership,
    def-before-use per block, BlockRef scoping, attr serializability,
    and dtype/shape consistency re-derived through the registry's
    infer-shape (V0xx codes).
  * `dataflow`  — def-use chains and liveness (the ONE implementation;
    the memory-optimization transpiler consumes it too), dead-op/
    dead-var detection and the write-write / in-place-alias hazard
    detector (D0xx/H0xx codes).
  * `lints`     — TPU-specific rules: dynamic dims into MXU ops,
    jit-segment splits, unseeded RNG, AMP dtype mixes, grad orphans
    (L0xx codes).
  * `shard`     — static SPMD analysis: PartitionSpec propagation
    against a mesh description, divisibility/conflict/schedule
    checks, per-device peak-HBM estimation (S0xx codes), with the
    `costmodel` pricing the implied ICI collectives
    (`shard_comm_bytes_total{collective}`).
  * `alias`     — may-alias + last-use donation-safety analysis: per
    jit segment, which param/state buffers are provably donatable
    (A0xx codes); the executor consumes the resulting `DonationPlan`
    behind FLAGS_donation and `pmem audit` prices what it declines.

`check_program` runs all three and publishes finding counters into the
obs registry; the sibling roofline COST analyzer lives in
`fluid/analysis.py` (where the time goes vs. whether the program is
even well-formed).

Verification is wired in at the trust boundaries: the executor's
FLAGS_verify_program gate (verify before first compile),
`fluid.io.load_inference_model` (structural check on load), serving
engine warmup, and the `proglint` CLI (`tools/lint_cli.py`).
"""

from .diagnostics import (Diagnostic, ProgramVerificationError, Report,
                          Severity)
from .dataflow import Liveness, analyze_dataflow
from .lints import lint_program
from .verifier import verify_program
from .shard import (analyze_sharding, check_moe, check_pipeline,
                    check_ring, mesh_axis_sizes, ShardingPlan)
from .costmodel import CommCostReport
from .alias import (analyze_donation, donation_mode, DonationPlan,
                    state_donation)

__all__ = [
    "Diagnostic", "Severity", "Report", "ProgramVerificationError",
    "Liveness", "verify_program", "analyze_dataflow", "lint_program",
    "check_program", "analyze_sharding", "check_pipeline", "check_moe",
    "check_ring", "mesh_axis_sizes", "ShardingPlan", "CommCostReport",
    "analyze_donation", "donation_mode", "DonationPlan",
    "state_donation",
]


def check_program(program, level="full", fetches=None, bucket_hints=None,
                  suppress=(), publish=True, origin="analysis"):
    """Run verifier + dataflow + lints over `program` (a Program or
    ProgramDesc); returns one merged `Report`.

    level: "structural" skips the infer-shape re-derivation (V005-007)
        — cheap enough for every program load.
    fetches: runtime fetch names; enables dead-op detection (fetch is
        a by-name scope lookup, invisible to the IR without this).
    bucket_hints: serving shape-bucket config; demotes the dynamic
        batch-dim MXU finding to a covered advisory.
    suppress: diagnostic suppressions ("H002", "H002@scale",
        "H002@var:name") — see docs/ANALYSIS.md.
    publish: count findings into the obs registry
        (`analysis_diagnostics_total{code,severity}`).
    """
    report = Report(suppress=suppress)
    verify_program(program, level=level, report=report)
    analyze_dataflow(program, fetches=fetches, report=report)
    lint_program(program, bucket_hints=bucket_hints, report=report)
    if publish:
        report.publish(origin=origin)
    return report
