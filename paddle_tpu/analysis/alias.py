"""May-alias + last-use donation-safety analysis over the Program IR.

XLA buffer donation (`donate_argnums`) lets an input buffer be reused
for an output, halving the HBM footprint of params and optimizer state
— but a donated `jax.Array` is deleted after dispatch, so donating a
buffer something still reads is a crash (or, worse, a silent wrong
value on backends whose reloaded executables drop the aliasing).  This
module is the static proof obligation: an abstract interpretation over
block 0, layered on `dataflow.Liveness`, that classifies every buffer
per jit segment as provably-donatable or not and explains each refusal
with a stable code.

The safety argument, per candidate buffer `n` in segment `i`:

  * reads INSIDE the donating XLA program are always safe — XLA buffer
    assignment orders internal uses before the aliased write;
  * hazards are strictly host-side: a later segment's op reads `n`
    (last-use violation), a fetch returns `n` to the caller (A003), a
    sub-block references `n` by name (A002 — invisible to block-0
    liveness), `n` is persistable (the scope re-reads it on EVERY
    future `run()` — donation would strand a deleted array in the
    scope), or `n` is a feed (the caller owns that buffer; the
    device-prefetch path re-uses feed arrays across steps).

Diagnostic codes (docs/ANALYSIS.md):

  A001  declared in-place slot whose input buffer strands: the op
        forks the output under a new name (`Moment1` -> `Moment1__fork`)
        or omits the declared slot entirely, so XLA sees two buffers
        and the conservative `outputs ∩ reads` donation never fires.
  A002  read-after-donation hazard: a later op or a sub-block reads a
        buffer the plan would donate.  Always an error — by
        construction `analyze_donation` never PLANS such a donation;
        A002 surfaces when `DonationPlan.verify` re-checks a plan
        against a program that changed after planning.
  A003  a fetch aliases a donatable buffer: the donation is declined
        (the fetch would return a deleted array).
  A004  in-place update stranded outside its jit segment: eager
        execution never donates, so the declared reuse cannot happen.
  A005  donation requested on a backend where
        `pcache.donation_aliasing_safe()` is false: `auto` degrades to
        `conservative` (live-jit donation is safe everywhere; it is
        the serialized-executable reload that loses the aliasing).

The executor consumes the resulting `DonationPlan` at jit build behind
`FLAGS_donation=auto|conservative|off` (default `auto`); `pmem audit`
prices what the plan declines; `proglint --donation` lints it.
"""

from .common import EMPTY
from .dataflow import (Liveness, _block_sub_reads, _in_place_pairs)
from .diagnostics import Diagnostic, Report, Severity
from ..utils import flags

__all__ = ["MODES", "DonationPlan", "analyze_donation", "donation_mode",
           "state_donation"]

MODES = ("auto", "conservative", "off")


def donation_mode(value=None):
    """Normalize a requested donation mode; None reads FLAGS_donation.
    Unknown strings fall back to "auto" (the flag default) rather than
    raising — a typo'd env var must not take down a training job."""
    if value is None:
        try:
            value = flags.get_flag("donation")
        except Exception:
            value = "auto"
    value = str(value or "auto").strip().lower()
    return value if value in MODES else "auto"


def state_donation(default=True):
    """Whole-state donation decision for the pjit trainers
    (`make_parallel_step` / `make_overlapped_dp_step` /
    `SpmdTrainer`): False under FLAGS_donation=off, `default`
    otherwise.  The pjit step functions donate the entire state pytree
    as one argument — there is no per-buffer widening to do — so the
    plan's only say is the off switch."""
    return False if donation_mode() == "off" else bool(default)


class DonationPlan:
    """The analysis result: per-jit-segment donate sets plus the
    per-buffer classification `pmem audit` prices.

    segments: one dict per executor segment —
        {"index", "jit", "start", "end", "conservative", "widened",
         "declined": [{"name", "code", "reason"}]}
      `conservative` is the executor's own `outputs ∩ reads` set (in
      executor output order); `widened` are the extra provably-dead
      buffers `auto` mode adds.  start/end are block-0 op indices —
      `verify()` re-checks reads against them.
    entries: the per-op in-place walk (one row per declared in-place
      pair) — {"name", "op_index", "op_type", "slot", "segment",
      "status": donated|reclaimable|pinned|skip, "code", "reason"}.
      `reclaimable` rows carry the A-code explaining the refusal
      (code None only under mode=off, where the refusal IS the flag).
    """

    def __init__(self, mode, effective_mode, backend_safe, report,
                 segments, entries):
        self.mode = mode
        self.effective_mode = effective_mode
        self.backend_safe = backend_safe
        self.report = report
        self.segments = segments
        self.entries = entries

    def donate(self, i):
        """The names segment `i` donates under the effective mode."""
        if self.effective_mode == "off":
            return ()
        seg = self.segments[i]
        if self.effective_mode == "conservative":
            return tuple(seg["conservative"])
        return tuple(seg["conservative"]) + tuple(seg["widened"])

    def widened(self, i):
        """The names `auto` adds beyond conservative for segment `i`
        (empty under conservative/off)."""
        if self.effective_mode != "auto":
            return ()
        return tuple(self.segments[i]["widened"])

    def fingerprint(self):
        """Stable content hash of the effective donation decision —
        folds into compile-cache keys so a plan change re-keys."""
        import hashlib

        h = hashlib.sha256()
        h.update(self.effective_mode.encode())
        for i in range(len(self.segments)):
            h.update(b"|%d:" % i)
            h.update(",".join(self.donate(i)).encode())
        return h.hexdigest()[:16]

    def verify(self, program, fetches=(), report=None):
        """Re-check every planned donation against `program` as it is
        NOW.  A donation planned earlier becomes a read-after-donation
        hazard (A002, error) when a later op or a sub-block reads the
        buffer at-or-after the recorded segment end, and an A003
        decline when a fetch now aliases it.  Returns a Report; use it
        before replaying a cached plan over a rewritten program."""
        report = report if report is not None else Report()
        desc = getattr(program, "desc", program)
        bd = desc.block(0)
        fetch_set = set(fetches or ())
        lv = Liveness(bd.ops, final_live=fetch_set).analyze()
        use_sites = lv.use_sites()
        sub_reads = _block_sub_reads(desc, 0)
        for seg in self.segments:
            for name in tuple(seg["conservative"]) + tuple(seg["widened"]):
                late = [u for u in use_sites.get(name, ())
                        if u >= seg["end"]]
                if late or name in sub_reads:
                    where = ("op %d" % late[0]) if late else "a sub-block"
                    report.add(Diagnostic(
                        "A002", Severity.ERROR,
                        "read-after-donation hazard: segment %d donates "
                        "%r but %s reads it after the segment ends at op "
                        "%d" % (seg["index"], name, where, seg["end"]),
                        block_idx=0,
                        op_index=late[0] if late else None,
                        var_name=name))
                elif name in fetch_set:
                    report.add(Diagnostic(
                        "A003", Severity.WARNING,
                        "fetch %r aliases a buffer segment %d donates; "
                        "the fetch would return a deleted array"
                        % (name, seg["index"]),
                        block_idx=0, var_name=name))
        return report

    def to_dict(self):
        return {
            "mode": self.mode,
            "effective_mode": self.effective_mode,
            "backend_safe": self.backend_safe,
            "fingerprint": self.fingerprint(),
            "segments": [dict(s) for s in self.segments],
            "entries": [dict(e) for e in self.entries],
            "report": self.report.to_dict(),
        }


def _find_vd(desc, bd, name):
    """VarDesc lookup through the parent chain (executor idiom)."""
    cur = bd
    while True:
        if name in cur.vars:
            return cur.vars[name]
        if cur.parent_idx < 0:
            return None
        cur = desc.block(cur.parent_idx)


def analyze_donation(program, fetches=(), feeds=(), mode=None,
                     backend_safe=None, suppress=(), report=None,
                     publish=False, origin="alias"):
    """Whole-program donation-safety analysis; returns a DonationPlan.

    program: a Program or ProgramDesc (block 0 is analyzed, segmented
        exactly as the executor segments it).
    fetches: runtime fetch names — a fetch is a host-side read the IR
        cannot see; donating a fetched buffer returns a deleted array.
    feeds: runtime feed names — feed buffers are caller-owned (the
        device-prefetch path re-uses them across steps), never donated
        beyond what the caller's own jit signature says.
    mode: "auto" | "conservative" | "off"; None reads FLAGS_donation.
    backend_safe: tri-state.  True/False is the
        `pcache.donation_aliasing_safe()` verdict (False degrades
        `auto` to `conservative` with an A005); None means "do not
        consult the backend" — static audits and `proglint` stay
        zero-device and emit no A005.
    """
    # lazy import: the executor imports analysis lazily and vice versa
    from ..fluid.executor import _segment_block

    desc = getattr(program, "desc", program)
    bd = desc.block(0)
    mode = donation_mode(mode)
    report = report if report is not None else Report(suppress=suppress)

    effective = mode
    if mode == "auto" and backend_safe is False:
        report.add(Diagnostic(
            "A005", Severity.WARNING,
            "donation mode 'auto' requested but this backend's "
            "executable reload does not preserve donation aliasing "
            "(pcache.donation_aliasing_safe() is false); degrading to "
            "'conservative'", block_idx=0))
        effective = "conservative"

    fetch_set = set(fetches or ())
    feed_set = set(feeds or ())
    segments = _segment_block(bd.ops)
    lv = Liveness(bd.ops, final_live=fetch_set).analyze()
    use_sites = lv.use_sites()
    def_sites = lv.def_sites()
    sub_reads = _block_sub_reads(desc, 0)
    persistable = {n for n, vd in bd.vars.items() if vd.persistable}

    seg_rows, entries = [], []
    base = 0
    for si, (jit_ok, ops) in enumerate(segments):
        end = base + len(ops)
        # replicate the executor's per-segment signature exactly
        # (executor._CompiledProgram._analyze): first-read-before-
        # write order for reads, write order for writes
        reads, writes, seen_writes = [], [], set()
        for od in ops:
            for n in od.input_names():
                if n not in seen_writes and n not in reads:
                    reads.append(n)
            for n in od.output_names():
                if n != EMPTY:
                    seen_writes.add(n)
                    if n not in writes:
                        writes.append(n)
        needed_later = set(fetch_set)
        for od in bd.ops[end:]:
            needed_later.update(od.input_names())
        outputs = [n for n in writes
                   if n in needed_later or n in persistable]
        conservative = tuple(n for n in outputs if n in reads) \
            if jit_ok else ()
        conservative_set = set(conservative)

        # -- widening: extra provably-dead reads `auto` donates -------
        widened, declined = [], []
        if jit_ok:
            for n in reads:
                if n in conservative_set or n in feed_set \
                        or n in persistable:
                    # persistable: live at entry of EVERY future run()
                    # — the scope re-reads it; a forked in-place slot
                    # lands here and gets its A001 in the entry walk
                    continue
                if not any(d < base for d in def_sites.get(n, ())):
                    # read-before-def: the value comes from the
                    # caller's feed env (declared in `feeds` or not) —
                    # that buffer is caller-owned, never ours to donate
                    continue
                if any(u >= end for u in use_sites.get(n, ())):
                    continue  # a later op still reads it
                if n in sub_reads:
                    d = Diagnostic(
                        "A002", Severity.ERROR,
                        "a sub-block reads %r by name; donating it in "
                        "segment %d would hand the sub-block a deleted "
                        "buffer" % (n, si), block_idx=0, var_name=n)
                    report.add(d)
                    declined.append({"name": n, "code": "A002",
                                     "reason": d.message})
                    continue
                if n in fetch_set:
                    d = Diagnostic(
                        "A003", Severity.WARNING,
                        "fetch %r aliases a donatable buffer in segment "
                        "%d; donation declined (the fetch would return "
                        "a deleted array)" % (n, si),
                        block_idx=0, var_name=n)
                    report.add(d)
                    declined.append({"name": n, "code": "A003",
                                     "reason": d.message})
                    continue
                widened.append(n)

        seg_rows.append({
            "index": si, "jit": jit_ok, "start": base, "end": end,
            "conservative": conservative, "widened": tuple(widened),
            "declined": declined,
        })

        # -- the per-op in-place walk `pmem audit` prices -------------
        for off, od in enumerate(ops):
            op_idx = base + off
            for out_slot, in_slot in _in_place_pairs(od):
                outs = od.output(out_slot)
                ins = od.input(in_slot) if in_slot else []
                for k, in_name in enumerate(ins):
                    if in_name == EMPTY:
                        continue
                    out_name = outs[k] if k < len(outs) else None
                    entry = {"name": in_name, "op_index": op_idx,
                             "op_type": od.type, "slot": out_slot,
                             "segment": si, "status": "skip",
                             "code": None, "reason": None}
                    entries.append(entry)
                    if out_name == in_name \
                            and in_name in conservative_set:
                        if effective == "off":
                            entry["status"] = "reclaimable"
                            entry["reason"] = (
                                "donation disabled "
                                "(FLAGS_donation=off); the buffer is "
                                "provably donatable")
                        else:
                            entry["status"] = "donated"
                        continue
                    if in_name in fetch_set or any(
                            u > op_idx
                            for u in use_sites.get(in_name, ())):
                        entry["status"] = "pinned"  # genuinely live
                        continue
                    if out_name == in_name and not jit_ok:
                        entry["status"] = "reclaimable"
                        entry["code"] = "A004"
                        entry["reason"] = (
                            "in-place update runs in a non-jittable "
                            "segment — eager execution never donates")
                        report.add(Diagnostic(
                            "A004", Severity.WARNING,
                            entry["reason"],
                            block_idx=0, op_index=op_idx,
                            op_type=od.type, var_name=in_name))
                    elif out_name is None:
                        entry["status"] = "reclaimable"
                        entry["code"] = "A001"
                        entry["reason"] = (
                            "declared in-place slot %r is absent from "
                            "the op; the input buffer is stranded"
                            % out_slot)
                        report.add(Diagnostic(
                            "A001", Severity.WARNING,
                            entry["reason"],
                            block_idx=0, op_index=op_idx,
                            op_type=od.type, var_name=in_name))
                    elif out_name != in_name:
                        entry["status"] = "reclaimable"
                        entry["code"] = "A001"
                        entry["reason"] = (
                            "in-place slot %r forks %r -> %r; XLA sees "
                            "two buffers, no donation"
                            % (out_slot, in_name, out_name))
                        report.add(Diagnostic(
                            "A001", Severity.WARNING,
                            entry["reason"],
                            block_idx=0, op_index=op_idx,
                            op_type=od.type, var_name=in_name))
                    # else: same-name dead write inside a jit segment
                    # that never leaves it — nothing to donate ("skip")
        base = end

    if publish:
        report.publish(origin=origin)
    return DonationPlan(mode, effective, backend_safe, report,
                        seg_rows, entries)
