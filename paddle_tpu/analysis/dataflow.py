"""Def-use chains, liveness, dead code, and write/alias hazards over a
block's op list.

This is THE liveness implementation for the framework: the
memory-optimization transpiler's private `ControlFlowGraph` (reference:
memory_optimization_transpiler.py:33) now delegates here, so buffer
reuse and the analysis diagnostics can never disagree about when a
variable dies.

Diagnostics:

  D001 dead-op   an op none of whose outputs are live (no later read,
                 not persistable, not fetched).  Only computed when the
                 caller supplies `fetches` — fetch is a by-name scope
                 lookup at run time, invisible to the IR, so without
                 the fetch set every sink (loss, metric) would be a
                 false positive.
  D002 dead-var  a VarDesc no op in any block reads or writes (prune
                 leftovers).  Advisory.
  H001 write-write race  two ops write the same var with no read in
                 between and no dataflow path ordering them — the
                 first value is silently lost today, and under a
                 reordering scheduler (mesh-parallel, pipeline) the
                 final value is a coin flip.
  H002 read-write hazard  a var is overwritten — in place (output
                 aliases an input by name, or the registry declares
                 `in_place_outputs`) or by a plain redefinition —
                 while another op reads it with no dataflow path to
                 or from the writer.  List order saves the program
                 today; any schedule that honors only data edges (and
                 XLA buffer donation does) races.
  H003 in-place-not-aliased  an op slot the registry declares in-place
                 (ParamOut=Param) writing a DIFFERENT var than its
                 aliased input — the update forks the state instead of
                 advancing it.
"""

from collections import defaultdict

from ..ops import registry as op_registry
from .common import EMPTY, resolve_op_info
from .diagnostics import Diagnostic, Report, Severity

__all__ = ["Liveness", "analyze_block", "analyze_dataflow",
           "dead_op_indices", "liveness_peak_bytes",
           "liveness_timeline"]


def liveness_timeline(op_descs, var_bytes, final_live=(), top_n=0):
    """Per-op live-bytes series of `sum(var_bytes(name))` over each
    op's live set (live-in plus own defs).  THE activation-peak walk:
    the shard analyzer's S005 estimate, the auto_remat pass's accept
    gate, and the obs.mem memory timeline all run it, parameterized
    only by the byte policy (`var_bytes`: name -> bytes, returning 0
    for names that don't count), so the accountings cannot drift
    apart structurally.

    Returns {"series": [bytes per op], "peak_bytes", "peak_op",
    "top_buffers"}; `top_buffers` (only when top_n > 0) lists the
    top-N nonzero buffers live at the peak, largest first, each
    blamed to its defining op — `{"name", "bytes", "def_op",
    "def_op_type"}` (def_op None for values live from outside the op
    list: feeds, carried state)."""
    lv = Liveness(op_descs, final_live=final_live).analyze()
    cache = {}

    def nbytes(name):
        b = cache.get(name)
        if b is None:
            b = cache[name] = var_bytes(name)
        return b

    series = []
    peak, peak_op, peak_live = 0, None, ()
    for i in range(len(lv.ops)):
        live = lv.live_in[i] | lv.defs[i]
        total = 0
        for n in live:
            total += nbytes(n)
        series.append(total)
        if total > peak:
            peak, peak_op, peak_live = total, i, live
    top = []
    if top_n and peak_live:
        def_sites = lv.def_sites()
        ranked = sorted(peak_live, key=lambda n: (-nbytes(n), n))
        for name in ranked[:int(top_n)]:
            if nbytes(name) <= 0:
                break
            defs = [d for d in def_sites.get(name, ())
                    if d <= peak_op]
            d = defs[-1] if defs else None
            top.append({"name": name, "bytes": int(nbytes(name)),
                        "def_op": d,
                        "def_op_type": (lv.ops[d].type
                                        if d is not None else None)})
    return {"series": series, "peak_bytes": peak, "peak_op": peak_op,
            "top_buffers": top}


def liveness_peak_bytes(op_descs, var_bytes, final_live=()):
    """(peak, op_index) — the timeline walk reduced to its peak; see
    `liveness_timeline` for the full series + blamed buffers."""
    tl = liveness_timeline(op_descs, var_bytes, final_live=final_live)
    return tl["peak_bytes"], tl["peak_op"]


class Liveness:
    """Forward liveness over a straight-line op list (same uses/defs/
    live-in/live-out construction as the reference ControlFlowGraph).

    `final_live` seeds the live set after the last op (fetch targets,
    persistables) — the original transpiler seeded it empty and
    handled persistables separately; both behaviors are expressible.
    """

    def __init__(self, op_descs, final_live=()):
        self.ops = list(op_descs)
        self.uses = [set(od.input_names()) - {EMPTY} for od in self.ops]
        self.defs = [set(od.output_names()) - {EMPTY} for od in self.ops]
        self.live_in = [set() for _ in self.ops]
        self.live_out = [set() for _ in self.ops]
        self.final_live = set(final_live)

    def analyze(self):
        changed = True
        n = len(self.ops)
        while changed:
            changed = False
            for i in reversed(range(n)):
                live_out = (self.live_in[i + 1] if i + 1 < n
                            else self.final_live)
                live_in = self.uses[i] | (live_out - self.defs[i])
                if live_in != self.live_in[i] or \
                        live_out != self.live_out[i]:
                    self.live_in[i] = live_in
                    self.live_out[i] = live_out
                    changed = True
        return self

    def reuse_candidates(self, persistable=()):
        """Vars dead after each op whose buffer a later def could
        reuse: {op_index: [names]} (what XLA's buffer assignment will
        actually fold).  `persistable` names never release."""
        persistable = set(persistable)
        released = defaultdict(list)
        for i in range(len(self.ops)):
            dead = (self.live_in[i] | self.defs[i]) - self.live_out[i]
            for name in sorted(dead - persistable):
                released[i].append(name)
        return dict(released)

    # -- def-use chains ------------------------------------------------------
    def def_sites(self):
        """name -> ordered op indices that write it."""
        sites = defaultdict(list)
        for i, ds in enumerate(self.defs):
            for n in ds:
                sites[n].append(i)
        return dict(sites)

    def use_sites(self):
        """name -> ordered op indices that read it."""
        sites = defaultdict(list)
        for i, us in enumerate(self.uses):
            for n in us:
                sites[n].append(i)
        return dict(sites)

    def reachability(self):
        """Per-op bitset of ops reachable through def-use edges
        (i reaches j if j transitively consumes a value i defines).
        Edges only go forward in list order, so one reverse sweep
        suffices.  Returns a list of ints: bit j set in reach[i] means
        i reaches j (every op reaches itself)."""
        n = len(self.ops)
        last_def = {}
        succs = [[] for _ in range(n)]
        for j in range(n):
            for name in self.uses[j]:
                i = last_def.get(name)
                if i is not None:
                    succs[i].append(j)
            for name in self.defs[j]:
                last_def[name] = j
        reach = [0] * n
        for i in reversed(range(n)):
            r = 1 << i
            for j in succs[i]:
                r |= reach[j]
            reach[i] = r
        return reach


def _in_place_pairs(od):
    """[(out_slot, in_slot)] pairs that alias for this op: registry
    `in_place_outputs` declarations, plus any output that names the
    same var as an input (the by-name in-place idiom: optimizer state,
    scale-into-self).  The aliased input slot is "FooOut" -> "Foo",
    falling back to the prefix convention for abbreviated output slots
    (ftrl's "SquaredAccumOut" aliases "SquaredAccumulator")."""
    declared = ()
    if op_registry.has_op(od.type):
        declared = op_registry.get_op_info(od.type).in_place_outputs
    pairs = []
    for out_slot in declared:
        base = out_slot[:-3] if out_slot.endswith("Out") else out_slot
        if base in od.inputs:
            in_slot = base
        else:
            matches = sorted(s for s in od.inputs if s.startswith(base))
            in_slot = matches[0] if matches else None
        pairs.append((out_slot, in_slot))
    return pairs


def _attr_name_refs(od):
    """Names an op references through plain STRING attrs — the
    `recurrent` op wires its sub-block through name-list attrs
    (mem_pre_names/mem_post_names/step_input_names/closure_names/
    step_output_names), which slot-only scanning cannot see; killing
    the body ops that define those names silently degenerates the
    scan.  Conservative by construction: a cosmetic string attr that
    happens to match a var name only keeps that var alive."""
    refs = set()
    for v in od.attrs.values():
        if isinstance(v, str):
            refs.add(v)
        elif isinstance(v, (list, tuple)):
            refs.update(x for x in v if isinstance(x, str))
    return refs


def _block_name_sets(desc):
    """Per-block sets of every name the block references (op slots +
    string attrs + declared vars) — computed ONCE per program; a
    block's cross-block live set is the union of every OTHER block's
    set."""
    sets = []
    for b in desc.blocks:
        names = set(b.vars)
        for od in b.ops:
            names.update(od.input_names())
            names.update(od.output_names())
            names.update(_attr_name_refs(od))
        names.discard(EMPTY)
        sets.append(names)
    return sets


def _block_sub_reads(desc, skip_idx, name_sets=None):
    """Names referenced by any block other than `skip_idx` — those
    cross block boundaries by name and must be treated as live."""
    if name_sets is None:
        name_sets = _block_name_sets(desc)
    names = set()
    for idx, s in enumerate(name_sets):
        if idx != skip_idx:
            names |= s
    return names


def _is_effectful(od):
    """Ops the dead-code pass must never remove-or-flag: host ops
    (print/save/send have side effects), unregistered types (already a
    V001), and anything holding a sub-block."""
    info = resolve_op_info(od.type)
    if info is None or not info.jittable:
        return True
    from ..core.desc import BlockRef

    for v in od.attrs.values():
        if isinstance(v, BlockRef) or (isinstance(v, (list, tuple))
                                       and any(isinstance(x, BlockRef)
                                               for x in v)):
            return True
    return False


def _referenced_names(desc):
    """Every name any op in any block reads or writes — the D002
    universe, computed ONCE per program (analyze_dataflow passes it
    down).  String attr refs count: the recurrent op names its
    carries through attrs, and sweeping those VarDescs would break
    the scan lowering."""
    referenced = set()
    for b in desc.blocks:
        for od in b.ops:
            referenced.update(od.input_names())
            referenced.update(od.output_names())
            referenced.update(_attr_name_refs(od))
    return referenced


def dead_op_indices(desc, block_idx, fetches, name_sets=None):
    """The D001 dead set for one block: op indices none of whose
    outputs are ever read by a live op, fetched, persisted, or
    referenced by another block.  Iterates to a fixpoint (killing an
    op may kill its producers); effectful ops (host side effects,
    sub-block holders, unregistered types) are never dead.

    Shared by the D001 diagnostic below and the dead-op-elimination
    rewrite pass (`paddle_tpu.compile.passes`), so the lint and the
    transform can never disagree about what is removable.  Returns
    (dead_index_set, Liveness).

    The live seed takes the WHOLE cross-block read set, not just the
    names this block declares: control-flow carry variables (a while
    body writing `acc` declared in its parent) are referenced by the
    parent op's slots but declared elsewhere — intersecting with
    `bd.vars` would make the body's carried writes look dead."""
    bd = desc.block(block_idx)
    persistable = {n for n, vd in bd.vars.items() if vd.persistable}
    sub_reads = _block_sub_reads(desc, block_idx, name_sets=name_sets)
    live_seed = set(persistable) | sub_reads | set(fetches or ())
    lv = Liveness(bd.ops, final_live=live_seed).analyze()
    dead = set()
    changed = True
    while changed:
        changed = False
        needed = set(live_seed)
        for i in reversed(range(len(lv.ops))):
            if i in dead:
                continue
            if _is_effectful(lv.ops[i]) or (lv.defs[i] & needed):
                needed |= lv.uses[i]
            else:
                dead.add(i)
                changed = True
    return dead, lv


def analyze_block(desc, block_idx, report, fetches=None,
                  referenced=None, name_sets=None):
    """Dead-code + hazard diagnostics for one block."""
    bd = desc.block(block_idx)

    # -- dead ops (only with a fetch set; see module docstring) -------------
    if fetches is not None:
        # without a fetch set every sink is live by assumption; with
        # one, the shared fixpoint names the removable set (its
        # Liveness doubles as this block's analysis — the hazard
        # checks below only read def/use structure, not the seed)
        dead, lv = dead_op_indices(desc, block_idx, fetches,
                                   name_sets=name_sets)
        for i in sorted(dead):
            od = lv.ops[i]
            outs = sorted(lv.defs[i])
            report.add(Diagnostic(
                "D001", Severity.WARNING,
                "dead op: output(s) %s are never read, fetched, or "
                "persisted" % (", ".join(map(repr, outs)) or "(none)"),
                block_idx=block_idx, op_index=i, op_type=od.type,
                var_name=outs[0] if outs else None))
    else:
        persistable = {n for n, vd in bd.vars.items()
                       if vd.persistable}
        sub_reads = _block_sub_reads(desc, block_idx,
                                     name_sets=name_sets)
        lv = Liveness(bd.ops,
                      final_live=persistable | sub_reads).analyze()

    # -- dead vars ----------------------------------------------------------
    if referenced is None:
        referenced = _referenced_names(desc)
    for name, vd in bd.vars.items():
        if name in referenced or vd.persistable:
            continue
        if fetches is not None and name in fetches:
            continue
        report.add(Diagnostic(
            "D002", Severity.INFO,
            "var is declared but no op reads or writes it",
            block_idx=block_idx, var_name=name))

    # -- write/alias hazards ------------------------------------------------
    reach = lv.reachability()

    def ordered(a, b):
        return bool(reach[a] & (1 << b)) or bool(reach[b] & (1 << a))

    def_sites = lv.def_sites()
    use_sites = lv.use_sites()

    for name, writers in def_sites.items():
        if len(writers) < 2:
            continue
        for a, b in zip(writers, writers[1:]):
            # a read anywhere in (a, b] consumes the first value: the
            # overwrite is an intentional in-place chain or var reuse,
            # not a lost update — but each such reader must itself be
            # ordered against the overwrite, else it races it (the
            # read-write half of the hazard detector)
            between = [u for u in use_sites.get(name, ())
                       if a < u <= b]
            if not between:
                if not ordered(a, b):
                    report.add(Diagnostic(
                        "H001", Severity.ERROR,
                        "write-write race: op %d (%s) and op %d (%s) "
                        "both write %r with no read in between and no "
                        "dataflow path ordering them — the first "
                        "value is lost"
                        % (a, lv.ops[a].type, b, lv.ops[b].type, name),
                        block_idx=block_idx, op_index=b,
                        op_type=lv.ops[b].type, var_name=name))
                continue
            if name in lv.uses[b]:
                continue  # in-place overwrite: the alias loop below
                          # checks every reader against the writer
            for u in between:
                if u == b or ordered(u, b):
                    continue
                report.add(Diagnostic(
                    "H002", Severity.WARNING,
                    "overwrite of %r by op %d (%s) races op %d (%s), "
                    "which reads the previous value with no dataflow "
                    "path to the overwrite; only list order protects "
                    "this today"
                    % (name, b, lv.ops[b].type, u, lv.ops[u].type),
                    block_idx=block_idx, op_index=b,
                    op_type=lv.ops[b].type, var_name=name))

    for w, od in enumerate(lv.ops):
        in_place_names = set()
        for out_slot, in_slot in _in_place_pairs(od):
            outs = od.output(out_slot)
            ins = od.input(in_slot) if in_slot else []
            for k, out_name in enumerate(outs):
                if out_name == EMPTY:
                    continue
                in_name = ins[k] if k < len(ins) else None
                if in_name is not None and in_name != out_name:
                    report.add(Diagnostic(
                        "H003", Severity.WARNING,
                        "slot %r is declared in-place over %r but "
                        "writes %r while reading %r — the update "
                        "forks the state instead of advancing it"
                        % (out_slot, in_slot, out_name, in_name),
                        block_idx=block_idx, op_index=w,
                        op_type=od.type, var_name=out_name))
                else:
                    in_place_names.add(out_name)
        # the by-name idiom: any output that is also an input
        in_place_names |= (lv.defs[w] & lv.uses[w])

        for name in sorted(in_place_names):
            for r in use_sites.get(name, ()):
                if r == w or ordered(w, r):
                    continue
                report.add(Diagnostic(
                    "H002", Severity.WARNING,
                    "in-place update of %r races op %d (%s), which "
                    "reads it with no dataflow path to or from the "
                    "writer; only list order protects this today"
                    % (name, r, lv.ops[r].type),
                    block_idx=block_idx, op_index=w, op_type=od.type,
                    var_name=name))
    return report


def analyze_dataflow(desc, fetches=None, suppress=(), report=None):
    """Dead-code + hazard diagnostics for every block of a ProgramDesc
    (or Program); returns a `Report`."""
    desc = getattr(desc, "desc", desc)
    report = report if report is not None else Report(suppress=suppress)
    referenced = _referenced_names(desc)
    name_sets = _block_name_sets(desc)
    for block_idx in range(len(desc.blocks)):
        analyze_block(desc, block_idx, report,
                      fetches=fetches if block_idx == 0 else None,
                      referenced=referenced, name_sets=name_sets)
    return report
