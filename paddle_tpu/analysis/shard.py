"""Static SPMD/sharding analysis over the Program IR.

A bad partition rule, a non-divisible mesh axis, or a mis-ordered
collective fails minutes into an XLA compile — or worse, silently
replicates a tensor that should be sharded.  GSPMD-style sharding
propagation is exactly the kind of property that can be checked
*statically*: this pass propagates `PartitionSpec`s (the default
`parallel/sharding.py` rules, or explicit `match_partition_rules`-style
regex rules) through every op of a Program against a mesh description,
and reports stable diagnostics:

  S001 unsharded-param   a parameter (or ZeRO-1 optimizer slot) falls
                 back to replication: it matched no partition rule, or
                 min_shard_dim / divisibility forced the fallback.  The
                 message cites the reason (`param_spec_reason`).
                 Warning when the tensor is large enough that sharding
                 would have paid; info otherwise.
  S002 non-divisible     a sharded dim's static size is not divisible
                 by the product of its mesh axes — GSPMD would pad or
                 the lowering would reject it minutes later.  Error at
                 spec-introduction points (params, rules, concrete
                 trainer feeds, sequence extents); advisory for the
                 feed batch of pinned/exported IR, where the batch is
                 a runtime choice a rebuild can fix.
  S003 spec-conflict     two inputs of an op demand incompatible
                 layouts for the same dim — GSPMD inserts an implicit
                 reshard (all-gather) at that seam.  Warning; the
                 reshard is priced into the comm cost report.
  S004 schedule-hazard   collective ordering/deadlock hazards in the
                 pipeline/ring/moe schedules: an axis name missing
                 from the mesh, stage-count vs pp-size mismatch,
                 microbatch-count vs pp-stage mismatch (bubble
                 dominance), MoE expert-count not divisible by ep, or
                 MoE capacity overflow (guaranteed token drops).
  S005 hbm-over-budget   the static per-device peak-HBM estimate
                 (sharded params + optimizer state + liveness-derived
                 activation peak) exceeds a caller-supplied budget.
                 Error.

`analyze_sharding` is the program-level entry point; `check_pipeline`
/ `check_moe` / `check_ring` cover the schedule-level hazards that
have no Program to walk.  The mesh argument is anything with an
axis-name -> size mapping: a built `jax.sharding.Mesh`, a
`parallel.mesh.MeshConfig`, or a plain dict — so a lint can run
against `dp=256,mp=4` from a laptop with zero devices.

Wired in at the trust boundaries (all gated by FLAGS_verify_sharding):
`ParallelTrainer.init` / `make_parallel_step` analyze before any
lowering, the multichip dryrun refuses meshes that fail clean, and
`proglint --mesh dp=4,mp=2` runs it from CI.  Communication costs ride
along in a `costmodel.CommCostReport`
(`shard_comm_bytes_total{collective}` in the obs registry).
"""

import re
from collections import OrderedDict

from ..core.types import GRAD_SUFFIX
from .common import EMPTY, find_var_desc
from .costmodel import CommCostReport
from .dataflow import liveness_timeline
from .diagnostics import Diagnostic, Report, Severity

__all__ = ["analyze_sharding", "ShardingPlan", "mesh_axis_sizes",
           "check_pipeline", "check_moe", "check_ring"]

# ops whose outputs alias their inputs (state advance): specs are
# preserved by construction, nothing to propagate
_UPDATE_OPS = frozenset([
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
    "fused_update"])

_NON_STATE_SLOTS = frozenset(["Param", "Grad", "LearningRate"])

_MATMUL_OPS = frozenset(["mul", "matmul"])

_REDUCE_OPS = frozenset(["mean", "reduce_sum", "reduce_mean",
                         "reduce_max", "reduce_min", "reduce_prod"])

_BYTES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
          "float16": 2, "bfloat16": 2, "uint8": 1, "int8": 1, "bool": 1}


# ---------------------------------------------------------------------------
# mesh / spec plumbing
# ---------------------------------------------------------------------------

def mesh_axis_sizes(mesh):
    """Axis-name -> size for a jax Mesh, MeshConfig, or plain dict."""
    shape = getattr(mesh, "shape", mesh)
    try:
        items = list(dict(shape).items())
    except (TypeError, ValueError):
        raise TypeError("mesh must be a jax Mesh, a MeshConfig, or an "
                        "axis->size mapping; got %r" % (mesh,))
    return OrderedDict((str(a), int(s)) for a, s in items)


class _MeshView:
    """Duck-typed stand-in for a jax Mesh: just the `.shape` mapping,
    which is all `parallel.sharding`'s spec rules consult."""

    def __init__(self, axes):
        self.shape = axes


def _norm_spec(spec, ndim):
    """PartitionSpec / tuple -> canonical tuple of length `ndim` whose
    entries are None, an axis name, or a tuple of axis names."""
    entries = list(tuple(spec))[:ndim] if spec is not None else []
    entries += [None] * (ndim - len(entries))
    out = []
    for e in entries:
        if e is None:
            out.append(None)
        elif isinstance(e, (list, tuple)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append(str(e))
    return tuple(out)


def _dim_axes(entry):
    if entry is None:
        return ()
    if isinstance(entry, tuple):
        return entry
    return (entry,)


def _spec_str(spec):
    if not any(e is not None for e in spec):
        return "P() [replicated]"
    return "P(%s)" % ", ".join(
        "None" if e is None else
        ("(%s)" % ",".join(e) if isinstance(e, tuple) else e)
        for e in spec)


def _shard_factor(spec, axes):
    f = 1
    for e in spec:
        for a in _dim_axes(e):
            f *= axes.get(a, 1)
    return max(f, 1)


def _numel(shape):
    n = 1
    for s in shape or ():
        n *= max(int(s), 1)  # -1 (dynamic) counts as 1; documented
    return n


def _var_bytes(vd, spec, axes):
    if vd is None or vd.shape is None:
        return 0
    eb = _BYTES.get(vd.dtype, 4)
    return _numel(vd.shape) * eb // _shard_factor(spec, axes)


def _elem_bytes_of(desc, name):
    """Element size of a var by its recorded dtype (4 when unknown) —
    so comm pricing of bf16 programs stays consistent with the
    dtype-aware grad-sync pricing."""
    vd = find_var_desc(desc, 0, name)
    if vd is None or vd.dtype is None:
        return 4
    return _BYTES.get(vd.dtype, 4)


def _check_axes_known(name, spec, axes, report, op_index=None,
                      op_type=None):
    """S004: a user-supplied spec (partition rule / feed override)
    naming an axis the mesh does not have would silently analyze as
    unsharded (factor 1) while the real lowering rejects or
    replicates — the exact typo class this analyzer exists to catch."""
    ok = True
    for e in spec:
        for a in _dim_axes(e):
            if a not in axes:
                report.add(Diagnostic(
                    "S004", Severity.ERROR,
                    "spec %s names axis %r, which is not a mesh axis "
                    "(mesh has %s)" % (_spec_str(spec), a, list(axes)),
                    op_index=op_index, op_type=op_type, var_name=name))
                ok = False
    return ok


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------

class ShardingPlan:
    """The analyzer's output: per-var specs, replication reasons, the
    merged diagnostic report, the comm cost report, and the per-device
    HBM estimate."""

    def __init__(self, mesh_axes, report, comm):
        self.mesh_axes = mesh_axes
        self.report = report
        self.comm = comm
        self.var_specs = {}        # name -> canonical spec tuple
        self.param_reasons = {}    # name -> why it replicated (or None)
        self.peak_hbm_bytes = None
        self.hbm_breakdown = {}

    def spec_of(self, name):
        return self.var_specs.get(name)

    def sharded_params(self):
        return sorted(n for n in self.param_reasons
                      if any(e is not None for e in self.var_specs[n]))

    def replicated_params(self):
        return sorted(n for n in self.param_reasons
                      if not any(e is not None for e in self.var_specs[n]))

    def to_dict(self, topk=10):
        return {
            "mesh": dict(self.mesh_axes),
            "params_sharded": len(self.sharded_params()),
            "params_replicated": len(self.replicated_params()),
            "replication_reasons": {
                n: r for n, r in sorted(self.param_reasons.items()) if r},
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "hbm_breakdown": dict(self.hbm_breakdown),
            "comm": self.comm.to_dict(topk=topk),
        }

    def publish(self, origin="shard", diagnostics=True):
        """Diagnostic counters + comm bytes + peak-HBM gauge into the
        obs registry.  `diagnostics=False` skips the Report counters —
        for callers that merged into an ALREADY-PUBLISHED report
        (re-publishing would double-count every earlier finding)."""
        if diagnostics:
            self.report.publish(origin=origin)
        self.comm.publish()
        if self.peak_hbm_bytes is not None:
            from ..obs import registry as registry_mod

            registry_mod.get_registry().gauge(
                "shard_peak_hbm_bytes",
                "static per-device peak-HBM estimate from the sharding "
                "analyzer").set(self.peak_hbm_bytes)
        return self


# ---------------------------------------------------------------------------
# program-level analysis
# ---------------------------------------------------------------------------

def analyze_sharding(program, mesh, feed_names=None, feed_specs=None,
                     rules=None, fetches=None, zero_stage=0,
                     dp_axis="dp", mp_axis="mp", min_shard_dim=512,
                     hbm_gb=None, suppress=(), report=None,
                     publish=False, origin="shard",
                     concrete_feeds=False):
    """Propagate PartitionSpecs through `program` against `mesh`.

    program: a Program or bare ProgramDesc (block 0 is analyzed; specs
        are a global-block property).
    mesh: jax Mesh / MeshConfig / {axis: size} dict.
    feed_names: runtime feeds (inferred as producer-less
        non-persistable vars when omitted); they shard their leading
        dim over `dp_axis` unless `feed_specs` overrides.
    rules: optional match_partition_rules-style [(regex, spec), ...];
        first match wins, an unmatched param is an S001.  When None the
        default `param_spec_reason` heuristic applies and S001 cites
        its reason for any forced replication.
    zero_stage: >=1 prices/checks the ZeRO-1 optimizer-state layout.
    hbm_gb: per-device HBM budget in GiB; enables the S005 check.
    concrete_feeds: the feed shapes ARE the runtime shapes (the
        ParallelTrainer boundary) — a non-divisible static batch dim
        is then an S002 error.  False (linting pinned/exported IR)
        demotes it to an advisory: the batch is a runtime choice a
        rebuild can fix, unlike a parameter dim.

    Returns a `ShardingPlan` (`.report` has the diagnostics; pass
    `report=` to merge into an existing Report, e.g. check_program's).
    """
    desc = getattr(program, "desc", program)
    axes = mesh_axis_sizes(mesh)
    mesh_view = _MeshView(axes)
    report = report if report is not None else Report(suppress=suppress)
    comm = CommCostReport()
    plan = ShardingPlan(axes, report, comm)
    bd = desc.block(0)

    from ..parallel.sharding import param_spec_reason, zero1_spec_reason

    produced, consumed = set(), set()
    for od in bd.ops:
        produced.update(n for n in od.output_names() if n != EMPTY)
        consumed.update(n for n in od.input_names() if n != EMPTY)

    # -- parameters ---------------------------------------------------------
    params = {n: vd for n, vd in bd.vars.items()
              if getattr(vd, "is_parameter", False)}
    compiled_rules = None
    if rules is not None:
        compiled_rules = [(re.compile(pat), spec) for pat, spec in rules]
    for name, vd in sorted(params.items()):
        shape = vd.shape or ()
        reason = None
        if compiled_rules is not None:
            spec = None
            for pat, s in compiled_rules:
                if pat.search(name):
                    spec = _norm_spec(s, len(shape))
                    _check_axes_known(name, spec, axes, report)
                    break
            if spec is None:
                spec = _norm_spec((), len(shape))
                reason = "matched no partition rule"
                report.add(Diagnostic(
                    "S001", Severity.WARNING,
                    "parameter matched no partition rule: silently "
                    "replicated on all %d devices"
                    % _total_devices(axes), var_name=name))
        else:
            raw, reason = param_spec_reason(name, shape, mesh_view,
                                            mp_axis=mp_axis,
                                            min_shard_dim=min_shard_dim)
            spec = _norm_spec(raw, len(shape))
            if reason is not None:
                # worth a warning only when some dim could have
                # sharded profitably (>= min_shard_dim) yet didn't
                big = shape and max(int(s) for s in shape) \
                    >= min_shard_dim
                report.add(Diagnostic(
                    "S001",
                    Severity.WARNING if big else Severity.INFO,
                    "parameter falls back to replication: %s" % reason,
                    var_name=name))
        plan.param_reasons[name] = reason
        plan.var_specs[name] = spec
        _check_divisible(name, shape, spec, axes, report, op_index=None)

    # -- optimizer state ----------------------------------------------------
    state_param = _optimizer_state_params(bd)
    for name, pname in sorted(state_param.items()):
        vd = bd.vars.get(name)
        if vd is None or name in plan.var_specs:
            continue
        shape = vd.shape or ()
        base = plan.var_specs.get(pname, _norm_spec((), len(shape)))
        spec = base
        if zero_stage >= 1:
            raw, zreason = zero1_spec_reason(base, shape, mesh_view,
                                             dp_axis=dp_axis)
            spec = _norm_spec(raw, len(shape))
            if zreason is not None:
                report.add(Diagnostic(
                    "S001", Severity.INFO,
                    "zero-1 optimizer state stays unsharded: %s"
                    % zreason, var_name=name))
        plan.var_specs[name] = spec
        _check_divisible(name, shape, spec, axes, report, op_index=None)

    # -- feeds --------------------------------------------------------------
    feed_severity = Severity.ERROR if concrete_feeds else Severity.INFO
    if feed_names is None:
        feed_names = [n for n, vd in bd.vars.items()
                      if not vd.persistable and n not in produced
                      and n in consumed and n not in plan.var_specs]
    feed_specs = dict(feed_specs or {})
    for name in feed_names:
        vd = bd.vars.get(name)
        if vd is None:
            continue
        shape = vd.shape or ()
        if name in feed_specs:
            spec = _norm_spec(feed_specs[name], len(shape))
            _check_axes_known(name, spec, axes, report)
        elif shape and dp_axis in axes:
            spec = _norm_spec((dp_axis,), len(shape))
        else:
            spec = _norm_spec((), len(shape))
        plan.var_specs[name] = spec
        _check_divisible(name, shape, spec, axes, report, op_index=None,
                         severity=feed_severity,
                         hint=None if concrete_feeds else
                         " — a rebuild with a divisible batch fixes "
                         "this; the parameter layout is unaffected")

    # -- propagate through the op list --------------------------------------
    for i, od in enumerate(bd.ops):
        if od.type in ("flash_attention", "flash_attention_grad"):
            _check_flash_attention(desc, bd, i, od, axes, comm, report)
        if od.type in _UPDATE_OPS:
            continue  # outputs alias inputs; specs preserved
        _propagate_op(desc, bd, i, od, axes, plan, comm, report)

    # -- gradient synchronization cost --------------------------------------
    dp = axes.get(dp_axis, 1)
    for name, vd in sorted(params.items()):
        gname = name + GRAD_SUFFIX
        if gname not in produced:
            continue
        spec = plan.var_specs.get(name, ())
        nbytes = _var_bytes(vd, spec, axes)
        if dp > 1 and not any(dp_axis in _dim_axes(e) for e in spec):
            if zero_stage >= 1:
                comm.add("reducescatter", dp_axis, dp, nbytes,
                         "grad reduce-scatter %s" % name)
                comm.add("allgather", dp_axis, dp, nbytes,
                         "param all-gather %s" % name)
            else:
                comm.add("allreduce", dp_axis, dp, nbytes,
                         "grad sync %s" % name)

    # -- per-device peak HBM -------------------------------------------------
    _estimate_hbm(desc, bd, plan, axes, fetches, state_param, hbm_gb,
                  report)

    if publish:
        plan.publish(origin=origin)
    return plan


def _total_devices(axes):
    n = 1
    for s in axes.values():
        n *= s
    return n


def _optimizer_state_params(bd):
    """{state var name: param name} from the block's update ops (the
    desc-level sibling of parallel.sharding.optimizer_state_names)."""
    out = {}
    for od in bd.ops:
        if od.type not in _UPDATE_OPS:
            continue
        pnames = od.input("Param")
        pname = pnames[0] if pnames else None
        for slot, names in od.inputs.items():
            if slot in _NON_STATE_SLOTS:
                continue
            for n in names:
                if n != EMPTY and pname is not None:
                    out.setdefault(n, pname)
    return out


def _check_divisible(name, shape, spec, axes, report, op_index=None,
                     op_type=None, severity=Severity.ERROR, hint=None):
    """S002: a sharded STATIC dim must divide by its axes' product
    (dynamic -1 dims are runtime-bucketed; nothing to check).  Only
    the INTRODUCTION point of a spec is checked — a propagated dim was
    already checked at its source, so downstream vars never repeat the
    finding."""
    bad = False
    for d, (s, e) in enumerate(zip(shape or (), spec)):
        ax = _dim_axes(e)
        if not ax:
            continue
        prod = 1
        for a in ax:
            prod *= axes.get(a, 1)
        if prod > 1 and s is not None and int(s) > 0 and int(s) % prod:
            report.add(Diagnostic(
                "S002", severity,
                "dim %d (size %d) sharded %s is not divisible by "
                "%s=%d%s"
                % (d, int(s), _spec_str(spec), "*".join(ax), prod,
                   hint or ""),
                op_index=op_index, op_type=op_type, var_name=name))
            bad = True
    return bad


def _spec_for(plan, name, ndim):
    s = plan.var_specs.get(name)
    if s is None:
        return _norm_spec((), ndim)
    return s if len(s) == ndim else _norm_spec(s, ndim)


def _propagate_op(desc, bd, i, od, axes, plan, comm, report):
    """Transfer function for one op: derive output specs from input
    specs, flag S003 conflicts, and record partial-sum collectives."""
    def shape_of(name):
        vd = find_var_desc(desc, 0, name)
        return None if vd is None else vd.shape

    ins = []
    for n in od.input_names():
        if n == EMPTY:
            continue
        shp = shape_of(n)
        if shp is None:
            continue
        ins.append((n, shp, _spec_for(plan, n, len(shp))))

    for slot, names in od.outputs.items():
        for out_name in names:
            if out_name == EMPTY:
                continue
            out_shape = shape_of(out_name)
            if out_shape is None:
                continue
            ndim = len(out_shape)
            if out_name in plan.var_specs:
                continue  # params/feeds keep their assigned layout

            spec = None
            # the backward contract: X@GRAD mirrors X
            if out_name.endswith(GRAD_SUFFIX):
                src = out_name[: -len(GRAD_SUFFIX)]
                if src in plan.var_specs:
                    src_shape = shape_of(src)
                    if src_shape is not None \
                            and len(src_shape) == ndim:
                        spec = _spec_for(plan, src, ndim)
            if spec is None and od.type in _MATMUL_OPS \
                    and slot == "Out":
                spec = _matmul_spec(desc, od, i, ins, out_shape, axes,
                                    plan, comm, report)
            if spec is None and od.type in _REDUCE_OPS:
                spec = _norm_spec((), ndim)
                sharded = [s for _n, _shp, s in ins
                           if any(e is not None for e in s)]
                if sharded:
                    ax = next(a for e in sharded[0]
                              for a in _dim_axes(e))
                    comm.add("allreduce", ax, axes.get(ax, 1),
                             _numel(out_shape)
                             * _elem_bytes_of(desc, out_name),
                             "partial reduce at op %d (%s)"
                             % (i, od.type))
            if spec is None:
                spec = _generic_spec(desc, od, i, ins, out_name,
                                     out_shape, axes, comm, report)
            # no divisibility re-check here: every propagated dim was
            # checked where its spec was introduced (param/feed/rule)
            plan.var_specs[out_name] = spec


def _matmul_spec(desc, od, i, ins, out_shape, axes, plan, comm,
                 report):
    """mul/matmul: rows from X, cols from Y, and a partial-sum
    all-reduce when the contracted dim is sharded (the Megatron
    row-parallel pattern)."""
    xs = od.input("X")
    ys = od.input("Y")
    if not xs or not ys:
        return None
    by_name = {n: (shp, s) for n, shp, s in ins}
    if xs[0] not in by_name or ys[0] not in by_name:
        return None
    x_shape, x_spec = by_name[xs[0]]
    y_shape, y_spec = by_name[ys[0]]
    ndim = len(out_shape)
    if od.type == "mul":
        col = int(od.attr("x_num_col_dims", 1) or 1)
    else:
        col = max(len(x_shape) - 1, 1)
        if od.attr("transpose_X") or od.attr("transpose_Y"):
            return None  # transposed operands: stay conservative
    k_x = x_spec[-1] if x_spec else None
    # Y's contraction dim: -2 for (batched) matmul [.., k, n]; dim 0
    # for mul (Y is 2-D [k, n]) and 1-D vector operands
    k_y = y_spec[-2] if len(y_shape) >= 2 else \
        (y_spec[0] if y_spec else None)
    out = list(_norm_spec((), ndim))
    for d in range(min(col, ndim)):
        out[d] = x_spec[d] if d < len(x_spec) else None
    if ndim > col and len(y_spec) >= 2:
        out[-1] = y_spec[-1]
    kx_axes, ky_axes = set(_dim_axes(k_x)), set(_dim_axes(k_y))
    if kx_axes and ky_axes:
        if kx_axes == ky_axes:
            ax = sorted(kx_axes)[0]
            n = 1
            for a in kx_axes:
                n *= axes.get(a, 1)
            out_name = (od.output("Out") or [None])[0]
            nbytes = _numel(out_shape) \
                * _elem_bytes_of(desc, out_name) \
                // _shard_factor(tuple(out), axes)
            comm.add("allreduce", ax, n, nbytes,
                     "matmul partial-sum at op %d (%s -> %s)"
                     % (i, xs[0], out_name))
        else:
            report.add(Diagnostic(
                "S003", Severity.WARNING,
                "contraction dim sharded on incompatible axes: %r is "
                "%s, %r is %s — GSPMD must reshard one side"
                % (xs[0], _spec_str(x_spec), ys[0], _spec_str(y_spec)),
                op_index=i, op_type=od.type, var_name=xs[0]))
    return tuple(out)


def _generic_spec(desc, od, i, ins, out_name, out_shape, axes, comm,
                  report):
    """Default transfer: dimwise join over same-shape inputs (S003 on
    disagreement), else carry the leading-dim (batch) axis from an
    input with the same leading extent, else replicate."""
    ndim = len(out_shape)
    same = [(n, s) for n, shp, s in ins
            if tuple(shp or ()) == tuple(out_shape)]
    if same:
        out = [None] * ndim
        conflicted = False
        for n, s in same:
            for d, e in enumerate(s[:ndim]):
                if e is None:
                    continue
                if out[d] is None:
                    out[d] = e
                elif out[d] != e and not conflicted:
                    conflicted = True
                    first = next(nm for nm, sp in same
                                 if sp[d] == out[d])
                    report.add(Diagnostic(
                        "S003", Severity.WARNING,
                        "inputs demand incompatible layouts for dim "
                        "%d: %r wants %s, %r wants %s — GSPMD inserts "
                        "an implicit reshard here"
                        % (d, first, _axis_str(out[d]), n,
                           _axis_str(e)),
                        op_index=i, op_type=od.type, var_name=n))
                    shp = next(shp for nm, shp, sp in ins if nm == n)
                    ax = _dim_axes(e)[0]
                    comm.add("allgather", ax, axes.get(ax, 1),
                             _numel(shp) * _elem_bytes_of(desc, n),
                             "implicit reshard of %s at op %d (%s)"
                             % (n, i, od.type))
        return tuple(out)
    if ndim >= 1:
        lead = out_shape[0]
        for n, shp, s in ins:
            if not shp or s[0] is None:
                continue
            if int(shp[0]) == int(lead) or (int(shp[0]) < 0
                                            and int(lead) < 0):
                return tuple([s[0]] + [None] * (ndim - 1))
    return _norm_spec((), ndim)


def _axis_str(entry):
    return "+".join(_dim_axes(entry)) or "None"


def _check_flash_attention(desc, bd, i, od, axes, comm, report):
    """S004/S002 for in-program sequence parallelism: the op's
    `sequence_parallel_axis` attr must name a mesh axis, the sequence
    extent must divide by it (ring), and ulysses additionally needs
    the head count divisible (the all-to-all head swap)."""
    sp_axis = od.attr("sequence_parallel_axis", "") or ""
    if not sp_axis:
        return
    if sp_axis not in axes:
        # the op degrades gracefully (local attention) when the mesh
        # lacks the axis — that's the single-chip path of a program
        # built for sp meshes, so advisory, not an error
        report.add(Diagnostic(
            "S004", Severity.INFO,
            "op declares sequence-parallel axis %r but the mesh has "
            "axes %s: attention runs WITHOUT sequence parallelism "
            "here" % (sp_axis, list(axes)),
            op_index=i, op_type=od.type))
        return
    sp = axes[sp_axis]
    if sp <= 1:
        return
    q = (od.input("Q") or [None])[0]
    vd = find_var_desc(desc, 0, q) if q else None
    shape = vd.shape if vd is not None else None
    if shape and len(shape) == 3:
        t = int(shape[1])
        if t > 0 and t % sp:
            report.add(Diagnostic(
                "S002", Severity.ERROR,
                "sequence length %d not divisible by %s=%d"
                % (t, sp_axis, sp),
                op_index=i, op_type=od.type, var_name=q))
        mode = od.attr("sequence_parallel_mode", "ring") or "ring"
        heads = int(od.attr("num_heads", 1) or 1)
        if mode == "ulysses" and heads % sp:
            report.add(Diagnostic(
                "S004", Severity.ERROR,
                "ulysses all-to-all needs num_heads %d divisible by "
                "%s=%d" % (heads, sp_axis, sp),
                op_index=i, op_type=od.type))
        if t > 0 and od.type == "flash_attention":
            # ring cost: local K/V shards hop sp-1 times (a dynamic
            # batch dim prices at the documented -1 -> 1 floor)
            kv_bytes = 2 * _numel(shape) \
                * _elem_bytes_of(desc, q) // sp
            comm.add("ppermute", sp_axis, sp, kv_bytes * (sp - 1),
                     "ring attention K/V hops at op %d" % i)


def _estimate_hbm(desc, bd, plan, axes, fetches, state_param, hbm_gb,
                  report):
    """S005: params + optimizer state + liveness-derived activation
    peak, each divided by its spec's shard factor.  Dynamic (-1) dims
    count as 1, so the estimate is a floor for bucketed feeds."""
    persist_bytes = 0
    state_bytes = 0
    for name, vd in bd.vars.items():
        if not vd.persistable:
            continue
        spec = _spec_for(plan, name, len(vd.shape or ()))
        b = _var_bytes(vd, spec, axes)
        if name in state_param:
            state_bytes += b
        else:
            persist_bytes += b

    final_live = {n for n, vd in bd.vars.items() if vd.persistable}
    if fetches:
        final_live |= set(fetches)

    def _act_bytes(n):
        vd = bd.vars.get(n)
        if vd is None or vd.persistable:
            return 0
        return _var_bytes(vd, _spec_for(plan, n, len(vd.shape or ())),
                          axes)

    tl = liveness_timeline(bd.ops, _act_bytes, final_live, top_n=3)
    act_peak, peak_op = tl["peak_bytes"], tl["peak_op"]
    total = persist_bytes + state_bytes + act_peak
    plan.peak_hbm_bytes = total
    plan.hbm_breakdown = {
        "params_bytes": persist_bytes,
        "optimizer_state_bytes": state_bytes,
        "activation_peak_bytes": act_peak,
        "activation_peak_op": peak_op,
        # the top resident activations at the peak, blamed to their
        # defining ops (one shared liveness_timeline walk — the same
        # accounting the S005 total uses): the error can name WHICH
        # activations to remat instead of citing only totals
        "top_buffers": tl["top_buffers"],
    }
    if hbm_gb is not None and total > float(hbm_gb) * (1 << 30):
        top = "; ".join(
            "%s %.1f MiB (op %s %s)"
            % (b["name"], b["bytes"] / 2**20, b["def_op"],
               b["def_op_type"])
            for b in tl["top_buffers"])
        report.add(Diagnostic(
            "S005", Severity.ERROR,
            "static per-device peak HBM %.3f GiB (params %.3f + "
            "optimizer state %.3f + activation peak %.3f at op %s) "
            "exceeds the %.3f GiB budget%s"
            % (total / 2**30, persist_bytes / 2**30,
               state_bytes / 2**30, act_peak / 2**30, peak_op,
               float(hbm_gb),
               "" if not top else " — top resident: " + top),
            op_index=peak_op))


# ---------------------------------------------------------------------------
# schedule-level checks (no Program to walk)
# ---------------------------------------------------------------------------

def check_pipeline(mesh, n_stages, n_microbatches, axis_name="pp",
                   batch_size=None, report=None, suppress=()):
    """S004 hazards of a GPipe schedule: axis missing from the mesh,
    stage-count vs pp-size mismatch (the ppermute ring misroutes —
    stage i's output lands on a device holding different weights), and
    microbatch starvation (bubbles dominate)."""
    axes = mesh_axis_sizes(mesh)
    report = report if report is not None else Report(suppress=suppress)
    if axis_name not in axes:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "pipeline axis %r is not a mesh axis (mesh has %s)"
            % (axis_name, list(axes))))
        return report
    pp = axes[axis_name]
    if n_stages != pp:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "schedule stacks %d stages but mesh axis %s=%d — the "
            "stage-to-device ppermute ring would misroute activations"
            % (n_stages, axis_name, pp)))
    if n_microbatches < pp and (n_microbatches + pp - 1) > 0:
        report.add(Diagnostic(
            "S004", Severity.WARNING,
            "only %d microbatches for %d pipeline stages: bubble "
            "fraction %.0f%% of every step"
            % (n_microbatches, pp,
               100.0 * (pp - 1) / (n_microbatches + pp - 1))))
    if batch_size is not None and n_microbatches \
            and batch_size % n_microbatches:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "global batch %d not divisible into %d microbatches"
            % (batch_size, n_microbatches)))
    return report


def check_moe(mesh, n_experts, capacity_factor=1.25, tokens=None,
              axis_name="ep", batch_axis="dp", report=None,
              suppress=()):
    """S004/S002 hazards of the Switch-MoE dispatch: axis missing,
    expert count not divisible by ep (the all_to_all reshape needs
    e_loc = E/ep), token batch not divisible by its shard axes, and
    guaranteed capacity overflow (tokens dropped every step)."""
    axes = mesh_axis_sizes(mesh)
    report = report if report is not None else Report(suppress=suppress)
    if axis_name not in axes:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "expert axis %r is not a mesh axis (mesh has %s)"
            % (axis_name, list(axes))))
        return report
    ep = axes[axis_name]
    if n_experts % ep:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "%d experts not divisible by mesh axis %s=%d — the "
            "dispatch all_to_all needs %d local experts per device"
            % (n_experts, axis_name, ep, n_experts // max(ep, 1))))
    if tokens is not None:
        shard = ep * axes.get(batch_axis, 1)
        if tokens % shard:
            report.add(Diagnostic(
                "S002", Severity.ERROR,
                "token batch %d not divisible by %s*%s=%d"
                % (tokens, batch_axis, axis_name, shard)))
        elif n_experts and n_experts % ep == 0:
            from ..parallel.moe import expert_capacity

            b_local = tokens // shard
            cap = expert_capacity(b_local, n_experts, capacity_factor)
            if cap * n_experts < b_local:
                report.add(Diagnostic(
                    "S004", Severity.WARNING,
                    "expert capacity %d * %d experts < %d local "
                    "tokens (capacity_factor %.2f): >= %d tokens "
                    "dropped EVERY step even under perfect balance"
                    % (cap, n_experts, b_local, capacity_factor,
                       b_local - cap * n_experts)))
    return report


def check_ring(mesh, seq_len=None, n_heads=None, axis_name="sp",
               mode="ring", report=None, suppress=()):
    """S004/S002 hazards of sequence parallelism: axis missing,
    sequence not divisible by sp, ulysses head-swap divisibility."""
    axes = mesh_axis_sizes(mesh)
    report = report if report is not None else Report(suppress=suppress)
    if axis_name not in axes:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "sequence axis %r is not a mesh axis (mesh has %s)"
            % (axis_name, list(axes))))
        return report
    sp = axes[axis_name]
    if sp > 1 and seq_len is not None and seq_len % sp:
        report.add(Diagnostic(
            "S002", Severity.ERROR,
            "sequence length %d not divisible by %s=%d"
            % (seq_len, axis_name, sp)))
    if sp > 1 and mode == "ulysses" and n_heads is not None \
            and n_heads % sp:
        report.add(Diagnostic(
            "S004", Severity.ERROR,
            "ulysses all-to-all needs head count %d divisible by "
            "%s=%d" % (n_heads, axis_name, sp)))
    return report
