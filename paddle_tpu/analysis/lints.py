"""TPU-specific lint rules over the Program IR.

Where the verifier asks "is this program well-formed?" and the
dataflow pass asks "does it race?", the lints ask "will it be SLOW or
silently nondeterministic on this stack?":

  L001 dynamic-dim-mxu   a dynamic (-1) dim feeds an MXU op.  Every
                 distinct concrete size is a fresh XLA trace+compile —
                 the retrace storms serving exists to prevent.  A
                 dynamic LEADING dim (the batch dim) is advisory when
                 shape bucketing covers it (serving bucket hints /
                 DataFeeder buckets); a dynamic inner dim is a warning
                 always (nothing buckets those).
  L002 segment-split     a host (non-jittable) op sits between two
                 jittable runs, splitting what could be one fused XLA
                 executable into several, with a device sync at each
                 seam.
  L003 rng-no-seed       an op consumes the RNG stream with no seed
                 plumbing anywhere: seed attr 0, fix_seed unset, and
                 program.random_seed 0.  Replicated builds (pipeline
                 stages, data-parallel replicas) will all draw the
                 same default stream.
  L004 amp-dtype-mix     bf16/f32 mixes that violate the AMP policy
                 (fluid/amp.py): an op reading both bfloat16 and
                 float32 dense float tensors (implicit upcasts defeat
                 the bandwidth win), or a PERSISTABLE var declared
                 bfloat16 (master weights/stats must stay f32).
  L005 grad-orphan       a `@GRAD`-suffixed var that is declared but
                 neither produced nor consumed (a partial backward
                 left debris), or a produced parameter grad no op
                 consumes (the update the optimizer never applied).
"""

from ..core.types import GRAD_SUFFIX
from ..ops import registry as op_registry
from .common import EMPTY, find_var_desc as _find_vd, resolve_op_info
from .diagnostics import Diagnostic, Report, Severity

__all__ = ["lint_program"]


def _mxu_types():
    # the roofline analyzer's MXU family (fluid/analysis.py) plus the
    # fused attention op; lazy import keeps this package import-light
    from ..fluid.analysis import _MXU_FWD

    return set(_MXU_FWD) | {"flash_attention"}


def _op_jittable(od):
    info = resolve_op_info(od.type)
    # unknown: the verifier already flagged V001
    return info.jittable if info is not None else True


def _op_uses_rng(od):
    if not op_registry.has_op(od.type):
        return False  # grad kernels replay saved state, not the stream
    return op_registry.get_op_info(od.type).uses_rng


def _lint_block(desc, block_idx, report, mxu, random_seed,
                bucketed_feeds):
    bd = desc.block(block_idx)

    for i, od in enumerate(bd.ops):
        where = dict(block_idx=block_idx, op_index=i, op_type=od.type)

        fwd = od.type
        if op_registry.is_grad_op_type(fwd):
            fwd = op_registry.forward_type_of_grad(fwd)
        if fwd in mxu:
            for slot, names in od.inputs.items():
                for n in names:
                    if n == EMPTY:
                        continue
                    vd = _find_vd(desc, block_idx, n)
                    shape = tuple(vd.shape or ()) if vd else ()
                    dyn = [d for d, s in enumerate(shape)
                           if s is not None and s < 0]
                    if not dyn:
                        continue
                    inner = [d for d in dyn if d != 0]
                    if inner:
                        report.add(Diagnostic(
                            "L001", Severity.WARNING,
                            "dynamic inner dim(s) %s of input %r feed "
                            "an MXU op: every concrete size is a "
                            "fresh XLA trace (shape %s)"
                            % (inner, n, shape), var_name=n, **where))
                    else:
                        report.add(Diagnostic(
                            "L001", Severity.INFO,
                            "dynamic batch dim of input %r feeds an "
                            "MXU op%s" % (n,
                                          "; shape bucketing covers it"
                                          if bucketed_feeds else
                                          " — without shape buckets "
                                          "every batch size retraces"),
                            var_name=n, **where))

        if _op_uses_rng(od) and random_seed == 0:
            attrs = od.attrs
            # initializer idiom (uniform/gaussian writing persistable
            # params in a startup program) is exempt: the executor's
            # per-program PRNG stream makes it reproducible, and init
            # broadcast handles replica agreement
            outs = [n for n in od.output_names() if n != EMPTY]

            def _persist(n):
                vd = _find_vd(desc, block_idx, n)
                return vd is not None and vd.persistable

            all_persist = bool(outs) and all(_persist(n) for n in outs)
            if not all_persist and not attrs.get("fix_seed") and \
                    not int(attrs.get("seed", 0) or 0):
                report.add(Diagnostic(
                    "L003", Severity.WARNING,
                    "op draws from the RNG stream with no seed "
                    "plumbing (seed attr 0, program.random_seed 0): "
                    "replicated builds will correlate", **where))

        floats = {}
        for slot, names in od.inputs.items():
            for n in names:
                if n == EMPTY:
                    continue
                vd = _find_vd(desc, block_idx, n)
                if vd is None or vd.dtype is None:
                    continue
                if vd.dtype in ("bfloat16", "float32"):
                    floats.setdefault(vd.dtype, n)
        if len(floats) > 1:
            report.add(Diagnostic(
                "L004", Severity.WARNING,
                "mixed bf16/f32 inputs (%s is bfloat16, %s is "
                "float32): the implicit upcast defeats the AMP "
                "bandwidth win — cast explicitly or keep the chain "
                "one dtype" % (floats["bfloat16"], floats["float32"]),
                var_name=floats["bfloat16"], **where))

    # segment splits (root-relevant in every block)
    runs = []
    for i, od in enumerate(bd.ops):
        j = _op_jittable(od)
        if runs and runs[-1][0] == j:
            runs[-1][1].append(i)
        else:
            runs.append((j, [i]))
    for k, (jit_ok, idxs) in enumerate(runs):
        if jit_ok or k == 0 or k == len(runs) - 1:
            continue
        types = sorted({bd.ops[i].type for i in idxs})
        report.add(Diagnostic(
            "L002", Severity.WARNING,
            "host op(s) %s split two jittable runs: the block lowers "
            "to %d executables instead of 1, with a device sync at "
            "each seam" % (", ".join(types), sum(1 for r in runs
                                                 if r[0])),
            block_idx=block_idx, op_index=idxs[0],
            op_type=bd.ops[idxs[0]].type))

    # persistable bf16 masters
    for name, vd in bd.vars.items():
        if vd.persistable and vd.dtype == "bfloat16":
            report.add(Diagnostic(
                "L004", Severity.WARNING,
                "persistable var is declared bfloat16: the AMP policy "
                "keeps master weights/statistics f32 (bf16 has 8 "
                "mantissa bits — accumulation error compounds)",
                block_idx=block_idx, var_name=name))

    # grad orphans
    produced, consumed = set(), set()
    for od in bd.ops:
        produced.update(n for n in od.output_names() if n != EMPTY)
        consumed.update(n for n in od.input_names() if n != EMPTY)
    for name, vd in bd.vars.items():
        base = name.split("@RENAME@")[0]
        if not base.endswith(GRAD_SUFFIX):
            continue
        if name not in produced and name not in consumed:
            report.add(Diagnostic(
                "L005", Severity.WARNING,
                "grad var is declared but never produced or consumed "
                "(debris from a partial backward?)",
                block_idx=block_idx, var_name=name))
            continue
        src = base[: -len(GRAD_SUFFIX)]
        svd = _find_vd(desc, block_idx, src)
        if svd is not None and svd.is_parameter and \
                name in produced and name not in consumed:
            report.add(Diagnostic(
                "L005", Severity.WARNING,
                "parameter grad %r is computed but no op consumes it "
                "— the update is never applied" % name,
                block_idx=block_idx, var_name=name))


def lint_program(desc, bucket_hints=None, suppress=(), report=None):
    """TPU lints over a Program or ProgramDesc; returns a `Report`.

    `bucket_hints`: the serving export's bucket dict (or anything
    truthy meaning "feeds are shape-bucketed") — demotes the
    dynamic-batch-dim finding to a covered advisory.
    """
    program = desc if hasattr(desc, "desc") else None
    desc = getattr(desc, "desc", desc)
    report = report if report is not None else Report(suppress=suppress)
    mxu = _mxu_types()
    # a bare ProgramDesc (loaded JSON) does not carry random_seed;
    # None means "unknowable" and L003 stays quiet — firing on a
    # possibly-seeded program would make proglint --strict lie
    random_seed = (program.random_seed if program is not None else None)
    bucketed = bool(bucket_hints)
    for block_idx in range(len(desc.blocks)):
        _lint_block(desc, block_idx, report, mxu, random_seed, bucketed)
    return report
