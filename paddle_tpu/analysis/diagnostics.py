"""Structured diagnostics for the program analysis subsystem.

The verifier/dataflow/lint passes (see the sibling modules) report
`Diagnostic` records instead of raising ad-hoc exceptions: each record
carries a STABLE code (documented in docs/ANALYSIS.md), a severity, and
the op/block/var identity needed to act on it.  A `Report` aggregates
them, applies suppressions, publishes per-code counters into the obs
registry, and can be turned into a `ProgramVerificationError` when a
caller wants errors to be fatal (the executor's FLAGS_verify_program
gate does).

Code families:
  V0xx  structural verification (verifier.py)
  D0xx / H0xx  dataflow: dead code and write/alias hazards (dataflow.py)
  L0xx  TPU-specific lints (lints.py)
  A0xx  alias & donation safety (alias.py)

Suppressions are strings, matched most-specific-first:
  "H002"              suppress the code everywhere
  "H002@scale"        suppress the code on ops of one type
  "H002@var:fc_0.w_0" suppress the code for one variable name
"""

__all__ = ["Severity", "Diagnostic", "Report",
           "ProgramVerificationError"]


class Severity:
    """Ordered severities.  `error` findings make verification fail;
    `warning` is a real finding that does not block execution; `info`
    is advisory (e.g. a dynamic batch dim that shape bucketing is
    expected to absorb)."""

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def rank(cls, sev):
        return cls._ORDER.get(sev, 99)


class Diagnostic:
    """One finding.  Identity fields are optional — a program-wide
    finding has no op_index — but every pass fills what it knows so a
    consumer can locate the op without re-running the analysis."""

    __slots__ = ("code", "severity", "message", "block_idx", "op_index",
                 "op_type", "var_name")

    def __init__(self, code, severity, message, block_idx=None,
                 op_index=None, op_type=None, var_name=None):
        self.code = code
        self.severity = severity
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var_name = var_name

    def location(self):
        bits = []
        if self.block_idx is not None:
            bits.append("block %d" % self.block_idx)
        if self.op_index is not None:
            bits.append("op %d" % self.op_index)
        if self.op_type:
            bits.append("(%s)" % self.op_type)
        if self.var_name:
            bits.append("var %r" % self.var_name)
        return " ".join(bits)

    def format(self):
        loc = self.location()
        return "[%s:%s] %s%s" % (self.code, self.severity,
                                 (loc + ": ") if loc else "",
                                 self.message)

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__
                if getattr(self, k) is not None}

    def __repr__(self):
        return "Diagnostic(%s)" % self.format()

    def _suppress_keys(self):
        keys = [self.code]
        if self.op_type:
            keys.append("%s@%s" % (self.code, self.op_type))
        if self.var_name:
            keys.append("%s@var:%s" % (self.code, self.var_name))
        return keys


class Report:
    """An ordered collection of diagnostics with suppression filtering
    and severity accounting."""

    def __init__(self, diagnostics=(), suppress=()):
        self.suppressed = []
        self.diagnostics = []
        self._suppress = set(suppress or ())
        for d in diagnostics:
            self.add(d)

    def add(self, diag):
        if any(k in self._suppress for k in diag._suppress_keys()):
            self.suppressed.append(diag)
        else:
            self.diagnostics.append(diag)
        return self

    def extend(self, diags):
        for d in diags:
            self.add(d)
        return self

    def by_severity(self, severity):
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self):
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self):
        return self.by_severity(Severity.WARNING)

    def codes(self):
        return sorted({d.code for d in self.diagnostics})

    def has(self, code):
        return any(d.code == code for d in self.diagnostics)

    def ok(self):
        """True when no error-severity finding survived suppression."""
        return not self.errors

    def sorted(self):
        return sorted(
            self.diagnostics,
            key=lambda d: (Severity.rank(d.severity),
                           d.block_idx if d.block_idx is not None else -1,
                           d.op_index if d.op_index is not None else -1,
                           d.code))

    def format(self, max_lines=None):
        lines = [d.format() for d in self.sorted()]
        if max_lines is not None and len(lines) > max_lines:
            rest = len(lines) - max_lines
            lines = lines[:max_lines] + ["... (%d more)" % rest]
        return "\n".join(lines)

    def to_dict(self):
        return {"diagnostics": [d.to_dict() for d in self.sorted()],
                "suppressed": len(self.suppressed),
                "errors": len(self.errors),
                "warnings": len(self.warnings)}

    def publish(self, origin="analysis"):
        """Count surviving findings into the obs registry
        (`analysis_diagnostics_total{code,severity}` plus an
        `analysis_runs_total{origin}` run counter) so serving warmup /
        executor verification leave a scrapeable trail."""
        from ..obs import registry as registry_mod

        reg = registry_mod.get_registry()
        reg.counter("analysis_runs_total",
                    "program analysis passes executed",
                    labelnames=("origin",)).labels(origin=origin).inc()
        fam = reg.counter("analysis_diagnostics_total",
                          "static-analysis findings by diagnostic code",
                          labelnames=("code", "severity"))
        for d in self.diagnostics:
            fam.labels(code=d.code, severity=d.severity).inc()
        return self

    def raise_on_error(self):
        """Raise ProgramVerificationError when errors survived."""
        if not self.ok():
            raise ProgramVerificationError(self)
        return self


class ProgramVerificationError(RuntimeError):
    """A program failed verification.  The message names the first
    error's code, op index and variable (what you grep the logs for);
    `.report` carries the full structured findings."""

    def __init__(self, report):
        self.report = report
        errs = report.errors
        head = errs[0].format() if errs else "verification failed"
        more = "" if len(errs) <= 1 else " (+%d more)" % (len(errs) - 1)
        super().__init__("program verification failed: %s%s" % (head,
                                                                more))
