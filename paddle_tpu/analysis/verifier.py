"""Structural verification of a ProgramDesc.

The reference validates programs piecemeal at runtime (per-op
InferShape / attr checkers); here a malformed ProgramDesc surfaces as
an opaque XLA trace error deep inside execution.  This pass checks the
whole IR up front and reports structured `Diagnostic`s:

  V001 unknown-op        op type not in the registry (nor `<fwd>_grad`
                         of a registered forward)
  V002 undeclared-var    an input/output slot names a var with no
                         VarDesc anywhere on the block's scope chain
  V003 use-before-def    an input is produced only LATER in its block
                         (and is neither persistable nor a feed)
  V004 dangling-block-ref  a BlockRef attr indexes a missing block, or
                         one whose parent chain does not pass through
                         the referencing op's block
  V005 dtype-mismatch    recorded output dtype differs from the dtype
                         re-derived through the registry's infer-shape
  V006 shape-mismatch    recorded static output shape differs from the
                         re-derived one (dynamic -1 dims are wildcards)
  V007 infer-shape-failure  the registry's infer-shape itself rejects
                         the recorded input metas (shape/dtype algebra
                         broken, e.g. a matmul inner-dim mismatch)
  V008 bad-attr          an attr value does not serialize (not a
                         scalar/str/list/BlockRef tree)

`level="structural"` runs V001-V004/V008 only (pure desc walking, no
JAX tracing) — cheap enough for every program load.  `level="full"`
adds the V005-V007 re-derivation via `jax.eval_shape` over each op's
kernel, the check that catches silently-corrupted metas before they
become a compile-time mystery.
"""

from ..core.desc import BlockRef
from ..core.types import GRAD_SUFFIX, VarType, canonical_dtype, exec_dtype
from ..ops import registry as op_registry
from .common import EMPTY, find_var_desc as _find_var_desc, \
    resolve_op_info
from .diagnostics import Diagnostic, Report, Severity

__all__ = ["verify_program"]

_JSONABLE_SCALARS = (bool, int, float, str, bytes, type(None))


def _known_op(op_type):
    return resolve_op_info(op_type) is not None


def _attr_ok(value):
    if isinstance(value, _JSONABLE_SCALARS) or isinstance(value, BlockRef):
        return True
    if isinstance(value, (list, tuple)):
        return all(_attr_ok(v) for v in value)
    # numpy scalars sneak into attrs from shape math; they serialize
    try:
        import numpy as np

        if isinstance(value, (np.integer, np.floating, np.bool_)):
            return True
    except Exception:
        pass
    return False


def _block_refs(op_desc):
    refs = []
    for key, v in op_desc.attrs.items():
        if isinstance(v, BlockRef):
            refs.append((key, v.idx))
        elif isinstance(v, (list, tuple)):
            refs.extend((key, x.idx) for x in v if isinstance(x, BlockRef))
    return refs


def _chain_reaches(desc, sub_idx, owner_idx):
    """Does sub-block `sub_idx`'s parent chain pass through
    `owner_idx`?  (The op holding the BlockRef lives in owner.)"""
    idx = sub_idx
    seen = set()
    while 0 <= idx < len(desc.blocks) and idx not in seen:
        if idx == owner_idx:
            return True
        seen.add(idx)
        idx = desc.block(idx).parent_idx
    return owner_idx == 0 and idx == -1  # root owns every chain end


# ---------------------------------------------------------------------------
# structural pass
# ---------------------------------------------------------------------------

def _produced_somewhere(desc):
    """Names produced by any op in any block: a producer-less
    non-persistable var is a feed candidate (the executor accepts it
    from the feed dict).  Computed ONCE per program (verify_program
    passes it down)."""
    produced = set()
    for b in desc.blocks:
        for od in b.ops:
            produced.update(n for n in od.output_names() if n != EMPTY)
    return produced


def _verify_block_structure(desc, block_idx, report,
                            produced_somewhere=None):
    bd = desc.block(block_idx)

    # first def index per name IN THIS BLOCK (ordering applies within a
    # block only; names from ancestor blocks are closures)
    first_def = {}
    for i, od in enumerate(bd.ops):
        for n in od.output_names():
            if n != EMPTY and n not in first_def:
                first_def[n] = i
    if produced_somewhere is None:
        produced_somewhere = _produced_somewhere(desc)

    for i, od in enumerate(bd.ops):
        where = dict(block_idx=block_idx, op_index=i, op_type=od.type)

        if not _known_op(od.type):
            report.add(Diagnostic(
                "V001", Severity.ERROR,
                "op type %r is not registered" % od.type, **where))
            # slot/attr checks below don't need the registry; keep going

        for key, value in od.attrs.items():
            if not _attr_ok(value):
                report.add(Diagnostic(
                    "V008", Severity.ERROR,
                    "attr %r holds a non-serializable value of type %s"
                    % (key, type(value).__name__), **where))
        for key, idx in _block_refs(od):
            if not (0 <= idx < len(desc.blocks)):
                report.add(Diagnostic(
                    "V004", Severity.ERROR,
                    "attr %r references block %d but the program has "
                    "%d block(s)" % (key, idx, len(desc.blocks)),
                    **where))
            elif idx != block_idx and not _chain_reaches(desc, idx,
                                                         block_idx):
                report.add(Diagnostic(
                    "V004", Severity.ERROR,
                    "attr %r references block %d whose parent chain "
                    "does not pass through block %d"
                    % (key, idx, block_idx), **where))

        for slot, names in od.inputs.items():
            for n in names:
                if n == EMPTY:
                    continue
                vd = _find_var_desc(desc, block_idx, n)
                if vd is None:
                    report.add(Diagnostic(
                        "V002", Severity.ERROR,
                        "input slot %r reads %r, which has no VarDesc "
                        "on the block's scope chain" % (slot, n),
                        var_name=n, **where))
                    continue
                if vd.persistable or vd.type == VarType.TENSOR_ARRAY:
                    continue  # initialized by startup / first write
                d = first_def.get(n)
                # d == i is the by-name in-place idiom (the op reads
                # the PREVIOUS value — fed or scope-resident — and
                # writes the new one, e.g. increment in_place): only a
                # strictly later first definition is an error
                if d is not None and d > i and n in bd.vars:
                    report.add(Diagnostic(
                        "V003", Severity.ERROR,
                        "input slot %r reads %r before its first "
                        "definition (op %d)" % (slot, n, d),
                        var_name=n, **where))
                elif d is None and n in bd.vars \
                        and n in produced_somewhere:
                    # produced only in OTHER blocks yet declared here:
                    # nothing in this block (or a feed) supplies it
                    report.add(Diagnostic(
                        "V003", Severity.ERROR,
                        "input slot %r reads %r, which no op in block "
                        "%d produces (and it is not persistable)"
                        % (slot, n, block_idx), var_name=n, **where))

        for slot, names in od.outputs.items():
            for n in names:
                if n == EMPTY:
                    continue
                if _find_var_desc(desc, block_idx, n) is None:
                    report.add(Diagnostic(
                        "V002", Severity.ERROR,
                        "output slot %r writes %r, which has no "
                        "VarDesc on the block's scope chain"
                        % (slot, n), var_name=n, **where))


# ---------------------------------------------------------------------------
# meta re-derivation pass (level="full")
# ---------------------------------------------------------------------------

def _shapes_conflict(recorded, computed):
    """Static dims must agree; -1 on either side is a wildcard.  An
    empty/missing recorded shape means 'never inferred' — not a
    conflict."""
    if not recorded or computed is None:
        return False
    if len(recorded) != len(computed):
        return True
    return any(r != c for r, c in zip(recorded, computed)
               if r is not None and r >= 0 and c is not None and c >= 0)


def _verify_block_meta(desc, block_idx, report):
    bd = desc.block(block_idx)
    for i, od in enumerate(bd.ops):
        where = dict(block_idx=block_idx, op_index=i, op_type=od.type)
        if not _known_op(od.type):
            continue  # already a V001
        if op_registry.is_grad_op_type(od.type) \
                and not op_registry.has_op(od.type):
            _verify_grad_meta(desc, block_idx, od, where, report)
            continue
        info = op_registry.get_op_info(od.type)
        if info.infer_shape is not None or not info.jittable:
            # explicit infer rules mutate descs (can't re-derive
            # side-effect-free); host ops keep their declared meta
            continue

        ins_meta = {}
        broken = False
        for slot, names in od.inputs.items():
            metas = []
            for n in names:
                if n == EMPTY:
                    broken = True  # generic kernels can't take holes
                    break
                vd = _find_var_desc(desc, block_idx, n)
                if vd is None or vd.shape is None:
                    broken = True  # V002 already reported / no meta
                    break
                metas.append((vd.shape, vd.dtype, vd.lod_level, vd.type))
            if broken:
                break
            ins_meta[slot] = metas
        if broken:
            continue

        try:
            outs = op_registry.generic_infer_shape(od.type, ins_meta,
                                                   od.attrs)
        except Exception as err:
            report.add(Diagnostic(
                "V007", Severity.ERROR,
                "infer-shape rejected the recorded input metas: %s: %s"
                % (type(err).__name__, err), **where))
            continue

        for slot, names in od.outputs.items():
            metas = outs.get(slot)
            if metas is None:
                continue
            for n, meta in zip(names, metas):
                if n == EMPTY:
                    continue
                vd = _find_var_desc(desc, block_idx, n)
                if vd is None:
                    continue  # V002 already reported
                shape, dtype = meta[0], meta[1]
                if vd.dtype is not None and \
                        exec_dtype(vd.dtype) != exec_dtype(dtype):
                    report.add(Diagnostic(
                        "V005", Severity.ERROR,
                        "output slot %r: recorded dtype %s, but the "
                        "registry infer-shape derives %s"
                        % (slot, vd.dtype, canonical_dtype(dtype)),
                        var_name=n, **where))
                if _shapes_conflict(vd.shape, shape):
                    report.add(Diagnostic(
                        "V006", Severity.ERROR,
                        "output slot %r: recorded shape %s, but the "
                        "registry infer-shape derives %s"
                        % (slot, tuple(vd.shape), tuple(shape)),
                        var_name=n, **where))


def _verify_grad_meta(desc, block_idx, od, where, report):
    """Generic grad ops: `X@GRAD` mirrors `X` (the backward builder's
    contract, see framework._grad_op_infer_shape)."""
    for slot, names in od.outputs.items():
        for n in names:
            if n == EMPTY or not n.endswith(GRAD_SUFFIX):
                continue
            src = n[: -len(GRAD_SUFFIX)]
            svd = _find_var_desc(desc, block_idx, src)
            gvd = _find_var_desc(desc, block_idx, n)
            if svd is None or gvd is None:
                continue
            if gvd.dtype is not None and svd.dtype is not None and \
                    exec_dtype(gvd.dtype) != exec_dtype(svd.dtype):
                report.add(Diagnostic(
                    "V005", Severity.ERROR,
                    "grad output %r has dtype %s but its source %r "
                    "has %s" % (n, gvd.dtype, src, svd.dtype),
                    var_name=n, **where))
            if _shapes_conflict(gvd.shape, svd.shape):
                report.add(Diagnostic(
                    "V006", Severity.ERROR,
                    "grad output %r has shape %s but its source %r "
                    "has %s" % (n, tuple(gvd.shape), src,
                                tuple(svd.shape)),
                    var_name=n, **where))


def verify_program(desc, level="full", suppress=(), report=None):
    """Verify a ProgramDesc (or Program); returns a `Report`.

    level: "structural" — registry/slot/scope/attr checks only;
           "full" — also re-derive output dtype/shape per op through
           the registry and compare against the recorded VarDescs.
    """
    desc = getattr(desc, "desc", desc)  # accept Program
    if level not in ("structural", "full"):
        raise ValueError("level must be 'structural' or 'full', got %r"
                         % (level,))
    report = report if report is not None else Report(suppress=suppress)
    produced = _produced_somewhere(desc)
    for block_idx in range(len(desc.blocks)):
        _verify_block_structure(desc, block_idx, report,
                                produced_somewhere=produced)
    if level == "full":
        for block_idx in range(len(desc.blocks)):
            _verify_block_meta(desc, block_idx, report)
    return report
