"""Forward rematerialization (activation checkpointing) as an IR pass.

HBM is the scarce resource on TPU: a training program built by
``append_backward`` keeps every forward activation live until its grad
op consumes it, so peak memory grows with network depth x batch.  This
pass trades FLOPs for that memory the way ``jax.checkpoint`` does, but
at the Program level — the backward here is explicit IR (fluid/
backward.py), not JAX autodiff, so JAX's own remat cannot see it.

Given user-chosen checkpoint variables, the program is cut into
segments of recomputable forward ops.  In the backward region, the
first grad op that reads a segment's intermediate triggers insertion of
a cloned copy of that segment (outputs renamed ``@RCP<k>``), and every
later op that read the intermediate is remapped to the clone.  The
original intermediates then die at the end of the forward pass, and XLA
frees/reuses their buffers.

Two things make the clone actually rematerialize instead of folding
back into the original computation:

* its checkpoint inputs pass through a ``recompute_barrier`` op
  (``lax.optimization_barrier``) so XLA's CSE cannot unify the cloned
  ops with the originals (same trick ``jax.checkpoint`` uses);
* the barrier also consumes one incoming *gradient* value of the
  triggering grad op, giving the clone a true data dependency on the
  backward front so the scheduler cannot hoist it next to the original
  forward (which would keep both copies live and save nothing).

Ops that must not run twice are never cloned and their outputs become
implicit checkpoints: RNG consumers (dropout — a re-drawn mask would
decouple the forward and backward masks), host/non-jittable ops, and
control-flow ops carrying sub-blocks.

The reference snapshot has no recompute machinery (its memory lever is
the reuse transpiler, memory_optimization_transpiler.py); this is the
TPU-native extension of the same memory/compute trade, alongside
`fluid.memory_optimize`.
"""

from ..core.desc import OpDesc, VarDesc, BlockRef
from ..core.types import GRAD_SUFFIX
from ..ops import registry as op_registry
from .backward import EMPTY

__all__ = ["recompute_program", "RecomputeOptimizer", "auto_checkpoints"]

_RCP = "@RCP"


def _is_recomputable(op_desc):
    if not op_registry.has_op(op_desc.type):
        return False
    info = op_registry.get_op_info(op_desc.type)
    if not info.jittable or info.uses_rng:
        return False
    if any(isinstance(a, BlockRef) for a in op_desc.attrs.values()):
        return False
    return op_desc.type not in ("feed", "fetch")


def _fwd_outputs(op_desc):
    """Real forward outputs: skip empties and grad-named vars (the loss
    grad seed is a fill_constant in the forward slice)."""
    return [n for n in op_desc.output_names()
            if n and GRAD_SUFFIX not in n]


class _Rewriter:
    def __init__(self, block, checkpoints):
        self.block = block
        bd = block.desc
        self.ops = bd.ops
        self.first_grad = next(
            (i for i, od in enumerate(self.ops)
             if op_registry.is_grad_op_type(od.type)), None)

        ckpt = set(checkpoints)
        for name, vd in bd.vars.items():
            if vd.persistable or vd.is_parameter:
                ckpt.add(name)

        # split the forward slice into segments of recomputable ops,
        # cut after each op that produces a user checkpoint
        self.seg_ops = []          # seg id -> [OpDesc]
        self.seg_of = {}           # intermediate var -> seg id
        cur = []
        produced = set()
        for od in self.ops[:self.first_grad or 0]:
            outs = _fwd_outputs(od)
            produced.update(outs)
            if not outs:
                # e.g. the loss-grad seed fill_constant: not a forward
                # value; cloning it would add a second writer of a live
                # backward variable
                continue
            if not _is_recomputable(od):
                ckpt.update(outs)
                continue
            cur.append(od)
            if any(n in ckpt for n in outs):
                self._close_segment(cur, ckpt)
                cur = []
        self._close_segment(cur, ckpt)
        # anything the forward never produced (feeds, startup state)
        # is a checkpoint by construction of seg_of
        self.ckpt = ckpt
        self.materialized = {}     # seg id -> {orig name: renamed}
        self.n_cloned = 0

    def _close_segment(self, ops, ckpt):
        if not ops:
            return
        sid = len(self.seg_ops)
        self.seg_ops.append(list(ops))
        for od in ops:
            for n in _fwd_outputs(od):
                if n not in ckpt:
                    self.seg_of[n] = sid

    def run(self):
        if self.first_grad is None or not self.seg_of:
            return 0
        i = self.first_grad
        while i < len(self.ops):
            od = self.ops[i]
            needed = sorted({n for n in od.input_names()
                             if n in self.seg_of})
            if needed:
                clones = []
                renames = {}
                for n in needed:
                    renames.update(
                        self._materialize(self.seg_of[n], od, clones))
                self.ops[i:i] = clones
                i += len(clones)
                od.inputs = type(od.inputs)(
                    (slot, [renames.get(n, n) for n in names])
                    for slot, names in od.inputs.items())
            i += 1
        self.block.sync_with_desc()
        return self.n_cloned

    def _trigger_of(self, grad_op):
        """A value on the backward front: an incoming grad of the op
        that first needs the segment (OG@ slots for grad ops; any
        grad-named input for grad-accumulation sums etc.).  Never the
        EMPTY placeholder, and never a forward value — a forward
        intermediate as trigger would pin the original live across the
        backward, defeating the pass."""
        for slot, names in grad_op.inputs.items():
            if slot.startswith("OG@"):
                for n in names:
                    if n and n != EMPTY:
                        return n
        for names in grad_op.inputs.values():
            for n in names:
                if n and n != EMPTY and GRAD_SUFFIX in n:
                    return n
        return None

    def _materialize(self, sid, trigger_op, out_clones):
        """Append clone ops for segment `sid` (and, recursively, any
        earlier segment it reads) to `out_clones`; return the rename
        map."""
        if sid in self.materialized:
            return self.materialized[sid]
        renames = {}
        self.materialized[sid] = renames
        suffix = "%s%d" % (_RCP, sid)

        # barrier the checkpoint inputs of the whole segment once:
        # external reads that are neither another segment's intermediate
        # (those rematerialize recursively below) nor produced in this
        # segment
        own_outs = {n for od in self.seg_ops[sid]
                    for n in _fwd_outputs(od)}
        barrier_ins = sorted({
            n for od in self.seg_ops[sid] for n in od.input_names()
            if n and n not in self.seg_of and n not in own_outs})
        for n in barrier_ins:
            renames[n] = n + suffix + "@IN"
            self._clone_var(n, renames[n])
        barrier = OpDesc(
            "recompute_barrier",
            {"X": list(barrier_ins),
             "Trigger": [t for t in [self._trigger_of(trigger_op)] if t]},
            {"Out": [renames[n] for n in barrier_ins]}, {})
        out_clones.append(barrier)

        for od in self.seg_ops[sid]:
            if not any(n in self.seg_of for n in _fwd_outputs(od)):
                # every output is a checkpoint (the segment's tail op):
                # the original stays live, a clone would be dead code
                continue
            ins = type(od.inputs)()
            for slot, names in od.inputs.items():
                mapped = []
                for n in names:
                    if n in renames:
                        mapped.append(renames[n])
                    elif n in self.seg_of and self.seg_of[n] != sid:
                        # reads an earlier segment's intermediate:
                        # rematerialize that one first
                        sub = self._materialize(self.seg_of[n],
                                                trigger_op, out_clones)
                        mapped.append(sub.get(n, n))
                    else:
                        mapped.append(n)
                ins[slot] = mapped
            outs = type(od.outputs)()
            for slot, names in od.outputs.items():
                row = []
                for n in names:
                    if n and GRAD_SUFFIX not in n:
                        renames[n] = n + suffix
                        self._clone_var(n, renames[n])
                        row.append(renames[n])
                    else:
                        row.append(n)
                outs[slot] = row
            out_clones.append(OpDesc(od.type, ins, outs, dict(od.attrs)))
            self.n_cloned += 1
        # only intermediate renames leak out; checkpoints keep their
        # original (live) values for every consumer outside the clone
        self.materialized[sid] = {
            n: rn for n, rn in renames.items() if n in self.seg_of}
        return self.materialized[sid]

    def _clone_var(self, src_name, new_name):
        bd = self.block.desc
        if new_name in bd.vars:
            return
        src = bd.vars.get(src_name)
        vd = VarDesc(new_name)
        if src is not None:
            vd.type = src.type
            vd.dtype = src.dtype
            vd.shape = src.shape
            vd.lod_level = src.lod_level
        vd.stop_gradient = True
        bd.vars[new_name] = vd


def recompute_program(program, checkpoints, block=None):
    """Rewrite a built training program (forward + backward [+ update
    ops]) so forward segments between ``checkpoints`` are recomputed in
    the backward region instead of kept live across it.  Returns the
    number of cloned forward ops (0 = nothing to do).  Global block
    only; sub-block (while/recurrent) bodies are left intact."""
    names = [c if isinstance(c, str) else c.name for c in checkpoints]
    block = block if block is not None else program.global_block()
    return _Rewriter(block, names).run()


def auto_checkpoints(program, every=8, block=None):
    """Heuristic checkpoint picker for models that don't expose natural
    cut points: every ``every``-th recomputable single-output forward op
    output becomes a checkpoint.  Good enough for chain-style CNNs
    (ResNet/VGG benches); hand-picked block outputs remain the better
    choice when the model builder can provide them."""
    if every < 1:
        raise ValueError("auto_checkpoints stride must be >= 1, got %r"
                         % (every,))
    block = block if block is not None else program.global_block()
    picks, seen = [], 0
    for op in block.desc.ops:
        if op_registry.is_grad_op_type(op.type):
            break
        outs = _fwd_outputs(op)
        if len(outs) != 1 or not _is_recomputable(op):
            continue
        seen += 1
        if seen % every == 0:
            picks.append(outs[0])
    return picks


class RecomputeOptimizer:
    """Optimizer wrapper: run the inner optimizer's ``minimize`` and
    then apply the recompute rewrite (reference has no counterpart; the
    API shape follows the wrapper convention later Paddle adopted for
    its RecomputeOptimizer so migration reads the same)."""

    def __init__(self, optimizer, checkpoints):
        self._inner = optimizer
        self._checkpoints = list(checkpoints)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, **kwargs):
        optimize_ops, params_grads = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set, **kwargs)
        recompute_program(loss.block.program, self._checkpoints)
        return optimize_ops, params_grads
