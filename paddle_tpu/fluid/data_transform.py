"""Kernel-boundary data-layout transforms (NCHW <-> NHWC).

The reference inserts device/layout/dtype transforms whenever a
tensor's layout differs from what the chosen kernel expects
(reference: framework/data_transform.cc:29, data_layout_transform.cc —
invoked from operator.cc:520 between InferShape and Compute).  This
framework has a single device and XLA owns physical layouts, so two of
the three transform kinds are subsumed; the remaining one — LOGICAL
layout, NCHW vs NHWC — is a program property, and this pass is its
equivalent: instead of a per-run dispatch check, ``convert_layout``
rewrites a built forward program once so every layout-capable op runs
in the requested layout, inserting explicit ``transpose`` ops exactly
where a layout boundary is crossed (the same points operator.cc would
have transformed at, but visible in the IR and differentiable).

Run it BEFORE ``append_backward``/``minimize``: gradients of the
rewritten forward then follow the new layout automatically, including
the inserted transposes.  Weights are untouched — conv kernels keep
OIHW filters in both layouts (ops/conv.py _layout4d), so parameters
and checkpoints are layout-portable.

On TPU this is an experimentation surface, not a default: XLA already
assigns C-minor physical layouts to NCHW convolutions (docs/PERF.md
round-3 profile), so the pass exists for capability parity with the
reference and for measuring that claim (bench.py BENCH_LAYOUT=NHWC).
"""

from ..core.desc import OpDesc, VarDesc
from ..ops import registry as op_registry

__all__ = ["convert_layout", "LAYOUT_CAPABLE", "LAYOUT_AGNOSTIC"]

NCHW_TO_NHWC = (0, 2, 3, 1)
NHWC_TO_NCHW = (0, 3, 1, 2)

# ops whose kernels read a data_layout attr (ops/conv.py, ops/norm.py)
LAYOUT_CAPABLE = ("conv2d", "conv2d_transpose", "pool2d", "batch_norm")

# elementwise ops that operate identically on any dim order, so a
# layout flows through them without a transform.  Binary entries are
# only transparent when both tensor operands carry the same layout
# (broadcast against a vector is handled by the axis rewrite below).
LAYOUT_AGNOSTIC = ("relu", "relu6", "sigmoid", "tanh", "sqrt", "abs",
                   "square", "exp", "dropout", "scale", "cast", "clip",
                   "elementwise_add", "elementwise_sub",
                   "elementwise_mul", "elementwise_div", "elementwise_max",
                   "elementwise_min", "sum")

# per-op input slots that carry the image tensor (other slots are
# layout-free side inputs: scales, biases, running stats, RNG state)
_DATA_SLOTS = {
    "conv2d": ("Input",), "conv2d_transpose": ("Input",),
    "pool2d": ("X",), "batch_norm": ("X",),
}


def _is_4d(block, name):
    try:
        shape = block.desc.var(name).shape
    except KeyError:
        return False
    return shape is not None and len(shape) == 4


def _permute_shape(shape, perm):
    return tuple(shape[p] for p in perm)


def convert_layout(program, to="NHWC", block=None, layout_out=None):
    """Rewrite a forward program's conv stack to run in ``to`` layout.

    Feeds and parameters keep their declared layouts; consumers that
    are neither layout-capable nor layout-agnostic see NCHW restored at
    their inputs, so the program's observable contract (feeds, fetches
    of boundary values, parameter shapes) is unchanged.  Returns the
    number of inserted transpose ops.  ``layout_out`` (a dict, when
    given) is filled with the final var -> "NHWC" map so callers (the
    `layout` rewrite pass) can tell which vars now live in the new
    layout — shape comparison cannot: a C==H==W tensor permutes to an
    identical shape.  Must run before the backward is appended —
    rewriting grad ops would require transforming grad chains too,
    which append_backward does for free afterwards.
    """
    if to != "NHWC":
        raise ValueError("convert_layout targets NHWC (programs are "
                         "built NCHW); got %r" % (to,))
    block = block if block is not None else program.global_block()
    for op in block.desc.ops:
        if op_registry.is_grad_op_type(op.type):
            raise ValueError(
                "convert_layout must run before append_backward "
                "(found grad op %r)" % (op.type,))

    new_ops = []
    inserted = [0]
    # var name -> "NHWC" for vars currently in NHWC
    layout = layout_out if layout_out is not None else {}
    alias = {}       # (var name, target layout) -> transposed alias name

    def transposed(name, to_layout):
        """Alias of ``name`` in ``to_layout``, inserting the transform
        op (cached: one transform per var per direction, the same
        de-dup operator.cc gets from its transform cache)."""
        key = (name, to_layout)
        if key in alias:
            return alias[key]
        perm = NCHW_TO_NHWC if to_layout == "NHWC" else NHWC_TO_NCHW
        new_name = "%s@%s" % (name, to_layout)
        src = block.desc.var(name)
        block.desc.vars[new_name] = VarDesc(
            new_name, src.type, src.dtype,
            _permute_shape(src.shape, perm), src.lod_level)
        new_ops.append(OpDesc("transpose", {"X": [name]},
                              {"Out": [new_name]}, {"axis": list(perm)}))
        inserted[0] += 1
        alias[key] = new_name
        if to_layout == "NHWC":
            layout[new_name] = "NHWC"
        return new_name

    def rewrite_slot(op, slot, names, to_layout):
        op.inputs[slot] = [
            transposed(n, to_layout)
            if _is_4d(block, n) and
            (layout.get(n, "NCHW") != to_layout) else n
            for n in names]

    for op in list(block.desc.ops):
        if op.type in LAYOUT_CAPABLE:
            for slot in _DATA_SLOTS[op.type]:
                rewrite_slot(op, slot, op.input(slot), "NHWC")
            op.attrs["data_layout"] = "NHWC"
            for out_name in op.output_names():
                if _is_4d(block, out_name):
                    v = block.desc.var(out_name)
                    v.shape = _permute_shape(v.shape, NCHW_TO_NHWC)
                    layout[out_name] = "NHWC"
        elif op.type in LAYOUT_AGNOSTIC:
            in_4d = [n for ns in op.inputs.values() for n in ns
                     if _is_4d(block, n)]
            if any(layout.get(n) == "NHWC" for n in in_4d):
                # converge mixed operands to NHWC rather than falling
                # back: one transform here beats two at the boundary
                for slot, names in list(op.inputs.items()):
                    rewrite_slot(op, slot, names, "NHWC")
                if op.attr("axis", None) == 1 and op.type.startswith(
                        "elementwise_"):
                    # channel-vector broadcast (conv bias): channel
                    # moved from dim 1 to dim 3
                    op.attrs["axis"] = 3
                for out_name in op.output_names():
                    if _is_4d(block, out_name):
                        v = block.desc.var(out_name)
                        v.shape = _permute_shape(v.shape, NCHW_TO_NHWC)
                        layout[out_name] = "NHWC"
        else:
            # layout boundary: this op's kernel assumes the built
            # (NCHW) dim order — restore it at each NHWC input
            for slot, names in list(op.inputs.items()):
                op.inputs[slot] = [
                    transposed(n, "NCHW")
                    if layout.get(n) == "NHWC" else n for n in names]
        new_ops.append(op)

    block.desc.ops = new_ops
    return inserted[0]
